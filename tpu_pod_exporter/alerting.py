"""Native alerting plane — in-root rule evaluation + exactly-once webhooks.

The reference exporter only *emits* metrics: ``deploy/prometheus-rules.yaml``
ships ~20 alerts that never fire unless an external Prometheus scrapes the
tree. The root already owns every piece a self-contained alerting plane
needs — a per-round merged snapshot (the evaluation input), a TSDB-lite
with recording rules (PR 11: history for ``rate()`` and durable ``ALERTS``
series), a push plane (PR 15: transition streaming), and the exactly-once
WAL delivery machinery the egress proved (PR 7: notification durability).
This module composes them:

- :func:`parse_alert_rules` — an alerting-rule grammar extending the
  PR-11 recording-rule file format::

      alert TpuRootLeafDown = tpu_root_leaf_up == 0
        for 2m
        keep_firing 1m
        labels(severity="warning")
        annotations(summary="Leaf {{ $labels.leaf }} down")
        suppress(tpu_root_leaf_partition_suspected == 1)

  The expression language is the PromQL subset the shipped rule file
  actually uses: selectors with ``= != =~ !~`` matchers, arithmetic,
  filtering comparisons, ``rate(m[5m])``, ``sum/avg/min/max/count`` with
  ``by``/``without``, ``and/or/unless`` with ``on (...)`` joins,
  ``time()`` and ``histogram_quantile``. Parse errors name the line and
  what would be accepted; metric names are validated against the schema
  at startup (the parse_chaos_spec contract — a typo'd rule file must
  fail at boot, never silently alert on nothing).
- :class:`AlertEvaluator` — attached to the root's merge round. Each
  round it evaluates every rule against the published snapshot (plus the
  store's recording-rule outputs, so alerts can reference precomputed
  rollups), runs per-instance ``pending → firing → keep-firing →
  resolved`` state machines with flap damping, suppresses
  partition-induced false positives via the root's stale-serve suspicion
  gauges, publishes ``ALERTS``-shaped series into the FleetStore
  (post-incident forensics, queryable over ``/api/v1?source=store``),
  feeds the ``route=alerts`` stream shape, and writes the
  ``alert-status.json`` sidecar the ``status --tree`` footer reads.
- :class:`AlertNotifier` — a webhook sender riding
  :class:`~tpu_pod_exporter.persist.WalBuffer` + the egress
  :class:`~tpu_pod_exporter.supervisor.CircuitBreaker`: every firing/
  resolved transition is framed with a durable sequence number, buffered
  on disk, and POSTed exactly-once (2xx acks the fsynced cursor; poison
  4xx are counted and skipped; outages backlog and drain contiguously
  across root restarts — the PR-7 ledger discipline).
- :func:`import_prometheus_rules` — translates
  ``deploy/prometheus-rules.yaml`` into the native grammar so the two
  surfaces cannot drift (``tests/test_rules_equivalence.py`` round-trips
  every shipped alert).

CLI (``python -m tpu_pod_exporter.alerting``): ``--check`` validates a
native rule file, ``--import`` translates the Prometheus rule file.
The end-to-end drill lives in the scenario engine (``make alert-demo``).
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import os
import re
import socket
import sys
import threading
import time
import urllib.error
from collections import deque
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Iterable, Mapping, Sequence

from tpu_pod_exporter.egress import build_breaker, default_send
from tpu_pod_exporter.metrics import registry, schema
from tpu_pod_exporter.persist import WalBuffer, atomic_write
from tpu_pod_exporter.supervisor import (
    DEGRADED_AFTER_REOPENS,
    STATE_VALUES,
    CLOSED,
    CircuitBreaker,
)
from tpu_pod_exporter.utils import RateLimitedLogger

if TYPE_CHECKING:  # typing only — no runtime import cost
    from tpu_pod_exporter.metrics.registry import Snapshot, SnapshotBuilder
    from tpu_pod_exporter.store import FleetStore, RecordingRule

log = logging.getLogger("tpu_pod_exporter.alerting")

# Series name the evaluator publishes alert state under (the Prometheus
# ALERTS convention: labels alertname/alertstate plus the instance labels).
ALERTS_METRIC = "ALERTS"

# Sidecar under --alert-dir: the `status --tree` alerts: footer and the
# notifier's drained-buffer seq recovery both read it. One writer — the
# root's round thread (evaluate_round) — the same single-writer discipline
# as the egress status sidecar.
STATUS_NAME = "alert-status.json"

# Exactly-once bookkeeping: the notification's durable sequence number
# rides a private header (the chaos webhook receiver ledgers it; real
# receivers may use it for idempotency or ignore it).
SEQ_HEADER = "X-Tpe-Alert-Seq"

PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

# External series names accepted by validation although no schema spec
# exists for them: `up` is Prometheus's own scrape-health series (imported
# rules reference it; at the root it simply evaluates empty), ALERTS is
# this module's own output (meta-alerts on alerts are legal).
_EXTERNAL_NAMES = frozenset(("up", ALERTS_METRIC))

# Label identity of one series: sorted (label, value) pairs, empty values
# dropped (the Prometheus missing-label convention).
LabelKey = tuple[tuple[str, str], ...]
Vector = dict[LabelKey, float]


_SPEC_GROUPS: tuple[tuple[Any, ...], ...] = (
    schema.ALL_SPECS, schema.AGGREGATE_SPECS, schema.LEAF_SPECS,
    schema.ROOT_SPECS, schema.HISTORY_SPECS, schema.PERSIST_SPECS,
    schema.PRESSURE_SPECS, schema.EGRESS_SPECS,
    schema.FLEET_QUERY_SPECS, schema.STORE_SPECS, schema.STREAM_SPECS,
    schema.REPLICA_SPECS, schema.ALERT_SPECS, schema.FAMILY_SPECS,
)

# Histograms live as HistogramSpec module attributes (their parent/lines
# child families carry the samples); alerts reference the EXPOSITION
# names — name_bucket / name_sum / name_count.
_HISTOGRAMS: tuple[Any, ...] = tuple(
    obj for obj in vars(schema).values()
    if isinstance(obj, registry.HistogramSpec)
)


def _schema_names() -> frozenset[str]:
    names = set()
    for group in _SPEC_GROUPS:
        for spec in group:
            names.add(spec.name)
    for hist in _HISTOGRAMS:
        base = hist.parent.name
        names.update((base, f"{base}_bucket", f"{base}_sum",
                      f"{base}_count"))
    return frozenset(names)


_SPEC_BY_NAME = {
    spec.name: spec
    for group in _SPEC_GROUPS
    for spec in group
    if not getattr(spec, "raw_lines", False)
}

# Exposition series name → (histogram spec, suffix kind).
_HIST_BY_EXPO_NAME = {
    f"{hist.parent.name}_{kind}": (hist, kind)
    for hist in _HISTOGRAMS
    for kind in ("bucket", "sum", "count")
}

# One pre-rendered raw-lines series prefix: `name_bucket{k="v",le="0.1"}`
# (or a bare `name_count` when the histogram is unlabeled).
_HIST_PREFIX_RE = re.compile(
    r"^(?P<series>[A-Za-z_][A-Za-z0-9_:]*)(?:\{(?P<labels>.*)\})?$")
_HIST_LABEL_RE = re.compile(
    r'(?P<key>[A-Za-z_][A-Za-z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"')


# ------------------------------------------------------------ expressions


_TOKEN_RE = re.compile(
    r"""\s*(?:
      (?P<dur>\d+(?:\.\d+)?(?:ms|[smhdwy]))
    | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<name>[A-Za-z_][A-Za-z0-9_:]*)
    | (?P<str>"(?:[^"\\]|\\.)*")
    | (?P<op>=~|!~|==|!=|<=|>=|[()\[\]{},<>+\-*/%=])
    )""",
    re.VERBOSE,
)

_DUR_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
              "d": 86400.0, "w": 604800.0, "y": 31536000.0}

_AGG_OPS = ("sum", "avg", "min", "max", "count")
_SET_OPS = ("and", "or", "unless")
_CMP_OPS = ("==", "!=", "<=", ">=", "<", ">")


def parse_duration(text: str) -> float:
    """``5m``/``30s``/``1h``/``90`` → seconds; raises ValueError."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(ms|[smhdwy])?", text.strip())
    if m is None:
        raise ValueError(
            f"bad duration {text!r} (want <number>[ms|s|m|h|d|w|y])")
    return float(m.group(1)) * _DUR_UNITS.get(m.group(2) or "s", 1.0)


def _fmt_num(v: float) -> str:
    return repr(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)


def _fmt_dur(seconds: float) -> str:
    return f"{seconds:g}s"


class EvalContext:
    """Evaluation input for one round: the instant vectors plus the rate
    window reader. ``vector(name)`` answers the CURRENT labeled samples of
    one series name; ``rate(name, window_s)`` answers per-second rates
    over the trailing window (counter-reset aware)."""

    def __init__(
        self,
        now: float,
        vector_fn: Callable[[str], Vector],
        rate_fn: Callable[[str, float], Vector],
    ) -> None:
        self.now = now
        self._vector_fn = vector_fn
        self._rate_fn = rate_fn

    def vector(self, name: str) -> Vector:
        return self._vector_fn(name)

    def rate(self, name: str, window_s: float) -> Vector:
        return self._rate_fn(name, window_s)


class Expr:
    """One parsed expression node. ``evaluate`` returns an instant vector
    or a float scalar; ``names`` collects referenced series names (into
    ``out``), ``rate_names`` the subset read through ``rate()``;
    ``render`` emits the canonical text the round-trip tests compare."""

    def evaluate(self, ctx: EvalContext) -> Vector | float:
        raise NotImplementedError

    def names(self, out: set[str]) -> None:  # noqa: B027 — leaves have none
        pass

    def rate_names(self, out: set[str]) -> None:  # noqa: B027
        pass

    def render(self) -> str:
        raise NotImplementedError


@dataclass(frozen=True)
class Num(Expr):
    value: float

    def evaluate(self, ctx: EvalContext) -> float:
        return self.value

    def render(self) -> str:
        return _fmt_num(self.value)


@dataclass(frozen=True)
class TimeFn(Expr):
    def evaluate(self, ctx: EvalContext) -> float:
        return ctx.now

    def render(self) -> str:
        return "time()"


def _match_one(op: str, pattern: str, rx: "re.Pattern[str] | None",
               value: str) -> bool:
    if op == "=":
        return value == pattern
    if op == "!=":
        return value != pattern
    assert rx is not None
    return bool(rx.fullmatch(value)) == (op == "=~")


@dataclass(frozen=True)
class Selector(Expr):
    metric: str
    matchers: tuple[tuple[str, str, str], ...] = ()  # (label, op, value)

    def _regexes(self) -> tuple["re.Pattern[str] | None", ...]:
        return tuple(
            re.compile(val) if op in ("=~", "!~") else None
            for _lbl, op, val in self.matchers
        )

    def _filter(self, vec: Vector) -> Vector:
        if not self.matchers:
            return dict(vec)
        rxs = self._regexes()
        out: Vector = {}
        for key, value in vec.items():
            labels = dict(key)
            ok = True
            for (lbl, op, val), rx in zip(self.matchers, rxs):
                if not _match_one(op, val, rx, labels.get(lbl, "")):
                    ok = False
                    break
            if ok:
                out[key] = value
        return out

    def evaluate(self, ctx: EvalContext) -> Vector:
        return self._filter(ctx.vector(self.metric))

    def names(self, out: set[str]) -> None:
        out.add(self.metric)

    def render(self) -> str:
        if not self.matchers:
            return self.metric
        inner = ",".join(f"{lbl}{op}{json.dumps(val)}"
                         for lbl, op, val in self.matchers)
        return f"{self.metric}{{{inner}}}"


@dataclass(frozen=True)
class Rate(Expr):
    selector: Selector
    window_s: float

    def evaluate(self, ctx: EvalContext) -> Vector:
        vec = ctx.rate(self.selector.metric, self.window_s)
        return self.selector._filter(vec)

    def names(self, out: set[str]) -> None:
        out.add(self.selector.metric)

    def rate_names(self, out: set[str]) -> None:
        out.add(self.selector.metric)

    def render(self) -> str:
        return f"rate({self.selector.render()}[{_fmt_dur(self.window_s)}])"


@dataclass(frozen=True)
class Agg(Expr):
    op: str                      # sum | avg | min | max | count
    mode: str                    # "" | "by" | "without"
    labels: tuple[str, ...]
    arg: Expr

    def evaluate(self, ctx: EvalContext) -> Vector:
        vec = self.arg.evaluate(ctx)
        if isinstance(vec, float):
            raise ValueError(f"{self.op}() needs a vector operand")
        groups: dict[LabelKey, list[float]] = {}
        for key, value in vec.items():
            if self.mode == "by":
                labels = dict(key)
                gkey = tuple(sorted(
                    (lbl, labels[lbl]) for lbl in self.labels
                    if lbl in labels))
            elif self.mode == "without":
                gkey = tuple((k, v) for k, v in key
                             if k not in self.labels)
            else:
                gkey = ()
            groups.setdefault(gkey, []).append(value)
        out: Vector = {}
        for gkey, values in groups.items():
            if self.op == "sum":
                out[gkey] = sum(values)
            elif self.op == "avg":
                out[gkey] = sum(values) / len(values)
            elif self.op == "min":
                out[gkey] = min(values)
            elif self.op == "max":
                out[gkey] = max(values)
            else:
                out[gkey] = float(len(values))
        return out

    def names(self, out: set[str]) -> None:
        self.arg.names(out)

    def rate_names(self, out: set[str]) -> None:
        self.arg.rate_names(out)

    def render(self) -> str:
        grouping = (f" {self.mode} ({', '.join(self.labels)})"
                    if self.mode else "")
        return f"{self.op}{grouping} ({self.arg.render()})"


@dataclass(frozen=True)
class HistogramQuantile(Expr):
    q: float
    arg: Expr

    def evaluate(self, ctx: EvalContext) -> Vector:
        vec = self.arg.evaluate(ctx)
        if isinstance(vec, float):
            raise ValueError("histogram_quantile needs a vector operand")
        groups: dict[LabelKey, list[tuple[float, float]]] = {}
        for key, value in vec.items():
            labels = dict(key)
            le = labels.pop("le", None)
            if le is None:
                continue
            try:
                bound = float("inf") if le in ("+Inf", "Inf") else float(le)
            except ValueError:
                continue
            gkey = tuple(sorted(labels.items()))
            groups.setdefault(gkey, []).append((bound, value))
        out: Vector = {}
        for gkey, buckets in groups.items():
            buckets.sort()
            total = buckets[-1][1] if buckets else 0.0
            if not buckets or not math.isinf(buckets[-1][0]) or total <= 0:
                continue
            rank = self.q * total
            lo_bound, lo_count = 0.0, 0.0
            result = buckets[-2][0] if len(buckets) > 1 else 0.0
            for bound, count in buckets:
                if count >= rank:
                    if math.isinf(bound):
                        result = buckets[-2][0] if len(buckets) > 1 else 0.0
                    elif count > lo_count:
                        result = lo_bound + (bound - lo_bound) * (
                            (rank - lo_count) / (count - lo_count))
                    else:
                        result = bound
                    break
                lo_bound, lo_count = bound, count
            out[gkey] = result
        return out

    def names(self, out: set[str]) -> None:
        self.arg.names(out)

    def rate_names(self, out: set[str]) -> None:
        self.arg.rate_names(out)

    def render(self) -> str:
        return f"histogram_quantile({_fmt_num(self.q)}, {self.arg.render()})"


def _on_key(key: LabelKey, on: tuple[str, ...]) -> LabelKey:
    labels = dict(key)
    return tuple((lbl, labels.get(lbl, "")) for lbl in on)


def _arith(op: str, a: float, b: float) -> float:
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b if b != 0 else float("nan")
    return math.fmod(a, b) if b != 0 else float("nan")


def _cmp(op: str, a: float, b: float) -> bool:
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<=":
        return a <= b
    if op == ">=":
        return a >= b
    if op == "<":
        return a < b
    return a > b


@dataclass(frozen=True)
class Binary(Expr):
    """Arithmetic, filtering comparison, or set op. ``on`` carries the
    join labels when the rule wrote ``on (...)`` — None means match on
    the full label identity (the PromQL default). ``on == ()`` is the
    explicit ``on ()`` match-everything join."""

    op: str
    lhs: Expr
    rhs: Expr
    on: tuple[str, ...] | None = None

    def evaluate(self, ctx: EvalContext) -> Vector | float:
        lv = self.lhs.evaluate(ctx)
        rv = self.rhs.evaluate(ctx)
        if self.op in _SET_OPS:
            return self._set_op(lv, rv)
        if isinstance(lv, float) and isinstance(rv, float):
            if self.op in _CMP_OPS:
                raise ValueError(
                    f"scalar {self.op} scalar needs a vector operand")
            return _arith(self.op, lv, rv)
        if isinstance(lv, float) or isinstance(rv, float):
            return self._scalar_op(lv, rv)
        return self._vector_op(lv, rv)

    def _scalar_op(self, lv: Vector | float,
                   rv: Vector | float) -> Vector:
        out: Vector = {}
        if isinstance(lv, float):
            assert isinstance(rv, dict)
            for key, value in rv.items():
                if self.op in _CMP_OPS:
                    # Scalar-LHS comparison keeps the VECTOR element —
                    # filter semantics mirror vector-op-scalar.
                    if _cmp(self.op, lv, value):
                        out[key] = value
                else:
                    out[key] = _arith(self.op, lv, value)
            return out
        assert isinstance(rv, float)
        for key, value in lv.items():
            if self.op in _CMP_OPS:
                if _cmp(self.op, value, rv):
                    out[key] = value
            else:
                out[key] = _arith(self.op, value, rv)
        return out

    def _vector_op(self, lv: Vector, rv: Vector) -> Vector:
        on = self.on
        if on is None:
            index: dict[LabelKey, float] = dict(rv)
            rkey = (lambda k: k)
        else:
            index = {}
            for key, value in rv.items():
                k = _on_key(key, on)
                if k in index:
                    raise ValueError(
                        f"many-to-one {self.op} match on "
                        f"({', '.join(on) or 'nothing'})")
                index[k] = value
            rkey = (lambda k: _on_key(k, on))
        out: Vector = {}
        for key, value in lv.items():
            other = index.get(rkey(key))
            if other is None:
                continue
            if self.op in _CMP_OPS:
                if _cmp(self.op, value, other):
                    out[key] = value
            else:
                out[key] = _arith(self.op, value, other)
        return out

    def _set_op(self, lv: Vector | float, rv: Vector | float) -> Vector:
        if isinstance(lv, float) or isinstance(rv, float):
            raise ValueError(f"{self.op} needs vector operands")
        on = self.on
        if self.op == "or":
            out = dict(lv)
            lkeys = ({_on_key(k, on) for k in lv} if on is not None
                     else set(lv))
            for key, value in rv.items():
                k = _on_key(key, on) if on is not None else key
                if k not in lkeys:
                    out[key] = value
            return out
        rkeys = ({_on_key(k, on) for k in rv} if on is not None
                 else set(rv))
        out = {}
        for key, value in lv.items():
            k = _on_key(key, on) if on is not None else key
            present = k in rkeys
            if present == (self.op == "and"):
                out[key] = value
        return out

    def names(self, out: set[str]) -> None:
        self.lhs.names(out)
        self.rhs.names(out)

    def rate_names(self, out: set[str]) -> None:
        self.lhs.rate_names(out)
        self.rhs.rate_names(out)

    def render(self) -> str:
        mod = ""
        if self.on is not None:
            mod = f" on ({', '.join(self.on)})"
        return (f"({self.lhs.render()} {self.op}{mod} "
                f"{self.rhs.render()})")


class _Parser:
    """Recursive-descent parser over the tokenized expression. Precedence
    (loosest first): or · and/unless · comparisons · + - · * / %."""

    def __init__(self, text: str) -> None:
        self.text = text
        self.tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if m is None or m.end() == pos:
                if text[pos:].strip():
                    raise ValueError(
                        f"unexpected character {text[pos:].strip()[0]!r} "
                        f"at offset {pos}")
                break
            pos = m.end()
            for kind in ("dur", "num", "name", "str", "op"):
                tok = m.group(kind)
                if tok is not None:
                    self.tokens.append((kind, tok))
                    break
        self.i = 0

    def peek(self) -> tuple[str, str] | None:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise ValueError("unexpected end of expression")
        self.i += 1
        return tok

    def expect(self, op: str) -> None:
        tok = self.next()
        if tok != ("op", op):
            raise ValueError(f"expected {op!r}, got {tok[1]!r}")

    def _maybe_on(self) -> tuple[str, ...] | None:
        tok = self.peek()
        if tok == ("name", "on"):
            self.next()
            return self._label_list()
        return None

    def _label_list(self) -> tuple[str, ...]:
        self.expect("(")
        labels: list[str] = []
        while True:
            tok = self.next()
            if tok == ("op", ")"):
                break
            if tok == ("op", ","):
                continue
            if tok[0] != "name":
                raise ValueError(
                    f"expected a label name, got {tok[1]!r}")
            labels.append(tok[1])
        return tuple(labels)

    def parse(self) -> Expr:
        expr = self._or()
        tok = self.peek()
        if tok is not None:
            raise ValueError(f"unexpected trailing token {tok[1]!r}")
        return expr

    def _or(self) -> Expr:
        lhs = self._and()
        while self.peek() == ("name", "or"):
            self.next()
            on = self._maybe_on()
            lhs = Binary("or", lhs, self._and(), on)
        return lhs

    def _and(self) -> Expr:
        lhs = self._cmp()
        while self.peek() in (("name", "and"), ("name", "unless")):
            op = self.next()[1]
            on = self._maybe_on()
            lhs = Binary(op, lhs, self._cmp(), on)
        return lhs

    def _cmp(self) -> Expr:
        lhs = self._add()
        tok = self.peek()
        while tok is not None and tok[0] == "op" and tok[1] in _CMP_OPS:
            op = self.next()[1]
            on = self._maybe_on()
            lhs = Binary(op, lhs, self._add(), on)
            tok = self.peek()
        return lhs

    def _add(self) -> Expr:
        lhs = self._mul()
        tok = self.peek()
        while tok is not None and tok[0] == "op" and tok[1] in ("+", "-"):
            op = self.next()[1]
            on = self._maybe_on()
            lhs = Binary(op, lhs, self._mul(), on)
            tok = self.peek()
        return lhs

    def _mul(self) -> Expr:
        lhs = self._atom()
        tok = self.peek()
        while (tok is not None and tok[0] == "op"
               and tok[1] in ("*", "/", "%")):
            op = self.next()[1]
            on = self._maybe_on()
            lhs = Binary(op, lhs, self._atom(), on)
            tok = self.peek()
        return lhs

    def _atom(self) -> Expr:
        tok = self.next()
        kind, text = tok
        if kind == "op" and text == "(":
            expr = self._or()
            self.expect(")")
            return expr
        if kind in ("num", "dur") and kind == "num":
            return Num(float(text))
        if kind == "name":
            if text == "time":
                self.expect("(")
                self.expect(")")
                return TimeFn()
            if text == "rate":
                return self._rate()
            if text == "histogram_quantile":
                return self._quantile()
            if text in _AGG_OPS:
                return self._agg(text)
            return self._selector(text)
        raise ValueError(f"unexpected token {text!r}")

    def _selector(self, metric: str) -> Selector:
        matchers: list[tuple[str, str, str]] = []
        if self.peek() == ("op", "{"):
            self.next()
            while True:
                tok = self.next()
                if tok == ("op", "}"):
                    break
                if tok == ("op", ","):
                    continue
                if tok[0] != "name":
                    raise ValueError(
                        f"expected a matcher label, got {tok[1]!r}")
                lbl = tok[1]
                op_tok = self.next()
                if op_tok[0] != "op" or op_tok[1] not in (
                        "=", "!=", "=~", "!~"):
                    raise ValueError(
                        f"bad matcher operator {op_tok[1]!r} "
                        f"(want = / != / =~ / !~)")
                op = op_tok[1]
                val_tok = self.next()
                if val_tok[0] != "str":
                    raise ValueError(
                        f'matcher value must be quoted, got {val_tok[1]!r}')
                val = json.loads(val_tok[1])
                if op in ("=~", "!~"):
                    try:
                        re.compile(val)
                    except re.error as e:
                        raise ValueError(
                            f"bad matcher regex {val!r}: {e}") from e
                matchers.append((lbl, op, val))
        return Selector(metric, tuple(matchers))

    def _rate(self) -> Rate:
        self.expect("(")
        tok = self.next()
        if tok[0] != "name":
            raise ValueError("rate() wants metric[window]")
        sel = self._selector(tok[1])
        self.expect("[")
        dur = self.next()
        if dur[0] not in ("dur", "num"):
            raise ValueError(f"bad rate window {dur[1]!r}")
        window = parse_duration(dur[1])
        self.expect("]")
        self.expect(")")
        return Rate(sel, window)

    def _quantile(self) -> HistogramQuantile:
        self.expect("(")
        q_tok = self.next()
        if q_tok[0] != "num":
            raise ValueError("histogram_quantile wants a numeric quantile")
        self.expect(",")
        arg = self._or()
        self.expect(")")
        return HistogramQuantile(float(q_tok[1]), arg)

    def _agg(self, op: str) -> Agg:
        mode = ""
        labels: tuple[str, ...] = ()
        tok = self.peek()
        if tok in (("name", "by"), ("name", "without")):
            mode = self.next()[1]
            labels = self._label_list()
        self.expect("(")
        arg = self._or()
        self.expect(")")
        if not mode:
            nxt = self.peek()
            if nxt in (("name", "by"), ("name", "without")):
                mode = self.next()[1]
                labels = self._label_list()
        return Agg(op, mode, labels, arg)


def parse_expr(text: str) -> Expr:
    """Parse one expression; ValueError names the offending token."""
    return _Parser(text).parse()


# ---------------------------------------------------------- rule grammar


@dataclass(frozen=True)
class AlertRule:
    """One parsed alert rule (see module docstring for the grammar)."""

    name: str
    expr: Expr
    expr_text: str
    for_s: float
    keep_firing_s: float
    labels: tuple[tuple[str, str], ...]
    annotations: tuple[tuple[str, str], ...]
    suppress: Expr | None
    suppress_text: str
    line_no: int


def _alert_err(line_no: int, line: str, msg: str) -> ValueError:
    return ValueError(f"alert rule line {line_no} ({line!r}): {msg}")


_ALERT_HEAD_RE = re.compile(
    r"^alert\s+(?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*=\s*(?P<expr>.+)$")
_CLAUSE_DUR_RE = re.compile(r"^(?P<kw>for|keep_firing)\s+(?P<dur>\S+)$")
_CLAUSE_PAREN_RE = re.compile(
    r"^(?P<kw>labels|annotations|suppress)\s*\((?P<body>.*)\)$")
_KV_RE = re.compile(
    r'(?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*=\s*'
    r'(?P<val>"(?:[^"\\]|\\.)*")')


def _parse_kv(line_no: int, raw: str, body: str) -> tuple[
        tuple[str, str], ...]:
    pairs: list[tuple[str, str]] = []
    rest = body
    while rest.strip():
        m = _KV_RE.match(rest.strip())
        if m is None:
            raise _alert_err(line_no, raw,
                             f'bad {rest.strip()[:40]!r}: want '
                             f'key="value"[, ...]')
        pairs.append((m.group("key"), json.loads(m.group("val"))))
        rest = rest.strip()[m.end():].lstrip()
        if rest.startswith(","):
            rest = rest[1:]
        elif rest.strip():
            raise _alert_err(line_no, raw,
                             f"expected ',' between pairs, got "
                             f"{rest.strip()[:20]!r}")
    return tuple(pairs)


def _validate_names(rule_name: str, line_no: int, raw: str, expr: Expr,
                    known: frozenset[str]) -> None:
    referenced: set[str] = set()
    expr.names(referenced)
    for name in sorted(referenced):
        if name in known or name in _EXTERNAL_NAMES or ":" in name:
            continue  # colon names are recording-rule outputs
        raise _alert_err(
            line_no, raw,
            f"alert {rule_name!r} references unknown metric {name!r}: "
            f"alerts evaluate over schema-registered families, "
            f"recording-rule outputs (names with ':'), or "
            f"{'/'.join(sorted(_EXTERNAL_NAMES))}")


def parse_alert_rules(
    text: str, known_names: frozenset[str] | None = None
) -> tuple[AlertRule, ...]:
    """Parse an alert-rule file body. Raises ValueError naming the
    offending line and what would be accepted — a typo'd rule file must
    fail at startup, never silently alert on nothing (the store's
    parse_rules contract). ``known_names`` overrides the schema-name set
    the validator accepts (drill harnesses inject synthetic families)."""
    known = known_names if known_names is not None else _schema_names()
    rules: list[AlertRule] = []
    seen: dict[str, int] = {}
    current: dict[str, Any] | None = None

    def finish() -> None:
        nonlocal current
        if current is None:
            return
        c = current
        current = None
        rules.append(AlertRule(
            name=c["name"], expr=c["expr"], expr_text=c["expr_text"],
            for_s=c.get("for_s", 0.0),
            keep_firing_s=c.get("keep_firing_s", 0.0),
            labels=c.get("labels", ()),
            annotations=c.get("annotations", ()),
            suppress=c.get("suppress"),
            suppress_text=c.get("suppress_text", ""),
            line_no=c["line_no"],
        ))

    for line_no, raw_line in enumerate(text.splitlines(), start=1):
        line = raw_line.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indented = line[0] in (" ", "\t")
        stripped = line.strip()
        if not indented:
            finish()
            m = _ALERT_HEAD_RE.match(stripped)
            if m is None:
                raise _alert_err(
                    line_no, stripped,
                    "want `alert NAME = <expr>` with indented clause "
                    "lines (for/keep_firing/labels/annotations/suppress)")
            name = m.group("name")
            if name in seen:
                raise _alert_err(
                    line_no, stripped,
                    f"duplicate alert name {name!r} "
                    f"(first defined on line {seen[name]})")
            seen[name] = line_no
            expr_text = m.group("expr").strip()
            try:
                expr = parse_expr(expr_text)
            except ValueError as e:
                raise _alert_err(line_no, stripped, str(e)) from e
            _validate_names(name, line_no, stripped, expr, known)
            current = {"name": name, "expr": expr,
                       "expr_text": expr_text, "line_no": line_no}
            continue
        if current is None:
            raise _alert_err(line_no, stripped,
                             "clause line outside any alert block")
        md = _CLAUSE_DUR_RE.match(stripped)
        if md is not None:
            try:
                seconds = parse_duration(md.group("dur"))
            except ValueError as e:
                raise _alert_err(line_no, stripped, str(e)) from e
            key = "for_s" if md.group("kw") == "for" else "keep_firing_s"
            current[key] = seconds
            continue
        mp = _CLAUSE_PAREN_RE.match(stripped)
        if mp is None:
            raise _alert_err(
                line_no, stripped,
                "want one of: for <dur> | keep_firing <dur> | "
                'labels(k="v", ...) | annotations(k="v", ...) | '
                "suppress(<expr>)")
        kw = mp.group("kw")
        body = mp.group("body")
        if kw == "suppress":
            try:
                sup = parse_expr(body)
            except ValueError as e:
                raise _alert_err(line_no, stripped, str(e)) from e
            _validate_names(current["name"], line_no, stripped, sup, known)
            current["suppress"] = sup
            current["suppress_text"] = body.strip()
        else:
            current[kw] = _parse_kv(line_no, stripped, body)
    finish()
    return tuple(rules)


def load_alert_rules_file(
    path: str, known_names: frozenset[str] | None = None
) -> tuple[AlertRule, ...]:
    """Read + parse an alert rule file; OSError/ValueError propagate (a
    missing or malformed rule file is a startup error, not a no-op)."""
    with open(path, encoding="utf-8") as f:
        return parse_alert_rules(f.read(), known_names=known_names)


def render_rules(rules: Sequence[AlertRule]) -> str:
    """Canonical native-grammar rendering — the round-trip the importer
    equivalence tests pin: parse(render(parse(x))) == parse(x)."""
    out: list[str] = []
    for r in rules:
        out.append(f"alert {r.name} = {r.expr.render()}")
        if r.for_s:
            out.append(f"  for {_fmt_dur(r.for_s)}")
        if r.keep_firing_s:
            out.append(f"  keep_firing {_fmt_dur(r.keep_firing_s)}")
        if r.labels:
            kv = ", ".join(f"{k}={json.dumps(v)}" for k, v in r.labels)
            out.append(f"  labels({kv})")
        if r.annotations:
            kv = ", ".join(f"{k}={json.dumps(v)}"
                           for k, v in r.annotations)
            out.append(f"  annotations({kv})")
        if r.suppress is not None:
            out.append(f"  suppress({r.suppress.render()})")
        out.append("")
    return "\n".join(out)


_TMPL_LABEL_RE = re.compile(
    r"\{\{\s*\$labels\.([A-Za-z_][A-Za-z0-9_]*)\s*\}\}")
_TMPL_VALUE_RE = re.compile(r"\{\{\s*\$value[^}]*\}\}")


def render_template(text: str, labels: Mapping[str, str],
                    value: float) -> str:
    """Annotation interpolation: ``{{ $labels.x }}`` and ``{{ $value }}``
    (format pipelines collapse to %g — notification bodies, not Go
    templates)."""
    out = _TMPL_LABEL_RE.sub(lambda m: labels.get(m.group(1), ""), text)
    return _TMPL_VALUE_RE.sub(f"{value:g}", out)


# ------------------------------------------------------------- notifier


class AlertNotifier:
    """Exactly-once webhook delivery for alert transitions.

    Two threads touch it, with the egress shipper's exact coupling: the
    root's ROUND thread calls :meth:`enqueue` (frames one notification
    with a durable seq and appends it to the
    :class:`~tpu_pod_exporter.persist.WalBuffer` — it is the buffer's one
    appender), and the SENDER thread drains oldest-first behind the
    breaker (2xx acks the fsynced cursor — never re-sent, even across a
    root restart; timeout/connection/5xx/429 are failures that open the
    breaker; other 4xx are poison, counted and acked-without-delivery so
    one rejected body cannot wedge every alert behind it). The sender is
    the buffer's ONE cursor-mover.

    Seq recovery mirrors the egress shipper: the newest pending record
    carries the highest issued seq; a drained buffer recovers it from the
    alert-status.json sidecar the evaluator writes each round."""

    def __init__(
        self,
        url: str,
        alert_dir: str,
        timeout_s: float = 5.0,
        max_backlog_mb: float = 16.0,
        breaker: CircuitBreaker | None = None,
        send: Callable[[str, bytes, Mapping[str, str], float], int] = default_send,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self.url = url
        self.alert_dir = alert_dir
        self.timeout_s = timeout_s
        self.max_backlog_bytes = int(max_backlog_mb * (1 << 20))
        self.breaker = (breaker if breaker is not None
                        else build_breaker(3, 0.5, 30.0))
        self._send = send
        self._clock = clock
        self._wallclock = wallclock
        self.buffer = WalBuffer(alert_dir)
        self._rlog = RateLimitedLogger(log)
        self._work = threading.Event()
        self._stop = threading.Event()
        self._sender: threading.Thread | None = None
        self._stats_lock = threading.Lock()
        self._seq = 0
        self._boot_seq = 0  # seqs <= this predate this process
        # (seq, wall, mono) of the head record — backlog age without
        # touching buffer files from foreign threads (egress discipline).
        self._head_meta: tuple[int, float, float] | None = None
        self._stats: dict[str, Any] = {
            "enqueued": 0,
            "sent": 0,
            "failed": 0,
            "dropped": {"backlog": 0, "poison": 0, "corrupt": 0,
                        "append": 0},
            "last_send_ok_wall": 0.0,
            "last_error": "",
        }
        self._open_errors: list[str] = []

    # ------------------------------------------------------------------ boot

    def load(self) -> dict:
        """Open + replay the notification buffer; resumes the durable
        seq. Never refuses to start: a hopeless dir records the error and
        the notifier runs degraded (every enqueue drops, counted)."""
        try:
            info = self.buffer.open()
        except OSError as e:
            self._open_errors.append(str(e))
            log.error("alert dir %s unusable (%s); notifications will "
                      "drop until it recovers", self.alert_dir, e)
            return {"pending": 0, "errors": [str(e)]}
        dropped = 0
        max_seq = 0
        tail = self.buffer.peek_last()
        if tail is not None:
            try:
                max_seq = int(json.loads(tail).get("seq", 0))
            except (ValueError, KeyError, TypeError):
                pass
        while self.buffer.pending():
            payload = self.buffer.peek()
            if payload is None:
                break
            try:
                head = json.loads(payload)
                with self._stats_lock:
                    self._head_meta = (int(head.get("seq", 0)),
                                       float(head.get("wall", 0.0)),
                                       float(head.get("mono", 0.0)))
                break
            except (ValueError, KeyError, TypeError):
                self.buffer.drop_oldest(1)
                dropped += 1
        # The sidecar covers the drained-buffer restart (no pending
        # record left to read the seq from) — same belt the egress wears.
        try:
            with open(os.path.join(self.alert_dir, STATUS_NAME),
                      encoding="utf-8") as f:
                doc = json.load(f)
            notif = doc.get("notifier") or {}
            max_seq = max(max_seq, int(notif.get("seq", 0)))
        except FileNotFoundError:
            pass
        except Exception:  # noqa: BLE001 — a torn sidecar restarts from the scan
            pass
        with self._stats_lock:
            self._seq = self._boot_seq = max_seq
            if dropped:
                self._stats["dropped"]["corrupt"] += dropped
        if info.get("pending"):
            log.info("alert notification backlog restored from %s: %d "
                     "record(s) pending (resuming at seq %d)",
                     self.alert_dir, info["pending"], max_seq)
        return info

    def start(self) -> None:
        if self._sender is not None:
            return
        self._sender = threading.Thread(
            target=self._sender_run, name="tpu-alert-sender", daemon=True
        )
        self._sender.start()

    # ------------------------------------------------------------ round side

    def enqueue(self, record: dict[str, Any]) -> int:
        """Frame one notification durably; called ONLY by the root's
        round thread (the buffer's single appender). Returns the assigned
        seq, or 0 when the append failed (counted)."""
        with self._stats_lock:
            self._seq += 1
            seq = self._seq
        doc = dict(record)
        doc["seq"] = seq
        doc["wall"] = self._wallclock()
        doc["mono"] = self._clock()
        payload = json.dumps(doc, separators=(",", ":")).encode()
        try:
            self.buffer.append(payload)
        except OSError as e:
            with self._stats_lock:
                self._stats["dropped"]["append"] += 1
            self._rlog.warning("alert_append",
                               "alert notification append failed: %s", e)
            return 0
        with self._stats_lock:
            self._stats["enqueued"] += 1
            if self._head_meta is None:
                self._head_meta = (seq, doc["wall"], doc["mono"])
        self._work.set()
        return seq

    # ----------------------------------------------------------- sender side

    def _peek_meta(self) -> None:
        payload = self.buffer.peek()
        meta: tuple[int, float, float] | None = None
        if payload is not None:
            try:
                head = json.loads(payload)
                meta = (int(head.get("seq", 0)),
                        float(head.get("wall", 0.0)),
                        float(head.get("mono", 0.0)))
            except (ValueError, KeyError, TypeError):
                meta = None
        with self._stats_lock:
            self._head_meta = meta

    def _enforce_caps(self) -> None:
        dropped = 0
        while (self.buffer.pending_bytes() > self.max_backlog_bytes
               and self.buffer.pending() > 1):
            if not self.buffer.drop_oldest(1):
                break
            dropped += 1
        if dropped:
            with self._stats_lock:
                self._stats["dropped"]["backlog"] += dropped
            self._peek_meta()
            self._rlog.warning(
                "alert_backlog",
                "alert notification backlog over %d bytes; dropped %d "
                "oldest record(s) (bounded loss by policy)",
                self.max_backlog_bytes, dropped)

    def _sender_run(self) -> None:
        while not self._stop.is_set():
            if self.buffer.pending() == 0:
                self._work.clear()
                self._work.wait(0.25)
                continue
            self._enforce_caps()
            if self.buffer.pending() == 0:
                continue
            if self.breaker.decide() == "skip":
                self._stop.wait(
                    min(max(self.breaker.seconds_until_probe, 0.05), 0.25)
                )
                continue
            try:
                progressed = self._send_one()
            except Exception as e:  # noqa: BLE001 — the sender must survive anything
                progressed = False
                self.breaker.record_failure()
                with self._stats_lock:
                    self._stats["failed"] += 1
                    self._stats["last_error"] = f"unexpected: {e}"
                self._rlog.warning("alert_send", "alert webhook send "
                                   "failed unexpectedly: %s", e)
            if not progressed and self.breaker.state == CLOSED:
                # Failure floor (the egress rule): a connection-refused
                # receiver fails in microseconds; with a disabled breaker
                # a zero-delay retry loop would spin a core.
                self._stop.wait(0.05)

    def _send_one(self) -> bool:
        """One webhook attempt against the head record. EVERY exit leaves
        the breaker with a recorded outcome — decide() already consumed
        this turn (possibly the single half-open probe), and an
        outcome-less return would park it in HALF_OPEN forever."""
        payload = self.buffer.peek()
        if payload is None:
            if self.breaker.state != CLOSED:
                self.breaker.record_failure()
            return False
        try:
            head = json.loads(payload)
            seq = int(head.get("seq", 0))
        except (ValueError, KeyError, TypeError):
            self.buffer.drop_oldest(1)
            with self._stats_lock:
                self._stats["dropped"]["corrupt"] += 1
            self._peek_meta()
            if self.breaker.state != CLOSED:
                self.breaker.record_failure()
            return True
        headers = {
            "Content-Type": "application/json",
            SEQ_HEADER: str(seq),
        }
        status: int | None = None
        error = ""
        try:
            status = self._send(self.url, payload, headers, self.timeout_s)
        except urllib.error.HTTPError as e:
            status = e.code
            error = f"HTTP {e.code}"
        except (urllib.error.URLError, TimeoutError, socket.timeout,
                ConnectionError, OSError) as e:
            error = f"{type(e).__name__}: {e}"
        if status is not None and 200 <= status < 300:
            self.breaker.record_success()
            self.buffer.ack()
            self._peek_meta()
            wall = self._wallclock()
            with self._stats_lock:
                self._stats["sent"] += 1
                self._stats["last_send_ok_wall"] = wall
                self._stats["last_error"] = ""
            return True
        if status is not None and 400 <= status < 500 and status != 429:
            # Poison: the receiver is UP and rejects this body. Retrying
            # forever would wedge every notification behind it. 429 is
            # deliberate backpressure → failure/retry below.
            self.breaker.record_success()
            self.buffer.ack()
            self._peek_meta()
            with self._stats_lock:
                self._stats["dropped"]["poison"] += 1
                self._stats["last_error"] = f"poison: HTTP {status}"
            self._rlog.warning(
                "alert_poison",
                "webhook rejected notification seq=%d with HTTP %d; "
                "skipping it (poison must not wedge the queue)",
                seq, status)
            return True
        self.breaker.record_failure()
        with self._stats_lock:
            self._stats["failed"] += 1
            self._stats["last_error"] = error or f"HTTP {status}"
        if self.breaker.state != CLOSED:
            self._rlog.warning(
                "alert_fail",
                "alert webhook send failed (%s); breaker %s, next probe "
                "in %.1fs, %d notification(s) buffered on disk",
                error or f"HTTP {status}", self.breaker.state,
                self.breaker.seconds_until_probe, self.buffer.pending())
        return False

    # ----------------------------------------------------------------- state

    @property
    def degraded(self) -> bool:
        """/readyz degraded predicate — the egress reopen threshold."""
        return (self.breaker.state != CLOSED
                and self.breaker.reopens >= DEGRADED_AFTER_REOPENS)

    def backlog_age_s(self) -> float:
        """Age of the oldest pending notification, from CACHED head
        metadata (round-thread safe: no buffer file reads). Records from
        this process age on their monotonic stamp (clock-step fenced);
        pre-restart records age on wall time (their mono stamp belongs
        to a dead clock) — the egress _batch_age rule."""
        if self.buffer.pending() == 0:
            return 0.0
        with self._stats_lock:
            meta = self._head_meta
            boot_seq = self._boot_seq
        if meta is None:
            return 0.0
        seq, wall, mono = meta
        if mono > 0 and seq > boot_seq:
            return max(self._clock() - mono, 0.0)
        return max(self._wallclock() - wall, 0.0)

    def stats(self) -> dict:
        with self._stats_lock:
            out: dict[str, Any] = dict(self._stats)
            out["dropped"] = dict(self._stats["dropped"])
            out["seq"] = self._seq
        out["url"] = self.url
        out["backlog_records"] = self.buffer.pending()
        out["backlog_bytes"] = self.buffer.pending_bytes()
        out["backlog_age_s"] = self.backlog_age_s()
        out["breaker_state"] = self.breaker.state
        out["breaker_state_value"] = STATE_VALUES[self.breaker.state]
        out["breaker_reopens"] = self.breaker.reopens
        out["degraded"] = self.degraded
        if self._open_errors:
            out["open_errors"] = list(self._open_errors)
        return out

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._work.set()
        if self._sender is not None:
            self._sender.join(timeout)
            self._sender = None
        self.buffer.close()


# ------------------------------------------------------------- evaluator


class _Instance:
    """One alert instance's state machine (keyed by rule + label set)."""

    __slots__ = ("labels", "state", "active_since", "state_since",
                 "last_true", "value")

    def __init__(self, labels: dict[str, str], now: float,
                 value: float) -> None:
        self.labels = labels
        self.state = PENDING
        self.active_since = now
        self.state_since = now
        self.last_true = now
        self.value = value


class AlertEvaluator:
    """Per-round alert evaluation at the root.

    Thread contract: :meth:`evaluate_round` is called by ONE thread (the
    root's round loop — the same single-appender seat the FleetStore
    holds); the read surfaces (:meth:`rows`, :meth:`stats`,
    :meth:`emit`, :meth:`ready_detail`) come from HTTP handler / stream
    pump threads and copy state out under the evaluator lock. All
    evaluation work and every I/O (store append, notifier enqueue,
    sidecar write) happens OUTSIDE the lock; only the commit of the new
    state is under it."""

    def __init__(
        self,
        rules: Sequence[AlertRule],
        alert_dir: str | None = None,
        notifier: AlertNotifier | None = None,
        store: "FleetStore | None" = None,
        recording_rules: "Sequence[RecordingRule]" = (),
        suppression: bool = True,
        history_slack_s: float = 60.0,
        max_transitions: int = 512,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self.rules = tuple(rules)
        self.alert_dir = alert_dir
        self.notifier = notifier
        self._store = store
        self._recording_rules = tuple(recording_rules)
        self.suppression_enabled = suppression
        self._wallclock = wallclock
        self._rlog = RateLimitedLogger(log)
        referenced: set[str] = set()
        rated: set[str] = set()
        for r in self.rules:
            r.expr.names(referenced)
            r.expr.rate_names(rated)
            if r.suppress is not None:
                r.suppress.names(referenced)
                r.suppress.rate_names(rated)
        self._referenced = frozenset(referenced)
        self._rated = frozenset(rated)
        # Rate history window: the widest rate() window any rule uses,
        # plus slack for round jitter. Bounded deques per series.
        max_window = 60.0
        for r in self.rules:
            max_window = max(max_window, _max_rate_window(r.expr))
            if r.suppress is not None:
                max_window = max(max_window, _max_rate_window(r.suppress))
        self._hist_window_s = max_window + history_slack_s
        self._hist: dict[str, dict[LabelKey, deque[tuple[float, float]]]] = {
            name: {} for name in self._rated
        }
        self._lock = threading.Lock()
        self._active: dict[tuple[str, LabelKey], _Instance] = {}
        self._transitions: deque[dict[str, Any]] = deque(
            maxlen=max_transitions)
        self._transitions_total: dict[tuple[str, str], int] = {}
        self._suppressed_total: dict[str, int] = {}
        self._eval_failures = 0
        self._last_round_failures = 0
        self._rounds = 0
        self._last_transition_wall = 0.0
        self._generation = 0

    # ------------------------------------------------------------ round side

    def evaluate_round(self, snapshot: "Snapshot",
                       now_wall: float | None = None) -> dict[str, Any]:
        """Evaluate every rule against one published snapshot; runs the
        state machines, appends ALERTS series to the store, enqueues
        notifications, and writes the status sidecar. Called once per
        root merge round, on the round thread."""
        now = self._wallclock() if now_wall is None else now_wall
        vectors = self._ingest(snapshot, now)
        ctx = EvalContext(now, lambda name: vectors.get(name, {}),
                          self._rate_vector_fn(now))
        round_failures = 0
        transitions: list[dict[str, Any]] = []
        notifications: list[dict[str, Any]] = []
        suppressed_counts: dict[str, int] = {}
        with self._lock:
            active = {k: v for k, v in self._active.items()}
        for rule in self.rules:
            try:
                result = rule.expr.evaluate(ctx)
                if isinstance(result, float):
                    raise ValueError("top-level expression is a scalar")
                sup_vec: Vector | None = None
                if (rule.suppress is not None
                        and self.suppression_enabled):
                    sup = rule.suppress.evaluate(ctx)
                    sup_vec = sup if isinstance(sup, dict) else None
            except Exception as e:  # noqa: BLE001 — one bad rule must not stop the round
                round_failures += 1
                self._rlog.warning(f"rule:{rule.name}",
                                   "alert rule %s failed: %s",
                                   rule.name, e)
                continue
            self._step_rule(rule, result, sup_vec, active, now,
                            transitions, notifications,
                            suppressed_counts)
        firing = sum(1 for inst in active.values()
                     if inst.state == FIRING)
        pending = sum(1 for inst in active.values()
                      if inst.state == PENDING)
        with self._lock:
            self._active = active
            self._rounds += 1
            self._generation += 1
            self._last_round_failures = round_failures
            self._eval_failures += round_failures
            for t in transitions:
                self._transitions.append(t)
                key = (str(t["alert"]), str(t["to"]))
                self._transitions_total[key] = (
                    self._transitions_total.get(key, 0) + 1)
                self._last_transition_wall = now
            for name, n in suppressed_counts.items():
                self._suppressed_total[name] = (
                    self._suppressed_total.get(name, 0) + n)
        # I/O strictly outside the lock (lock-io discipline).
        if self.notifier is not None:
            for notif in notifications:
                self.notifier.enqueue(notif)
        if self._store is not None:
            rows = [
                (ALERTS_METRIC,
                 {"alertname": name, "alertstate": inst.state,
                  **inst.labels},
                 1.0)
                for (name, _key), inst in active.items()
            ]
            if rows:
                try:
                    self._store.append_samples(rows, now_wall=now)
                except Exception as e:  # noqa: BLE001 — store trouble must not stop alerting
                    self._rlog.warning("store_append",
                                       "ALERTS store append failed: %s", e)
        self._write_status(now, firing, pending)
        return {"firing": firing, "pending": pending,
                "transitions": len(transitions),
                "eval_failures": round_failures}

    def _step_rule(
        self,
        rule: AlertRule,
        result: Vector,
        sup_vec: Vector | None,
        active: dict[tuple[str, LabelKey], _Instance],
        now: float,
        transitions: list[dict[str, Any]],
        notifications: list[dict[str, Any]],
        suppressed_counts: dict[str, int],
    ) -> None:
        sup_keys = (tuple(sup_vec.keys()) if sup_vec else ())
        true_now: set[LabelKey] = set()
        for key, value in result.items():
            if sup_keys and _suppressed(key, sup_keys):
                suppressed_counts[rule.name] = (
                    suppressed_counts.get(rule.name, 0) + 1)
                continue  # held down as a presumed false positive
            true_now.add(key)
            ikey = (rule.name, key)
            inst = active.get(ikey)
            if inst is None:
                inst = _Instance(dict(key), now, value)
                active[ikey] = inst
                transitions.append(self._transition(
                    rule, inst, PENDING, now))
                if rule.for_s <= 0:
                    inst.state = FIRING
                    inst.state_since = now
                    transitions.append(self._transition(
                        rule, inst, FIRING, now))
                    notifications.append(self._notification(
                        rule, inst, FIRING, now))
                continue
            inst.last_true = now
            inst.value = value
            if (inst.state == PENDING
                    and now - inst.active_since >= rule.for_s):
                inst.state = FIRING
                inst.state_since = now
                transitions.append(self._transition(
                    rule, inst, FIRING, now))
                notifications.append(self._notification(
                    rule, inst, FIRING, now))
        for ikey in [k for k in active if k[0] == rule.name]:
            if ikey[1] in true_now:
                continue
            inst = active[ikey]
            if inst.state == PENDING:
                # Pending that recovers (or is suppressed) simply drops —
                # the Prometheus pending→inactive convention: no
                # notification, no resolved transition.
                del active[ikey]
                continue
            if now - inst.last_true <= rule.keep_firing_s:
                continue  # keep-firing: flap damping absorbs the dip
            inst.state = RESOLVED
            transitions.append(self._transition(rule, inst, RESOLVED, now))
            notifications.append(self._notification(
                rule, inst, RESOLVED, now))
            del active[ikey]

    def _transition(self, rule: AlertRule, inst: _Instance, to: str,
                    now: float) -> dict[str, Any]:
        return {"alert": rule.name, "to": to, "wall": now,
                "labels": dict(inst.labels), "value": inst.value}

    def _notification(self, rule: AlertRule, inst: _Instance, state: str,
                      now: float) -> dict[str, Any]:
        labels = {"alertname": rule.name, **dict(rule.labels),
                  **inst.labels}
        annotations = {
            k: render_template(v, labels, inst.value)
            for k, v in rule.annotations
        }
        return {"alert": rule.name, "state": state, "labels": labels,
                "annotations": annotations, "value": inst.value,
                "active_since": inst.active_since}

    # ------------------------------------------------------------- data feed

    def _ingest(self, snapshot: "Snapshot",
                now: float) -> dict[str, Vector]:
        vectors: dict[str, Vector] = {}
        for name in self._referenced:
            if ":" in name:
                continue  # recording-rule outputs handled below
            hist = _HIST_BY_EXPO_NAME.get(name)
            if hist is not None:
                vec = self._hist_vector(snapshot, name, *hist)
                if vec:
                    vectors[name] = vec
                continue
            spec = _SPEC_BY_NAME.get(name)
            if spec is None:
                continue  # external names (`up`) evaluate empty here
            view = snapshot.samples_view(name)
            if not view:
                continue
            label_names = spec.label_names
            vec = {}
            for lvs, value in view.items():
                key = tuple(sorted(
                    (ln, lv) for ln, lv in zip(label_names, lvs) if lv))
                vec[key] = float(value)
            vectors[name] = vec
        self._ingest_recording(snapshot, vectors)
        self._trim_history(vectors, now)
        return vectors

    def _hist_vector(self, snapshot: "Snapshot", wanted: str,
                     hist: Any, kind: str) -> Vector:
        """Recover one histogram exposition series (_bucket/_sum/_count)
        from its raw-lines child family: each sample's label 'tuple' is a
        1-tuple holding the fully pre-rendered series prefix."""
        view = snapshot.samples_view(hist.lines.name)
        if not view:
            return {}
        vec: Vector = {}
        for lvs, value in view.items():
            if not lvs:
                continue
            m = _HIST_PREFIX_RE.match(lvs[0])
            if m is None or m.group("series") != wanted:
                continue
            labels = {
                lm.group("key"): json.loads(f'"{lm.group("val")}"')
                for lm in _HIST_LABEL_RE.finditer(m.group("labels") or "")
            }
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if v))
            vec[key] = float(value)
        return vec

    def _ingest_recording(self, snapshot: "Snapshot",
                          vectors: dict[str, Vector]) -> None:
        if self._recording_rules:
            from tpu_pod_exporter.store import evaluate_rule
            wanted = {name for name in self._referenced if ":" in name}
            for rrule in self._recording_rules:
                if rrule.name not in wanted:
                    continue
                try:
                    vec = {}
                    for labels, value in evaluate_rule(rrule, snapshot):
                        vec[tuple(sorted(labels.items()))] = value
                    vectors[rrule.name] = vec
                except Exception as e:  # noqa: BLE001 — rule series degrade to absent
                    self._rlog.warning(f"rrule:{rrule.name}",
                                       "recording rule %s failed during "
                                       "alert ingest: %s", rrule.name, e)

    def _trim_history(self, vectors: dict[str, Vector],
                      now: float) -> None:
        horizon = now - self._hist_window_s
        for name in self._rated:
            series = self._hist[name]
            vec = vectors.get(name, {})
            for key, value in vec.items():
                dq = series.get(key)
                if dq is None:
                    dq = deque()
                    series[key] = dq
                dq.append((now, value))
            for key in list(series):
                dq = series[key]
                while dq and dq[0][0] < horizon:
                    dq.popleft()
                if not dq:
                    del series[key]

    def _rate_vector_fn(
        self, now: float
    ) -> Callable[[str, float], Vector]:
        def rate(name: str, window_s: float) -> Vector:
            out: Vector = {}
            for key, dq in self._hist.get(name, {}).items():
                pts = [(t, v) for t, v in dq if t >= now - window_s]
                if len(pts) < 2:
                    continue
                increase = 0.0
                prev = pts[0][1]
                for _t, v in pts[1:]:
                    increase += (v - prev) if v >= prev else v
                    prev = v
                span = pts[-1][0] - pts[0][0]
                if span > 0:
                    out[key] = increase / span
            return out

        return rate

    def backfill(self, samples: Iterable[tuple[str, Mapping[str, str],
                                               float, float]]) -> int:
        """Seed the rate history from stored pre-restart samples:
        ``(metric, labels, wall, value)`` tuples, oldest first. Called
        once at boot, before the round loop starts — rates stay
        continuous across a root restart (the live+store contract)."""
        n = 0
        for name, labels, wall, value in samples:
            series = self._hist.get(name)
            if series is None:
                continue
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if v))
            dq = series.get(key)
            if dq is None:
                dq = deque()
                series[key] = dq
            dq.append((float(wall), float(value)))
            n += 1
        return n

    # ------------------------------------------------------------- sidecar

    def _write_status(self, now: float, firing: int,
                      pending: int) -> None:
        if not self.alert_dir:
            return
        with self._lock:
            doc: dict[str, Any] = {
                "wall": now,
                "rules": len(self.rules),
                "firing": firing,
                "pending": pending,
                "rounds": self._rounds,
                "eval_failures": self._eval_failures,
                "last_round_failures": self._last_round_failures,
                "suppressed_total": sum(self._suppressed_total.values()),
                "last_transition_wall": self._last_transition_wall,
                "suppression": self.suppression_enabled,
            }
        if self.notifier is not None:
            s = self.notifier.stats()
            doc["notifier"] = {
                "seq": s["seq"],
                "url": s["url"],
                "sent": s["sent"],
                "failed": s["failed"],
                "backlog_records": s["backlog_records"],
                "backlog_bytes": s["backlog_bytes"],
                "backlog_age_s": round(s["backlog_age_s"], 3),
                "breaker": s["breaker_state"],
                "last_error": s["last_error"],
            }
        try:
            atomic_write(os.path.join(self.alert_dir, STATUS_NAME),
                         json.dumps(doc).encode())
        except OSError:
            pass

    # --------------------------------------------------------- read surfaces

    def rows(self) -> list[dict[str, Any]]:
        """Active alert instances as stream/query rows — label identity
        is the stable row key (state rides the row body, so a transition
        is a changed row and a resolution a removed key: exactly the
        delta semantics the stream plane ships)."""
        with self._lock:
            snap = [(name, inst.labels, inst.state, inst.value,
                     inst.state_since, inst.active_since)
                    for (name, _key), inst in self._active.items()]
        out = [
            {"metric": ALERTS_METRIC,
             "labels": {"alertname": name, **labels},
             "state": state, "value": value,
             "state_since": state_since, "active_since": active_since}
            for name, labels, state, value, state_since, active_since
            in snap
        ]
        out.sort(key=lambda r: sorted(r["labels"].items()))
        return out

    def transitions(self, limit: int = 100) -> list[dict[str, Any]]:
        with self._lock:
            items = list(self._transitions)
        return items[-limit:]

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def counts(self) -> tuple[int, int]:
        with self._lock:
            firing = sum(1 for i in self._active.values()
                         if i.state == FIRING)
            pending = sum(1 for i in self._active.values()
                          if i.state == PENDING)
        return firing, pending

    def suppressed_names(self) -> tuple[str, ...]:
        """Rule names whose instances suppression held down at least once
        over this evaluator's lifetime. The scenario fuzzer's suppress-
        aware verdict reads this: on a GENERATED timeline an alert may
        legitimately fire OR be suppressed, but either way its name must
        sit inside the derived expected∪allowed envelope — a rule
        engaging (even silently) outside that envelope means the
        generator's alert model and the evaluator disagree."""
        with self._lock:
            return tuple(sorted(
                name for name, n in self._suppressed_total.items() if n))

    @property
    def degraded(self) -> bool:
        """Evaluator errors in the last round, or a notifier whose
        breaker keeps reopening — the /readyz `alerting:` predicate
        (still HTTP 200: a down webhook must not pull the root from
        scrape rotation)."""
        with self._lock:
            failing = self._last_round_failures > 0
        if failing:
            return True
        return self.notifier is not None and self.notifier.degraded

    def ready_detail(self) -> dict[str, Any]:
        firing, pending = self.counts()
        with self._lock:
            detail: dict[str, Any] = {
                "rules": len(self.rules),
                "firing": firing,
                "pending": pending,
                "eval_failures": self._eval_failures,
            }
        if self.notifier is not None:
            s = self.notifier.stats()
            detail["notifier_breaker"] = s["breaker_state"]
            detail["notifier_backlog"] = s["backlog_records"]
        detail["status"] = "degraded" if self.degraded else "ok"
        return detail

    def stats(self) -> dict[str, Any]:
        firing, pending = self.counts()
        with self._lock:
            out: dict[str, Any] = {
                "rules": len(self.rules),
                "rounds": self._rounds,
                "firing": firing,
                "pending": pending,
                "eval_failures": self._eval_failures,
                "suppressed_total": dict(self._suppressed_total),
                "transitions_total": {
                    f"{alert}/{to}": n
                    for (alert, to), n in self._transitions_total.items()
                },
                "last_transition_wall": self._last_transition_wall,
                "suppression": self.suppression_enabled,
            }
        if self.notifier is not None:
            out["notifier"] = self.notifier.stats()
        return out

    def emit(self, b: "SnapshotBuilder") -> None:
        """Publish the alerting self-metric surface into a
        SnapshotBuilder (the root's publish path; one-round lag for the
        round's own transitions, the fleet_store.emit convention)."""
        for spec in schema.ALERT_SPECS:
            b.declare(spec)
        firing, pending = self.counts()
        with self._lock:
            transitions = dict(self._transitions_total)
            suppressed = dict(self._suppressed_total)
            eval_failures = self._eval_failures
        b.add(schema.TPU_ROOT_ALERTS_FIRING, float(firing))
        b.add(schema.TPU_ROOT_ALERTS_PENDING, float(pending))
        b.add(schema.TPU_ROOT_ALERT_RULES, float(len(self.rules)))
        b.add(schema.TPU_ROOT_ALERT_EVAL_FAILURES_TOTAL,
              float(eval_failures))
        for (alert, to), n in transitions.items():
            b.add(schema.TPU_ROOT_ALERT_TRANSITIONS_TOTAL, float(n),
                  (alert, to))
        for alert, n in suppressed.items():
            b.add(schema.TPU_ROOT_ALERT_SUPPRESSED_TOTAL, float(n),
                  (alert,))
        if self.notifier is not None:
            s = self.notifier.stats()
            b.add(schema.TPU_ROOT_ALERT_NOTIFICATIONS_SENT_TOTAL,
                  float(s["sent"]))
            b.add(schema.TPU_ROOT_ALERT_NOTIFICATIONS_FAILED_TOTAL,
                  float(s["failed"]))
            b.add(schema.TPU_ROOT_ALERT_NOTIFIER_BACKLOG_BYTES,
                  float(s["backlog_bytes"]))
            b.add(schema.TPU_ROOT_ALERT_NOTIFIER_BACKLOG_AGE_SECONDS,
                  s["backlog_age_s"])
            b.add(schema.TPU_ROOT_ALERT_NOTIFIER_BREAKER_STATE,
                  s["breaker_state_value"])

    def close(self) -> None:
        if self.notifier is not None:
            self.notifier.close()


def _suppressed(key: LabelKey, sup_keys: tuple[LabelKey, ...]) -> bool:
    """One suppression entry covers an instance when every label the two
    SHARE agrees (an empty-labeled entry covers everything — the
    scalar-truth case); disjoint label dimensions never suppress."""
    labels = dict(key)
    for skey in sup_keys:
        if not skey:
            return True
        shared = [(k, v) for k, v in skey if k in labels]
        if shared and all(labels[k] == v for k, v in shared):
            return True
    return False


def _max_rate_window(expr: Expr) -> float:
    if isinstance(expr, Rate):
        return expr.window_s
    if isinstance(expr, Binary):
        return max(_max_rate_window(expr.lhs), _max_rate_window(expr.rhs))
    if isinstance(expr, (Agg, HistogramQuantile)):
        return _max_rate_window(expr.arg)
    return 0.0


# ------------------------------------------------------------- importer


# Imported rules whose Prometheus shape has a partition-suppression twin
# in the native plane: the root's stale-serve suspicion gauge marks a
# leaf that LOOKS down but is being stale-served while its HA twin
# answers — exactly the false positive TpuRootLeafDown would page on.
DEFAULT_SUPPRESSIONS: Mapping[str, str] = {
    "TpuRootLeafDown": "tpu_root_leaf_partition_suspected == 1",
}


def import_prometheus_rules(
    yaml_text: str,
    suppressions: Mapping[str, str] = DEFAULT_SUPPRESSIONS,
) -> str:
    """Translate a Prometheus alerting-rules YAML body into the native
    grammar (alerts only; recording rules stay with --store-rules).
    Needs pyyaml (a test dependency) — the importer runs at dev/deploy
    time, never on the serving path."""
    try:
        import yaml
    except ImportError as e:  # pragma: no cover — present in CI/test envs
        raise RuntimeError(
            "the rule importer needs pyyaml (pip install pyyaml); "
            "native rule files need no yaml at runtime") from e
    doc = yaml.safe_load(yaml_text)
    out: list[str] = [
        "# Generated by `python -m tpu_pod_exporter.alerting --import` —",
        "# the native twin of deploy/prometheus-rules.yaml (alerts only).",
        "",
    ]
    for group in (doc or {}).get("groups", ()):
        for rule in group.get("rules", ()):
            name = rule.get("alert")
            if not name:
                continue  # recording rule
            expr = " ".join(str(rule.get("expr", "")).split())
            out.append(f"alert {name} = {expr}")
            if rule.get("for"):
                out.append(
                    f"  for {_fmt_dur(parse_duration(str(rule['for'])))}")
            labels = rule.get("labels") or {}
            if labels:
                kv = ", ".join(f"{k}={json.dumps(str(v))}"
                               for k, v in labels.items())
                out.append(f"  labels({kv})")
            annotations = rule.get("annotations") or {}
            if annotations:
                kv = ", ".join(f"{k}={json.dumps(str(v))}"
                               for k, v in annotations.items())
                out.append(f"  annotations({kv})")
            sup = suppressions.get(str(name))
            if sup:
                out.append(f"  suppress({sup})")
            out.append("")
    return "\n".join(out)


# ------------------------------------------------------- status footer


def alert_status_summary(alert_dir: str) -> dict[str, Any] | None:
    """Read the alert-status.json sidecar for ``status``'s ``alerts:``
    footer (None when missing/unreadable — the caller renders an explicit
    error line, the store-footer discipline)."""
    try:
        with open(os.path.join(alert_dir, STATUS_NAME),
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


# ------------------------------------------------------------------- CLI


def main(argv: Sequence[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_pod_exporter.alerting",
        description="Native alerting plane tools: validate rule files, "
                    "import Prometheus rule YAML.",
    )
    p.add_argument("--check", metavar="FILE",
                   help="parse + validate a native alert rule file")
    p.add_argument("--import", dest="import_yaml", metavar="YAML",
                   help="translate a Prometheus rules YAML into the "
                        "native grammar (stdout)")
    ns = p.parse_args(argv)
    if ns.check:
        try:
            rules = load_alert_rules_file(ns.check)
        except (OSError, ValueError) as e:
            print(f"FAIL: {e}", file=sys.stderr)
            return 1
        print(f"ok: {len(rules)} alert rule(s)")
        for r in rules:
            clauses = []
            if r.for_s:
                clauses.append(f"for {_fmt_dur(r.for_s)}")
            if r.keep_firing_s:
                clauses.append(f"keep_firing {_fmt_dur(r.keep_firing_s)}")
            if r.suppress is not None:
                clauses.append("suppressed")
            print(f"  {r.name}"
                  + (f" [{', '.join(clauses)}]" if clauses else ""))
        return 0
    if ns.import_yaml:
        with open(ns.import_yaml, encoding="utf-8") as f:
            text = import_prometheus_rules(f.read())
        # Prove the translation parses before handing it to an operator.
        parse_alert_rules(text)
        print(text)
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
