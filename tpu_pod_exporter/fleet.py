"""Federated query plane — fleet-wide ``/api/v1`` over the flight recorders.

PRs 1–5 gave every node a crash-safe flight recorder, but an incident on a
v5p slice spans 64 hosts: answering "when did duty cycle cliff across the
slice" meant 64 separate curls against per-node ``/api/v1/*``. This module
federates the query plane through the aggregator: one
``query_range``/``window_stats``/``series`` request fans out to every
non-quarantined target concurrently, merges per-series results under the
same label-identity keying the rollup publisher uses, and answers with
**partial-result semantics** — a dead or slow target degrades the answer
(``partial: true`` plus per-target status and staleness in the envelope),
it never fails the round.

Design points, mirroring the scrape fan-out's discipline:

- **Bounded pool, per-target deadline.** Fan-out runs on its own worker
  pool (never the scrape pool — a dashboard storm must not delay rounds);
  each target gets the fetch timeout, and an overall wait deadline marks
  stragglers ``timeout`` without blocking the response on them.
- **Breaker-aware skip.** Targets the aggregator's scrape breakers hold
  open are skipped outright (``quarantined`` status) — the query plane
  must not burn the very timeouts the quarantine exists to save; their
  absence still marks the result partial, because missing data is missing.
- **Result cache.** A small LRU keyed by (route, query, grid, generation)
  absorbs dashboard-refresh traffic: one fan-out per generation bump (the
  aggregator bumps per round), not one per panel. Gridded queries align
  start/end to the step so sliding dashboard windows land on the same key.
- **Observability of the plane itself.** Each query is a trace (root
  ``query``, ``fanout``/``merge`` phase spans) riding the aggregator's
  existing Tracer; the fan-out stamps a W3C traceparent so node-side
  ``/api/v1`` handlers join their serve spans to it, exactly like
  ``/metrics`` scrape spans join rounds. Latency/partial/cache counters
  publish under ``tpu_aggregator_fleet_query_*`` (schema.FLEET_QUERY_SPECS).
"""

from __future__ import annotations

import inspect
import json
import logging
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Callable, Mapping, Sequence

from tpu_pod_exporter.metrics import CounterStore, HistogramStore, schema
from tpu_pod_exporter.metrics.registry import SnapshotBuilder
from tpu_pod_exporter.supervisor import CLOSED, CircuitBreaker
from tpu_pod_exporter.trace import PollTrace, Tracer, format_traceparent
from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.fleet")

# Routes the plane federates; also the pre-seeded label set of
# tpu_aggregator_fleet_queries_total (stable surface from round 1).
FLEET_ROUTES: tuple[str, ...] = ("series", "query_range", "window_stats")

# Per-target terminal states in the response envelope.
OK = "ok"               # target answered with data
NO_DATA = "no_data"     # target answered 404: no samples for this query
ERROR = "error"         # connection/HTTP/parse failure
TIMEOUT = "timeout"     # missed the fan-out deadline (still running)
QUARANTINED = "quarantined"  # breaker open — skipped, not attempted


def target_query_url(target: str, path: str, params: Mapping[str, str]) -> str:
    """``host:port`` (or URL root) + API path + query string."""
    if target.startswith(("http://", "https://")):
        base = target[: -len("/metrics")] if target.endswith("/metrics") else target
    else:
        base = f"http://{target}"
    return f"{base}{path}?{urllib.parse.urlencode(params)}"


def default_api_fetch(url: str, timeout_s: float,
                      traceparent: str | None = None) -> dict:
    """GET one node-side /api/v1 URL, parsed JSON. Raises on HTTP/parse
    failure; the plane classifies HTTP 404 separately (no data is an
    answer, not an outage). ``traceparent`` joins the node-side handler's
    serve span to this query's trace."""
    headers = {}
    if traceparent:
        headers["traceparent"] = traceparent
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied targets
        doc = json.loads(resp.read().decode("utf-8", errors="replace"))
    if not isinstance(doc, dict):
        raise ValueError("api response is not a JSON object")
    return doc


def data_shape(route: str, merged: list) -> Any:
    """Route → response ``data`` shape, mirroring the node-local answers
    exactly so every parser that reads one exporter reads the fleet. THE
    one implementation — the leaf plane, the root plane and the store-
    backed plane all serve through it (shapes must not drift between
    tiers; the cross-tier contract test pins it)."""
    if route == "series":
        return merged
    if route == "query_range":
        return {"resultType": "matrix", "result": merged}
    return {"result": merged}


def rows_of(route: str, env: Mapping[str, Any]) -> list:
    """Inverse of :func:`data_shape`: the row list out of an envelope
    (empty on malformed shapes — a bad upstream answer degrades, never
    raises)."""
    data = env.get("data")
    if route == "series":
        return data if isinstance(data, list) else []
    if isinstance(data, dict):
        rows = data.get("result")
        return rows if isinstance(rows, list) else []
    return []


class _QueryCache:
    """Bounded LRU for query envelopes, keyed by (route, query, grid,
    generation). Entries are treated as immutable by every reader (the
    HTTP layer only serializes them); the lock guards dict order only —
    no I/O or serialization ever runs under it."""

    def __init__(self, entries: int) -> None:
        self.entries = entries
        self._lock = threading.Lock()
        self._data: OrderedDict[tuple, dict] = OrderedDict()
        # key -> estimated envelope bytes, maintained alongside _data so
        # bytes() is O(1): the memory-pressure ladder reads it every check
        # interval, and the shed decision must see the same number
        # /debug/vars reports.
        self._sizes: dict[tuple, int] = {}
        self._bytes = 0
        # Flipped by the memory-pressure ladder's fleet_cache rung: while
        # disabled, put() is a no-op (every query re-fans-out — pure
        # correctness, just slower dashboards).
        self._enabled = True

    @staticmethod
    def _estimate(env: dict) -> int:
        try:
            return len(json.dumps(env, default=str))
        except (TypeError, ValueError):
            return 1024

    def get(self, key: tuple) -> dict | None:
        with self._lock:
            env = self._data.get(key)
            if env is not None:
                self._data.move_to_end(key)
            return env

    def put(self, key: tuple, env: dict) -> None:
        if self.entries <= 0:
            return
        size = self._estimate(env)
        with self._lock:
            # _enabled re-checked INSIDE the lock: a put racing the
            # memory-ladder's set_enabled(False)+clear() must not land
            # after the clear and leave a "disabled" cache serving (and
            # accounting) a stale entry.
            if not self._enabled:
                return
            self._bytes += size - self._sizes.get(key, 0)
            self._sizes[key] = size
            self._data[key] = env
            self._data.move_to_end(key)
            while len(self._data) > self.entries:
                victim, _ = self._data.popitem(last=False)
                self._bytes -= self._sizes.pop(victim, 0)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._sizes.clear()
            self._bytes = 0

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            # Flag + clear under ONE lock hold (see put's re-check).
            self._enabled = bool(enabled)
            if not enabled:
                self._data.clear()
                self._sizes.clear()
                self._bytes = 0

    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)


# Public alias: the root query plane (shard.RootQueryPlane) reuses the
# same bounded LRU + byte-accounting for ITS generation-keyed result
# cache — one cache implementation, one memory-accounting story.
QueryCache = _QueryCache


class FleetQueryPlane:
    """Fan ``/api/v1`` queries out to every target; merge with partial-result
    semantics. Runs entirely on HTTP handler threads + its own pool — the
    aggregator's round loop is never involved beyond sharing breakers."""

    def __init__(
        self,
        targets: Sequence[str],
        timeout_s: float = 2.0,
        fetch: Callable[..., dict] = default_api_fetch,
        breakers: Mapping[str, CircuitBreaker] | None = None,
        tracer: Tracer | None = None,
        max_workers: int = 16,
        cache_entries: int = 128,
        generation_fn: Callable[[], int] | None = None,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
        targets_fn: Callable[[], Sequence[str]] | None = None,
    ) -> None:
        if not targets and targets_fn is None:
            raise ValueError("fleet query plane needs at least one target")
        self._static_targets = tuple(targets)
        # Live membership (the aggregator's TargetSet view): with a
        # --targets-file or the sharded leaf tier, the target list changes
        # between queries — each query snapshots the callable once so its
        # fan-out, statuses and merge ordering agree within the query.
        self._targets_fn = targets_fn
        self._timeout_s = timeout_s
        self._fetch = fetch
        # Same auto-detection as the scrape fan-out: injected 2-arg test
        # fetches don't get a traceparent kwarg forced on them.
        self._fetch_traceparent = False
        try:
            self._fetch_traceparent = (
                "traceparent" in inspect.signature(fetch).parameters
            )
        except (TypeError, ValueError):
            pass
        self._breakers = breakers
        self._tracer = tracer
        self._clock = clock
        self._wallclock = wallclock
        self._generation_fn = generation_fn
        self._cache = _QueryCache(cache_entries)
        self._rlog = RateLimitedLogger(log)
        self._counters = CounterStore()
        self._hist = HistogramStore(schema.TPU_AGG_FLEET_QUERY_HIST)
        # Pre-seed every counter so the conditional surface is stable from
        # the first exposition after the plane is attached.
        for route in FLEET_ROUTES:
            self._counters.inc(schema.TPU_AGG_FLEET_QUERIES_TOTAL.name,
                               (route,), 0.0)
        self._counters.inc(
            schema.TPU_AGG_FLEET_QUERY_PARTIAL_TOTAL.name, (), 0.0)
        self._counters.inc(
            schema.TPU_AGG_FLEET_QUERY_CACHE_HITS_TOTAL.name, (), 0.0)
        self._counters.inc(
            schema.TPU_AGG_FLEET_QUERY_CACHE_MISSES_TOTAL.name, (), 0.0)
        # The cap alone: workers spawn lazily per pending fan-out leg, so
        # small fleets stay small — and a plane built before a targets
        # file exists (targets_fn membership) still fans a grown fleet
        # out at full width instead of a boot-sized trickle.
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="tpu-fleet-query",
        )

    def _current_targets(self) -> tuple[str, ...]:
        """Membership snapshot for one query (live when targets_fn is
        wired, else the construction-time tuple)."""
        if self._targets_fn is not None:
            try:
                return tuple(self._targets_fn())
            except Exception:  # noqa: BLE001 — a broken hook degrades to static
                return self._static_targets
        return self._static_targets

    # ------------------------------------------------------------- public API

    def series(self) -> dict:
        return self._query("series", "/api/v1/series", {}, key=("series",))

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
    ) -> dict:
        if end is None:
            end = self._wallclock()
        if start is None:
            start = end - 300.0
        if step > 0:
            # Grid alignment: dashboard panels slide start/end continuously;
            # snapping both to the step grid makes successive refreshes of
            # one panel share a cache key (and an actual grid) within a
            # generation, at the cost of answering for up to one step more
            # than asked. The effective range rides the envelope.
            start = (start // step) * step
            end = -((-end) // step) * step
            # Alignment widened the range by up to 2·step; a request that
            # sat exactly at the node-side 11k resolution cap would now be
            # 400'd by every healthy target and read as a fleet-wide
            # outage. Give up grid points at the OLD edge instead.
            if (end - start) / step > 11000:
                start = end - 11000 * step
        match = dict(match or {})
        params = {"metric": metric, "start": f"{start:.3f}",
                  "end": f"{end:.3f}", "step": f"{step:g}", "agg": agg}
        for k, v in match.items():
            params[f"match[{k}]"] = v
        key = ("query_range", metric, tuple(sorted(match.items())),
               round(start, 3), round(end, 3), step, agg)
        env = self._query("query_range", "/api/v1/query_range", params,
                          key=key)
        env.setdefault("start", start)
        env.setdefault("end", end)
        return env

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
    ) -> dict:
        match = dict(match or {})
        params = {"metric": metric, "window": f"{window_s:g}"}
        for k, v in match.items():
            params[f"match[{k}]"] = v
        key = ("window_stats", metric, tuple(sorted(match.items())), window_s)
        return self._query("window_stats", "/api/v1/window_stats", params,
                           key=key)

    # --------------------------------------------------------------- internals

    def _query(self, route: str, path: str, params: Mapping[str, str],
               key: tuple) -> dict:
        self._counters.inc(schema.TPU_AGG_FLEET_QUERIES_TOTAL.name, (route,))
        generation = self._generation_fn() if self._generation_fn else 0
        cache_key = key + (generation,)
        cached = self._cache.get(cache_key)
        if cached is not None:
            self._counters.inc(
                schema.TPU_AGG_FLEET_QUERY_CACHE_HITS_TOTAL.name, ())
            # Shallow copy: the cached envelope is shared and read-only;
            # only the top-level "cached" marker differs per response.
            return {**cached, "cached": True}
        self._counters.inc(
            schema.TPU_AGG_FLEET_QUERY_CACHE_MISSES_TOTAL.name, ())
        t0 = self._clock()
        targets = self._current_targets()
        tracer = self._tracer
        tr = tracer.start_poll() if tracer is not None else None
        statuses, rows_by_target = self._fan_out(route, path, params, tr,
                                                 targets)
        mspan = tr.span("merge") if tr is not None else None
        merged, dup = self._merge(route, rows_by_target, statuses, targets)
        partial = any(
            st["state"] in (ERROR, TIMEOUT, QUARANTINED)
            for st in statuses.values()
        )
        took = self._clock() - t0
        env = {
            "status": "ok",
            "partial": partial,
            "route": route,
            # Source attribution, shared across every /api/v1 tier: a
            # fan-out answer is "live" by definition; the root's
            # store-backed plane (tpu_pod_exporter.store) upgrades this
            # to live|store|merged. One envelope contract — shapes must
            # not drift between tiers (asserted by the shared-contract
            # test in tests/test_store.py).
            "source": "live",
            "data": self._data_shape(route, merged),
            "targets": statuses,
            "fleet": {
                "targets": len(targets),
                "ok": sum(1 for s in statuses.values() if s["state"] == OK),
                "no_data": sum(
                    1 for s in statuses.values() if s["state"] == NO_DATA),
                "errors": sum(
                    1 for s in statuses.values()
                    if s["state"] in (ERROR, TIMEOUT)),
                "quarantined": sum(
                    1 for s in statuses.values()
                    if s["state"] == QUARANTINED),
                "merged_series": len(merged),
                "duplicate_series": dup,
            },
            "generation": generation,
            "took_s": round(took, 6),
        }
        if partial:
            self._counters.inc(
                schema.TPU_AGG_FLEET_QUERY_PARTIAL_TOTAL.name, ())
        self._hist.observe(took)
        if tracer is not None and tr is not None:
            if mspan is not None:
                tr.end_span(mspan, "ok", series=len(merged), duplicates=dup)
            tracer.finish(
                tr, status="ok" if not partial else "err",
                route=route, targets=len(targets),
                ok=env["fleet"]["ok"], partial=partial,
            )
        self._cache.put(cache_key, env)
        return env

    def _fan_out(
        self, route: str, path: str, params: Mapping[str, str],
        tr: PollTrace | None, targets: tuple[str, ...],
    ) -> tuple[dict[str, dict], dict[str, list]]:
        span = tr.span("fanout") if tr is not None else None
        traceparent = (
            format_traceparent(tr.trace_id, span.span_id)
            if tr is not None and span is not None and self._fetch_traceparent
            else None
        )
        now_wall = self._wallclock()
        statuses: dict[str, dict] = {}
        rows_by_target: dict[str, list] = {}
        futures: dict[Future, str] = {}
        for target in targets:
            br = self._breakers.get(target) if self._breakers else None
            if br is not None and br.state != CLOSED:
                # Quarantine is a scrape-plane fact the query plane trusts:
                # the endpoint is the same dead port, and probing it from
                # here would burn the timeout the breaker exists to save.
                statuses[target] = {
                    "state": QUARANTINED,
                    "next_probe_in_s": round(br.seconds_until_probe, 3),
                }
                continue
            fut = self._pool.submit(
                self._fetch_one, target, path, params, traceparent)
            futures[fut] = target
        # One overall deadline on top of the per-fetch socket timeout: a
        # target drip-feeding bytes (or a pool briefly saturated by another
        # query) marks stragglers `timeout` instead of delaying the answer.
        deadline = self._clock() + self._timeout_s + 0.5
        pending = set(futures)
        while pending:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            done, pending = wait(pending, timeout=remaining,
                                 return_when=FIRST_COMPLETED)
            for fut in done:
                target = futures[fut]
                state, rows, err, dur = fut.result()
                st: dict[str, Any] = {"state": state,
                                      "duration_s": round(dur, 6)}
                if err:
                    st["error"] = err
                if rows is not None:
                    st["series"] = len(rows)
                    st["staleness_s"] = self._staleness(route, rows, now_wall)
                    rows_by_target[target] = rows
                statuses[target] = st
                if state == ERROR:
                    self._counters.inc(
                        schema.TPU_AGG_FLEET_QUERY_TARGET_ERRORS_TOTAL.name,
                        (target,),
                    )
        for fut in pending:
            target = futures[fut]
            statuses[target] = {"state": TIMEOUT,
                                "error": "missed fan-out deadline"}
            self._counters.inc(
                schema.TPU_AGG_FLEET_QUERY_TARGET_ERRORS_TOTAL.name,
                (target,),
            )
            # Left running on the pool (the fetch's own socket timeout
            # bounds it); cancel() would be a no-op once started.
        if tr is not None and span is not None:
            tr.end_span(
                span, "ok",
                targets=len(targets),
                ok=sum(1 for s in statuses.values() if s["state"] == OK),
                timeouts=len(pending),
            )
        return statuses, rows_by_target

    def _fetch_one(
        self, target: str, path: str, params: Mapping[str, str],
        traceparent: str | None,
    ) -> tuple[str, list | None, str, float]:
        """One target's fan-out leg → (state, rows, error, duration)."""
        t0 = self._clock()
        url = target_query_url(target, path, params)
        try:
            if traceparent is not None:
                doc = self._fetch(url, self._timeout_s,
                                  traceparent=traceparent)
            else:
                doc = self._fetch(url, self._timeout_s)
        except urllib.error.HTTPError as e:
            dur = self._clock() - t0
            if e.code == 404:
                # The node answered: this metric/window simply has no
                # samples there (or history is disabled) — complete, not
                # partial.
                return NO_DATA, [], "", dur
            self._rlog.warning(f"query:{target}",
                               "fleet query to %s failed: %s", target, e)
            return ERROR, None, f"HTTP {e.code}", dur
        except Exception as e:  # noqa: BLE001 — a down host is data, not death
            self._rlog.warning(f"query:{target}",
                               "fleet query to %s failed: %s", target, e)
            return ERROR, None, str(e), self._clock() - t0
        dur = self._clock() - t0
        try:
            if path.endswith("/series"):
                rows = doc["data"]
            else:
                rows = doc["data"]["result"]
            if not isinstance(rows, list):
                raise TypeError("result is not a list")
        except (KeyError, TypeError) as e:
            self._rlog.warning(f"query:{target}",
                               "bad api answer from %s: %s", target, e)
            return ERROR, None, f"bad response shape: {e}", dur
        return OK, rows, "", dur

    @staticmethod
    def _staleness(route: str, rows: list, now_wall: float) -> float | None:
        """Per-target staleness: age of the target's freshest sample across
        the series it returned (None when the route carries no timestamps)."""
        newest = None
        for row in rows:
            try:
                ts = row.get("last_sample_wall_ts")
            except AttributeError:
                continue
            if isinstance(ts, (int, float)) and (
                    newest is None or ts > newest):
                newest = float(ts)
        if newest is None:
            return None
        return round(max(now_wall - newest, 0.0), 3)

    def _merge(
        self, route: str, rows_by_target: Mapping[str, list],
        statuses: dict[str, dict], targets: tuple[str, ...],
    ) -> tuple[list[dict], int]:
        """Label-identity merge — the same keying ``_publish`` uses for
        chips/slices: a series is (metric, label set), whichever host it
        came from. Colliding keys (the same label set from two targets —
        label-less self-metrics like ``tpu_exporter_up`` collide for EVERY
        target pair) are disambiguated with a synthetic ``target`` label
        rather than folded: dropping 63 hosts' up-series because their
        label sets match would silently discard exactly the per-host
        signal a fleet query exists to surface. Collisions are counted in
        ``duplicate_series``."""
        groups: dict[tuple, list[tuple[str, dict]]] = {}
        # Deterministic iteration: target membership order, so output
        # ordering resolves stably round to round.
        for target in targets:
            rows = rows_by_target.get(target)
            if not rows:
                continue
            for row in rows:
                if not isinstance(row, dict):
                    continue
                try:
                    key = (
                        row.get("metric", ""),
                        tuple(sorted((row.get("labels") or {}).items())),
                    )
                except TypeError:
                    continue
                groups.setdefault(key, []).append((target, row))
        merged: list[dict] = []
        duplicates = 0
        for key in sorted(groups):
            entries = groups[key]
            if len(entries) == 1:
                merged.append(entries[0][1])
                continue
            duplicates += len(entries) - 1
            for target, row in entries:
                merged.append({
                    **row,
                    "labels": {**(row.get("labels") or {}),
                               "target": target},
                })
        return merged, duplicates

    _data_shape = staticmethod(data_shape)

    # -------------------------------------------------------------- exposition

    def emit(self, b: SnapshotBuilder) -> None:
        """Publish the plane's self-metrics into one aggregator snapshot
        (called from ``SliceAggregator._publish`` — conditional surface,
        present only while the plane is attached)."""
        for spec in schema.FLEET_QUERY_SPECS:
            b.declare(spec)
        for spec in schema.FLEET_QUERY_SPECS:
            for lv, v in self._counters.items_for(spec.name):
                b.add(spec, v, lv)
        self._hist.emit(b)

    def stats(self) -> dict:
        """Introspection payload for the aggregator's /debug/vars."""
        return {
            "targets": len(self._current_targets()),
            "timeout_s": self._timeout_s,
            "cache_entries": len(self._cache),
            "cache_capacity": self._cache.entries,
            # The SAME estimate the memory-pressure ladder's shed decision
            # sums — /debug/vars and the governor must never disagree.
            "cache_bytes": self._cache.bytes(),
        }

    # ------------------------------------------------- pressure shed hook

    def cache_bytes(self) -> int:
        """Byte estimate of the result cache, for the memory budget's
        component accounting (tpu_pod_exporter.pressure)."""
        return self._cache.bytes()

    def set_cache_enabled(self, enabled: bool) -> None:
        """Memory-ladder rung ``fleet_cache``: clear + disable the result
        cache (queries re-fan-out; correctness unchanged). Reversible."""
        self._cache.set_enabled(enabled)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
