"""Single-chip synthetic workloads.

``flagship()`` is the canonical jittable forward step: a depth-stacked bf16
matmul chain driven by ``lax.scan``. Everything the MXU likes — large square
matmuls, bf16 inputs with f32 accumulation, one fused tanh per layer, no
data-dependent Python control flow — and nothing it doesn't.
"""

from __future__ import annotations

import functools


def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


def init_params(width: int = 512, depth: int = 8, seed: int = 0):
    """Stacked layer weights (depth, width, width) in bf16.

    Stacking + scan compiles one layer body reused `depth` times instead of
    unrolling `depth` HLOs — smaller programs, same MXU throughput.
    """
    jax, jnp = _jax()
    key = jax.random.PRNGKey(seed)
    scale = (2.0 / width) ** 0.5
    w = jax.random.normal(key, (depth, width, width), dtype=jnp.float32) * scale
    return {"layers": w.astype(jnp.bfloat16)}


def forward(params, x):
    """x: (batch, width) bf16 → (batch, width) bf16."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def layer(h, w):
        # f32 accumulation on the MXU, cast back to keep HBM traffic in bf16.
        y = jnp.dot(h, w, preferred_element_type=jnp.float32)
        return jnp.tanh(y).astype(jnp.bfloat16), None

    out, _ = lax.scan(layer, x, params["layers"])
    return out


def loss_fn(params, x, y):
    import jax.numpy as jnp

    pred = forward(params, x).astype(jnp.float32)
    return jnp.mean((pred - y.astype(jnp.float32)) ** 2)


def flagship(width: int = 512, depth: int = 8, batch: int = 256):
    """(jittable forward fn, example_args) — the compile-check entry point."""
    jax, jnp = _jax()
    params = init_params(width=width, depth=depth)
    x = jnp.ones((batch, width), dtype=jnp.bfloat16)
    return jax.jit(forward), (params, x)


@functools.lru_cache(maxsize=None)
def _burn_fn(width: int, depth: int, iters: int):
    jax, jnp = _jax()
    from jax import lax

    def burn(params, x):
        def body(h, _):
            h = forward(params, h)
            return h, None

        out, _ = lax.scan(body, x, None, length=iters)
        return out

    return jax.jit(burn)


def burn_step(params, x, iters: int = 10):
    """Run `iters` forward passes on-device per call — a duty-cycle dial:
    more iters per wall-second → higher TensorCore utilization."""
    width = x.shape[-1]
    depth = params["layers"].shape[0]
    return _burn_fn(width, depth, iters)(params, x)


def hbm_fill(n_bytes: int, device=None):
    """Allocate ~n_bytes on device (bf16 zeros) and return the live buffer.

    Holding the returned array keeps the HBM in use — the instrument for
    exercising tpu_hbm_used_bytes end-to-end on real hardware.
    """
    jax, jnp = _jax()
    n = max(n_bytes // 2, 1)  # bf16 = 2 bytes
    arr = jnp.zeros((n,), dtype=jnp.bfloat16)
    if device is not None:
        arr = jax.device_put(arr, device)
    return arr.block_until_ready()
