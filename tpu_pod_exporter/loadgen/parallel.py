"""Sequence-, pipeline-, and expert-parallel programs over a device mesh.

Completes the loadgen's parallelism coverage beyond ``sharded.py``'s dp×tp
step (SURVEY.md §2.8: the reference has *no* distributed component; here the
distributed dimension is the *instrument* — each strategy produces a
distinct, deterministic ICI traffic pattern the exporter's ``tpu_ici_*``
metrics must observe):

- **Ring attention** (sequence/context parallel): K/V blocks rotate around
  the mesh via ``lax.ppermute`` while a flash-style running softmax
  accumulates — neighbor-only ICI traffic, the long-context pattern.
- **Ulysses attention** (sequence parallel, all_to_all flavor): one
  ``all_to_all`` swaps the sequence shard for a head shard, exact
  attention runs per head on the full sequence, a second ``all_to_all``
  swaps back — two bulk crossbar bursts instead of n ppermute hops.
- **Pipeline parallel**: GPipe-style microbatch schedule; activations hop
  stage→stage via ``ppermute`` — directional neighbor traffic with bubbles.
- **Expert parallel (MoE)**: tokens ``lax.all_to_all`` to their expert's
  device and back — the dense crossbar pattern.
- **FSDP**: forward ``all_gather`` of the row-sharded weight; its transpose
  lowers the weight gradient to ``reduce_scatter`` — the fan-in/fan-out pair.
- **Multi-slice dp × tp** (2D mesh): cross-slice gradient all-reduce
  (DCN-class axis) over intra-slice tensor parallelism (ICI-class axis) —
  BASELINE config 5's compute shape.

All six are ``jax.shard_map`` programs with compiler-visible collectives
(no data-dependent Python control flow), verified numerically against their
single-device references in ``tests/test_parallel.py`` on the virtual CPU
mesh, and composed into the driver's multi-chip dry run
(``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations


def _shard_map():
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:  # jax < 0.8
        from jax.experimental.shard_map import shard_map as sm
    return sm


def make_1d_mesh(n_devices: int, axis: str, platform: str | None = None):
    import numpy as np
    from jax.sharding import Mesh

    from tpu_pod_exporter.loadgen.sharded import pick_devices

    return Mesh(
        np.array(pick_devices(n_devices, platform=platform)), axis_names=(axis,)
    )


# --------------------------------------------------------------------- ring

def reference_attention(q, k, v):
    """Plain softmax attention — the single-device ground truth. Dots pinned
    to precision='highest': XLA's default dot lowering may round operands
    (bf16-class) and a lossy reference would mask real defects."""
    import jax.numpy as jnp

    d = q.shape[-1]
    scores = jnp.matmul(q, k.T, precision="highest") / jnp.sqrt(jnp.float32(d))
    w = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    return jnp.matmul(w, v, precision="highest")


def ring_attention_fn(mesh, axis: str = "seq"):
    """shard_map program: q/k/v sharded along the sequence axis; K/V blocks
    rotate ``n`` hops around the ring while a running (max, denominator)
    softmax accumulates — numerically identical to full attention without
    any device ever holding the whole sequence (the long-context recipe)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local_block(q, k, v):
        # q: (Tq, d) local queries; k/v: (Tkv, d) — one rotating block.
        d = q.shape[-1]

        def body(carry, _):
            o, m, l, kb, vb = carry
            s = (q @ kb.T) / jnp.sqrt(jnp.float32(d))      # (Tq, Tkv)
            m_new = jnp.maximum(m, s.max(axis=-1))
            corr = jnp.exp(m - m_new)                       # (Tq,)
            p = jnp.exp(s - m_new[:, None])                 # (Tq, Tkv)
            l = l * corr + p.sum(axis=-1)
            o = o * corr[:, None] + p @ vb
            kb = lax.ppermute(kb, axis, perm)
            vb = lax.ppermute(vb, axis, perm)
            return (o, m_new, l, kb, vb), None

        # Derive the initial carry from q so its device-varying provenance
        # matches the loop outputs (jax ≥0.8 tracks varying manual axes).
        o0 = jnp.zeros_like(q)
        m0 = jnp.full_like(q[:, 0], -jnp.inf)
        l0 = jnp.zeros_like(q[:, 0])
        (o, _, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v), None, length=n)
        return o / l[:, None]

    sm = _shard_map()
    seq_sharded = P(axis, None)
    fn = sm(local_block, mesh=mesh,
            in_specs=(seq_sharded, seq_sharded, seq_sharded),
            out_specs=seq_sharded)
    sharding = NamedSharding(mesh, seq_sharded)
    return jax.jit(fn), sharding


def reference_mha(q, k, v):
    """Per-head softmax attention on full (T, H, d) tensors — ground truth
    for :func:`ulysses_attention_fn`. Deliberately vmap of
    :func:`reference_attention` over the head axis: ONE definition of the
    ground-truth attention math, so a stability/precision tweak there can
    never silently diverge from this one."""
    import jax

    return jax.vmap(reference_attention, in_axes=1, out_axes=1)(q, k, v)


def ulysses_attention_fn(mesh, axis: str = "seq"):
    """Ulysses-style sequence parallelism (DeepSpeed-Ulysses): q/k/v are
    sharded along the SEQUENCE axis; one ``all_to_all`` re-shards them to
    HEAD-parallel so each device computes exact full-sequence attention
    for its own heads, and a second ``all_to_all`` restores sequence
    sharding. The complementary recipe to :func:`ring_attention_fn` —
    two bulk all-to-alls instead of n ppermute hops, with no device ever
    holding all heads AND all sequence. Requires heads % n_devices == 0.

    Returns ``fn(q, k, v) -> out`` over (T, H, d) tensors sharded
    ``P(axis, None, None)``."""
    import jax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def local(q, k, v):
        # q/k/v local: (T/n, H, d). all_to_all: split heads, gather seq
        # → (T, H/n, d): full sequence for this device's head group.
        qh = lax.all_to_all(q, axis, split_axis=1, concat_axis=0, tiled=True)
        kh = lax.all_to_all(k, axis, split_axis=1, concat_axis=0, tiled=True)
        vh = lax.all_to_all(v, axis, split_axis=1, concat_axis=0, tiled=True)
        out = reference_mha(qh, kh, vh)  # exact attention, local heads
        # Inverse all_to_all: split seq, gather heads → (T/n, H, d).
        return lax.all_to_all(out, axis, split_axis=0, concat_axis=1, tiled=True)

    sm = _shard_map()
    spec = P(axis, None, None)
    fn = sm(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(fn), NamedSharding(mesh, spec)


# ----------------------------------------------------------------- pipeline

def pipeline_forward_fn(mesh, axis: str = "stage"):
    """GPipe-style pipeline: device ``i`` owns stage ``i``'s weights; each
    tick every stage computes its microbatch and ppermutes the activation to
    the next stage. ``n_micro + n_stages - 1`` ticks drain the schedule.

    Returns ``fn(stage_w, xs) -> ys`` where ``stage_w`` is (n_stages, w, w)
    sharded over the stage axis, ``xs`` is (n_micro, mb, w) replicated, and
    ``ys`` is the pipeline output (replicated; every device returns it)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_stage = mesh.shape[axis]
    # stage i sends to stage i+1 (no wraparound: directional traffic).
    perm = [(i, i + 1) for i in range(n_stage - 1)]

    def local(stage_w, xs):
        # stage_w: (1, w, w) this stage's weights; xs: (n_micro, mb, w).
        # n_micro comes from xs itself (static at trace time): a separately
        # configured count could silently drop or duplicate microbatches.
        n_micro = xs.shape[0]
        # The tick loop is a lax.scan, not a Python unroll: graph size stays
        # O(1) in n_micro + n_stage (a 64-stage mesh would otherwise unroll
        # ~190 matmul+ppermute ticks into one XLA program).
        w = stage_w[0]
        idx = lax.axis_index(axis)
        mb, width = xs.shape[1], xs.shape[2]
        # Pad the schedule's drain ticks so inject is dynamically indexable.
        xs_pad = jnp.concatenate(
            [xs, jnp.zeros((n_stage - 1, mb, width), xs.dtype)], axis=0
        )
        # Carries are written by device-varying computation, so their initial
        # values must carry the same provenance (jax ≥0.8 vma rule). pcast is
        # the current spelling; pvary the pre-0.8.1 one.
        def _varying(a):
            pcast = getattr(lax, "pcast", None)
            if pcast is not None:
                return pcast(a, (axis,), to="varying")
            return lax.pvary(a, (axis,))

        out0 = _varying(jnp.zeros_like(xs))
        h0 = _varying(jnp.zeros((mb, width), xs.dtype))

        def tick(carry, t):
            out, h_recv = carry
            inject = lax.dynamic_index_in_dim(xs_pad, t, keepdims=False)
            h_in = jnp.where(idx == 0, inject, h_recv)
            h_out = jnp.tanh(h_in @ w)
            slot = t - (n_stage - 1)
            # Only the last stage, and only during drain-valid ticks, writes
            # its result; everyone else adds zeros to the clamped slot.
            writes = (idx == n_stage - 1) & (slot >= 0)
            contrib = jnp.where(writes, h_out, jnp.zeros_like(h_out))
            out = out.at[jnp.maximum(slot, 0)].add(contrib)
            h_recv = lax.ppermute(h_out, axis, perm)
            return (out, h_recv), None

        ticks = jnp.arange(n_micro + n_stage - 1)
        (out, _), _ = lax.scan(tick, (out0, h0), ticks)
        # out is populated only on the last stage; all-reduce replicates it.
        return lax.psum(out, axis)

    sm = _shard_map()
    fn = sm(local, mesh=mesh,
            in_specs=(P(axis, None, None), P()),
            out_specs=P())
    return jax.jit(fn), NamedSharding(mesh, P(axis, None, None))


def reference_pipeline(stage_w, xs):
    """Sequential application of every stage — ground truth (highest-precision
    dots; see reference_attention)."""
    import jax.numpy as jnp

    h = xs  # (n_micro, mb, w)
    for i in range(stage_w.shape[0]):
        h = jnp.tanh(jnp.matmul(h, stage_w[i], precision="highest"))
    return h


# ---------------------------------------------------------------------- moe

def moe_forward_fn(mesh, axis: str = "expert"):
    """Expert-parallel MoE layer: device ``i`` owns expert ``i``. Local token
    ``j`` routes deterministically to expert ``j % n_experts`` (position
    routing keeps the program data-independent — the point is the
    ``all_to_all`` dispatch/combine traffic, not a learned gate).

    Returns ``fn(expert_w, x) -> y`` with ``expert_w`` (n_exp, d, d) sharded
    over the expert axis and ``x`` (tokens, d) sharded over the same axis."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_exp = mesh.shape[axis]

    def local(expert_w, x):
        # expert_w: (1, d, d); x: (t_local, d) with t_local % n_exp == 0.
        w = expert_w[0]
        t_local, d = x.shape
        cap = t_local // n_exp
        # Group local tokens by destination expert: token j → expert j%n_exp.
        groups = x.reshape(cap, n_exp, d).transpose(1, 0, 2)  # (n_exp, cap, d)
        # Dispatch: slot e goes to device e; receive one block per source.
        recv = lax.all_to_all(groups, axis, split_axis=0, concat_axis=0)
        hidden = jnp.tanh(recv.reshape(n_exp * cap, d) @ w)
        # Combine: send each source's processed block home.
        back = lax.all_to_all(hidden.reshape(n_exp, cap, d), axis,
                              split_axis=0, concat_axis=0)
        return back.transpose(1, 0, 2).reshape(t_local, d)

    sm = _shard_map()
    fn = sm(local, mesh=mesh,
            in_specs=(P(axis, None, None), P(axis, None)),
            out_specs=P(axis, None))
    return jax.jit(fn), NamedSharding(mesh, P(axis, None, None)), NamedSharding(mesh, P(axis, None))


def reference_moe(expert_w, x):
    """Every token through its position-routed expert — ground truth."""
    import jax.numpy as jnp

    n_exp = expert_w.shape[0]
    t = x.shape[0]
    idx = jnp.arange(t) % n_exp
    per_expert = jnp.einsum(
        "td,edh->teh", x, expert_w, precision="highest"
    )  # (t, n_exp, d)
    return jnp.tanh(per_expert[jnp.arange(t), idx])


# --------------------------------------------------------------------- fsdp

def fsdp_step_fn(mesh, axis: str = "shard", lr: float = 0.1):
    """FSDP-style sharded data parallelism: each device owns a row-shard of
    the weight and a batch-shard of the data. Forward ``all_gather``s the
    full weight (fan-in ICI); autodiff of the tiled all_gather lowers the
    weight gradient to ``reduce_scatter`` (fan-out) — together the one
    collective pair the other loadgen programs don't produce (ring
    ppermute, pipeline ppermute, MoE all_to_all, dp×tp psum).

    Returns ``fn(w_shard, x, y) -> (new_w_shard, loss)`` with everything
    sharded over ``axis`` except the (replicated) scalar loss."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]

    def local(w_shard, x, y):
        # w_shard: (d/n, d); x, y: (b/n, d)
        def local_loss(ws):
            w = lax.all_gather(ws, axis, axis=0, tiled=True)  # (d, d)
            pred = jnp.tanh(x @ w)
            return jnp.mean((pred - y) ** 2)

        loss, g = jax.value_and_grad(local_loss)(w_shard)
        loss = lax.pmean(loss, axis)  # global loss = mean of shard losses
        # The tiled all_gather's transpose is reduce_scatter: g already
        # holds the cross-device SUM of cotangents for *this* shard, so
        # the data-parallel mean is a plain /n — a pmean here would
        # wrongly average together grads of different shards.
        return w_shard - lr * (g / n), loss

    sm = _shard_map()
    fn = sm(local, mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(axis, None)),
            out_specs=(P(axis, None), P()))
    return jax.jit(fn), NamedSharding(mesh, P(axis, None))


def reference_fsdp(w, x, y, lr: float = 0.1):
    """Dense single-device step — ground truth for fsdp_step_fn (highest-
    precision dots; see reference_attention)."""
    import jax
    import jax.numpy as jnp

    def loss_of(wf):
        pred = jnp.tanh(jnp.matmul(x, wf, precision="highest"))
        return jnp.mean((pred - y) ** 2)

    loss, g = jax.value_and_grad(loss_of)(w)
    return w - lr * g, loss


# ------------------------------------------------------------- multi-slice

def make_2d_mesh(
    n_slices: int,
    per_slice: int,
    axes: tuple[str, str] = ("slice", "intra"),
    platform: str | None = None,
):
    import numpy as np
    from jax.sharding import Mesh

    from tpu_pod_exporter.loadgen.sharded import pick_devices

    devs = pick_devices(n_slices * per_slice, platform=platform)
    return Mesh(np.array(devs).reshape(n_slices, per_slice), axis_names=axes)


def multislice_step_fn(mesh, slice_axis: str = "slice",
                       tp_axis: str = "intra", lr: float = 0.1):
    """Cross-slice data parallelism × intra-slice tensor parallelism over a
    2D mesh — BASELINE config 5's compute shape (2 TPU slices cooperating
    over DCN). The batch row-shards across slices and the weight
    column-shards within each slice; the backward pass's gradient ``psum``
    over ``slice_axis`` is the cross-slice (DCN-class) collective and the
    loss ``psum`` over ``tp_axis`` the intra-slice (ICI-class) one — each
    mesh axis maps to one fabric, exactly the split the exporter's
    ``tpu_ici_*`` / ``tpu_dcn_*`` families observe.

    Returns ``(fn, w_sharding, x_sharding)``; ``fn(w, x) -> (new_w,
    loss)`` with w column-sharded over tp (replicated across slices) and x
    batch-sharded across slices (replicated within one).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def local(w_shard, x_shard):
        # w_shard: (d, d/tp); x_shard: (b/slices, d).
        def local_loss(ws):
            y = x_shard @ ws
            return jnp.sum(y * y)

        part, g = jax.value_and_grad(local_loss)(w_shard)
        # The cross-slice (DCN) gradient all-reduce is already IN g:
        # w_shard is replicated over slice_axis while x_shard varies over
        # it, so transposing that use makes jax insert psum(·, slice_axis)
        # on the cotangent to keep it replicated like its primal — the
        # same transpose rule the FSDP program's reduce_scatter comment
        # documents. An explicit psum here would double-count (measured:
        # exactly n_slices× the dense gradient).
        # The global loss crosses BOTH fabrics explicitly: column shards
        # (ICI-class, tp_axis) and batch shards (DCN-class, slice_axis).
        loss = lax.psum(part, (tp_axis, slice_axis))
        return w_shard - lr * g, loss

    sm = _shard_map()
    fn = sm(local, mesh=mesh,
            in_specs=(P(None, tp_axis), P(slice_axis, None)),
            out_specs=(P(None, tp_axis), P()))
    return (
        jax.jit(fn),
        NamedSharding(mesh, P(None, tp_axis)),
        NamedSharding(mesh, P(slice_axis, None)),
    )


def reference_multislice(w, x, lr: float = 0.1):
    """Dense single-device step — ground truth for multislice_step_fn
    (highest-precision dots; see reference_attention)."""
    import jax
    import jax.numpy as jnp

    def loss_of(wf):
        y = jnp.matmul(x, wf, precision="highest")
        return jnp.sum(y * y)

    loss, g = jax.value_and_grad(loss_of)(w)
    return w - lr * g, loss


# ------------------------------------------------------------------- dryrun

PARALLEL_PROGRAMS = (
    "ring", "ulysses", "pipeline", "moe", "fsdp", "multislice",
)


def build_parallel_program(name: str, n_devices: int, scale: int = 1):
    """One named strategy packaged for CLI looping on live hardware:
    returns ``(step, args, feed)`` where ``step(*args)`` runs one
    iteration and ``feed(args, out) -> args`` threads the output back in
    as the next input — a real data dependency per step, so no runtime
    can elide repeated identical executions (same trick as burn mode).
    ``scale`` multiplies the tensor dimensions (ICI bytes/step) without
    changing the collective pattern."""
    import jax
    import jax.numpy as jnp

    if name not in PARALLEL_PROGRAMS:
        raise ValueError(f"unknown program {name!r}; pick from {PARALLEL_PROGRAMS}")
    key = jax.random.PRNGKey(0)
    n = n_devices

    if name == "ring":
        mesh = make_1d_mesh(n, "seq")
        fn, sharding = ring_attention_fn(mesh)
        t, d = 4 * n * scale, 8 * scale
        q, k, v = (
            jax.device_put(jax.random.normal(key, (t, d), jnp.float32), sharding)
            for _ in range(3)
        )
        return fn, (q, k, v), lambda a, out: (out, a[1], a[2])

    if name == "ulysses":
        mesh = make_1d_mesh(n, "seq")
        fn, sharding = ulysses_attention_fn(mesh)
        t, h, d = 4 * n * scale, n, 8 * scale
        q, k, v = (
            jax.device_put(
                jax.random.normal(key, (t, h, d), jnp.float32), sharding
            )
            for _ in range(3)
        )
        return fn, (q, k, v), lambda a, out: (out, a[1], a[2])

    if name == "pipeline":
        mesh = make_1d_mesh(n, "stage")
        fn, w_sharding = pipeline_forward_fn(mesh)
        width, mb, n_micro = 8 * scale, 4 * scale, 2 * n
        stage_w = jax.device_put(
            jax.random.normal(key, (n, width, width), jnp.float32) * 0.5,
            w_sharding,
        )
        xs = jax.random.normal(key, (n_micro, mb, width), jnp.float32)
        return fn, (stage_w, xs), lambda a, out: (a[0], jnp.tanh(out))

    if name == "moe":
        mesh = make_1d_mesh(n, "expert")
        fn, w_sharding, x_sharding = moe_forward_fn(mesh)
        d = 8 * scale
        tokens = n * n * 2 * scale
        expert_w = jax.device_put(
            jax.random.normal(key, (n, d, d), jnp.float32) * 0.5, w_sharding
        )
        x = jax.device_put(
            jax.random.normal(key, (tokens, d), jnp.float32), x_sharding
        )
        return fn, (expert_w, x), lambda a, out: (a[0], out)

    if name == "fsdp":
        mesh = make_1d_mesh(n, "shard")
        fn, w_sharding = fsdp_step_fn(mesh)
        d = 2 * n * scale
        w = jax.device_put(
            jax.random.normal(key, (d, d), jnp.float32) * 0.3, w_sharding
        )
        x = jax.device_put(
            jax.random.normal(key, (4 * n, d), jnp.float32), w_sharding
        )
        y = jax.device_put(
            jax.random.normal(key, (4 * n, d), jnp.float32), w_sharding
        )
        return fn, (w, x, y), lambda a, out: (out[0], a[1], a[2])

    # multislice: 2 slices × n//2 chips (needs even n).
    if n % 2:
        raise ValueError("multislice needs an even device count")
    mesh = make_2d_mesh(2, n // 2)
    d = max(2 * scale, 2) * (n // 2)
    # lr scales with 1/d: the looped w <- step(w) feedback is plain
    # gradient descent on sum(y^2), which DIVERGES to NaN once
    # lr·λmax(2·xᵀx, psum'd over slices) exceeds 2; λmax for the (4, d)
    # normal x grows ~(√d+2)² ≈ d, so a FIXED lr that is stable at the
    # n=8 test shape (d=8) still NaNs on a 256-device pod or at --scale 20
    # (observed at lr=0.1 within ~100 steps; a fixed 0.005 just moves the
    # cliff to d≳150 — code-review r5). 0.04/d keeps ~10x margin at any d.
    fn, w_sharding, x_sharding = multislice_step_fn(mesh, lr=0.04 / d)
    w = jax.device_put(
        jax.random.normal(key, (d, d), jnp.float32) * 0.2, w_sharding
    )
    x = jax.device_put(
        jax.random.normal(key, (4, d), jnp.float32), x_sharding
    )
    return fn, (w, x), lambda a, out: (out[0], a[1])


def run_parallelism_dryrun(n_devices: int) -> dict[str, float]:
    """Compile + execute one step of each strategy on an n-device mesh with
    tiny shapes. Returns a finite checksum per strategy (the driver asserts
    non-NaN); used by ``__graft_entry__.dryrun_multichip``.

    Expressed ON TOP of :func:`build_parallel_program` — the dryrun
    verifies the exact programs the CLI loops, so mesh/shape/init config
    exists once and cannot drift between the two (code-review r5)."""
    import jax.numpy as jnp

    # Stable external key names (driver artifacts reference them).
    keys = {"ring": "ring_attention", "ulysses": "ulysses_attention",
            "multislice": "multislice_dp_tp"}
    results: dict[str, float] = {}
    for name in PARALLEL_PROGRAMS:
        if name == "multislice" and (n_devices < 4 or n_devices % 2):
            continue  # needs a 2 x n/2 mesh
        step, inputs, _feed = build_parallel_program(name, n_devices)
        out = step(*inputs)
        leaf = out[0] if isinstance(out, tuple) else out
        results[keys.get(name, name)] = float(jnp.sum(leaf))
    return results
