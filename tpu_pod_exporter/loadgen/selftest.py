"""Self-contained numeric verification of every loadgen parallelism program.

Runs the SP/PP/EP programs and the dp×tp sharded train step on an n-device
CPU mesh and compares each against its single-device ground truth, printing
ONE JSON line with per-check results. Designed to run inside a *sanitized*
child process (see ``tpu_pod_exporter.jaxenv``) so it works even when the
parent's JAX runtime is wedged by the experimental TPU-tunnel plugin:

    python -m tpu_pod_exporter.loadgen.selftest --n 8 --checks all

``__graft_entry__.dryrun_multichip`` runs ``--checks dryrun`` (compile +
execute only, the driver's gate); the test suite asserts on ``--checks
all`` numerics. Exit code 0 iff every requested check passed.

This is the seam the reference lacks entirely (zero tests — SURVEY.md §4);
the numeric-parity strategy follows §2.8: each parallelism strategy is
verified against a dense single-device reference before it is trusted as
an ICI-traffic instrument.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import traceback
from pathlib import Path


def run_subprocess(
    n_devices: int,
    checks: str = "dryrun",
    timeout: float = 300,
) -> subprocess.CompletedProcess:
    """Spawn this module as a sanitized child (see ``jaxenv``) and return
    the completed process. The single source of the spawn recipe — used by
    ``__graft_entry__.dryrun_multichip`` and the tests, so the env contract
    can't drift between the driver gate and the suite. Raises
    ``subprocess.TimeoutExpired`` (with captured output) on hang."""
    from tpu_pod_exporter.jaxenv import cpu_subprocess_env

    repo = Path(__file__).resolve().parents[2]
    env = cpu_subprocess_env(n_devices)
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable,
        "-m",
        "tpu_pod_exporter.loadgen.selftest",
        "--n",
        str(n_devices),
        "--checks",
        checks,
    ]
    return subprocess.run(
        cmd, cwd=repo, env=env, capture_output=True, text=True, timeout=timeout
    )


def _close(out, ref, rtol: float, atol: float) -> dict:
    """allclose verdict + max abs error, matching assert_allclose semantics
    (per-element bound atol + rtol*|ref|, not a flat absolute cutoff)."""
    import numpy as np

    out = np.asarray(out)
    ref = np.asarray(ref)
    return {
        "ok": bool(np.allclose(out, ref, rtol=rtol, atol=atol)),
        "max_abs_err": float(np.max(np.abs(out - ref))),
    }


def _pin_or_die(n: int) -> None:
    from tpu_pod_exporter.jaxenv import pin_cpu_inprocess

    if not pin_cpu_inprocess(n):
        print(
            json.dumps(
                {
                    "fatal": f"could not pin a {n}-device CPU mesh "
                    "(backends already initialized on a non-CPU platform?)"
                }
            )
        )
        raise SystemExit(3)


# --------------------------------------------------------------- checks

def check_dryrun_dp_tp(n: int) -> dict:
    from tpu_pod_exporter.loadgen.sharded import run_dryrun

    loss = run_dryrun(n, steps=1)
    return {"ok": loss == loss, "loss": loss}


def check_dryrun_parallelism(n: int) -> dict:
    from tpu_pod_exporter.loadgen.parallel import run_parallelism_dryrun

    results = run_parallelism_dryrun(n)
    ok = all(v == v for v in results.values())
    return {"ok": ok, **results}


def check_ring_attention(n: int) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh,
        reference_attention,
        ring_attention_fn,
    )

    mesh = make_1d_mesh(n, "seq")
    fn, sharding = ring_attention_fn(mesh)
    t, d = 4 * n, 16
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(k1, (t, d), jnp.float32)
    k = jax.random.normal(k2, (t, d), jnp.float32)
    v = jax.random.normal(k3, (t, d), jnp.float32)
    out = fn(*(jax.device_put(a, sharding) for a in (q, k, v)))
    return _close(out, reference_attention(q, k, v), rtol=2e-5, atol=2e-5)


def check_ring_attention_stability(n: int) -> dict:
    """Large score magnitudes exercise the running-max renormalization."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh,
        reference_attention,
        ring_attention_fn,
    )

    mesh = make_1d_mesh(n, "seq")
    fn, sharding = ring_attention_fn(mesh)
    t, d = 2 * n, 4
    q = 30.0 * jax.random.normal(jax.random.PRNGKey(0), (t, d), jnp.float32)
    k = 30.0 * jax.random.normal(jax.random.PRNGKey(1), (t, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (t, d), jnp.float32)
    out = np.asarray(fn(*(jax.device_put(a, sharding) for a in (q, k, v))))
    finite = bool(np.isfinite(out).all())
    res = _close(out, reference_attention(q, k, v), rtol=1e-4, atol=1e-4)
    return {**res, "ok": finite and res["ok"], "finite": finite}


def check_ulysses_attention(n: int) -> dict:
    """Ulysses head-swap SP vs exact multi-head attention: the two
    all_to_alls must be inverses and the per-head math exact."""
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh,
        reference_mha,
        ulysses_attention_fn,
    )

    mesh = make_1d_mesh(n, "seq")
    fn, sharding = ulysses_attention_fn(mesh)
    t, h, d = 4 * n, 2 * n, 16  # heads a strict multiple of devices
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(k1, (t, h, d), jnp.float32)
    k = jax.random.normal(k2, (t, h, d), jnp.float32)
    v = jax.random.normal(k3, (t, h, d), jnp.float32)
    out = fn(*(jax.device_put(a, sharding) for a in (q, k, v)))
    return _close(out, reference_mha(q, k, v), rtol=2e-5, atol=2e-5)


def check_pipeline(n: int) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh,
        pipeline_forward_fn,
        reference_pipeline,
    )

    mesh = make_1d_mesh(n, "stage")
    n_micro, mb, width = 2 * n, 4, 8
    fn, w_sharding = pipeline_forward_fn(mesh)
    stage_w = 0.5 * jax.random.normal(
        jax.random.PRNGKey(3), (n, width, width), jnp.float32
    )
    xs = jax.random.normal(jax.random.PRNGKey(4), (n_micro, mb, width), jnp.float32)
    out = fn(jax.device_put(stage_w, w_sharding), xs)
    return _close(out, reference_pipeline(stage_w, xs), rtol=2e-4, atol=2e-4)


def check_moe(n: int) -> dict:
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh,
        moe_forward_fn,
        reference_moe,
    )

    mesh = make_1d_mesh(n, "expert")
    fn, w_sharding, x_sharding = moe_forward_fn(mesh)
    d = 8
    tokens = n * n * 2
    expert_w = 0.5 * jax.random.normal(jax.random.PRNGKey(5), (n, d, d), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(6), (tokens, d), jnp.float32)
    out = fn(jax.device_put(expert_w, w_sharding), jax.device_put(x, x_sharding))
    return _close(out, reference_moe(expert_w, x), rtol=2e-4, atol=2e-4)


def check_fsdp(n: int) -> dict:
    """Sharded FSDP step (all_gather fwd / reduce_scatter bwd) must match
    the dense single-device SGD step."""
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        fsdp_step_fn,
        make_1d_mesh,
        reference_fsdp,
    )

    mesh = make_1d_mesh(n, "shard")
    fn, sharding = fsdp_step_fn(mesh)
    d, b = 2 * n, 4 * n
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(11), 3)
    w = 0.3 * jax.random.normal(k1, (d, d), jnp.float32)
    x = jax.random.normal(k2, (b, d), jnp.float32)
    y = jax.random.normal(k3, (b, d), jnp.float32)
    new_w, loss = fn(
        jax.device_put(w, sharding),
        jax.device_put(x, sharding),
        jax.device_put(y, sharding),
    )
    ref_w, ref_loss = reference_fsdp(w, x, y)
    res = _close(new_w, ref_w, rtol=2e-5, atol=2e-5)
    loss_err = abs(float(loss) - float(ref_loss))
    return {
        **res,
        "ok": res["ok"] and loss_err < 1e-5,
        "loss_abs_err": loss_err,
    }


def check_multislice(n: int) -> dict:
    """Cross-slice dp × intra-slice tp over a 2D mesh (2 slices × n/2)
    must match the dense single-device SGD step — validates the gradient
    psum over the DCN-class axis and the two-fabric loss reduction."""
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_2d_mesh,
        multislice_step_fn,
        reference_multislice,
    )

    if n < 4 or n % 2:
        # Same mesh-size guard as run_parallelism_dryrun: the 2×(n/2) mesh
        # needs an even device count, and d=2n must divide by tp=n/2.
        return {"ok": True, "skipped": f"needs even n>=4, got {n}"}
    mesh = make_2d_mesh(2, n // 2)
    fn, w_sharding, x_sharding = multislice_step_fn(mesh)
    d, b = 2 * n, 8
    k1, k2 = jax.random.split(jax.random.PRNGKey(13), 2)
    w = 0.3 * jax.random.normal(k1, (d, d), jnp.float32)
    x = jax.random.normal(k2, (b, d), jnp.float32)
    new_w, loss = fn(jax.device_put(w, w_sharding), jax.device_put(x, x_sharding))
    ref_w, ref_loss = reference_multislice(w, x)
    res = _close(new_w, ref_w, rtol=2e-4, atol=2e-4)
    loss_err = abs(float(loss) - float(ref_loss)) / max(abs(float(ref_loss)), 1e-9)
    return {
        **res,
        "ok": res["ok"] and loss_err < 1e-4,
        "loss_rel_err": loss_err,
    }


def check_sharded_descends(n: int) -> dict:
    """SGD on a fixed batch must strictly descend over 5 steps."""
    import numpy as np

    from tpu_pod_exporter.loadgen.sharded import make_mesh, sharded_train_step

    mesh = make_mesh(n)
    step, params, (x, y) = sharded_train_step(mesh, width=64, depth=2, batch=16)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    ok = bool(np.isfinite(losses).all()) and losses[-1] < losses[0]
    return {"ok": ok, "losses": losses}


def check_flagship(n: int) -> dict:
    import numpy as np

    from tpu_pod_exporter.loadgen.workload import flagship

    fn, (params, x) = flagship(width=64, depth=2, batch=8)
    out = np.asarray(fn(params, x)).astype(np.float32)
    ok = out.shape == (8, 64) and bool(np.isfinite(out).all())
    return {"ok": ok, "shape": list(out.shape)}


CHECKS = {
    "dryrun_dp_tp": check_dryrun_dp_tp,
    "dryrun_parallelism": check_dryrun_parallelism,
    "ring_attention": check_ring_attention,
    "ring_attention_stability": check_ring_attention_stability,
    "ulysses_attention": check_ulysses_attention,
    "pipeline": check_pipeline,
    "moe": check_moe,
    "fsdp": check_fsdp,
    "multislice": check_multislice,
    "sharded_descends": check_sharded_descends,
    "flagship": check_flagship,
}

# The driver's multichip gate: compile + execute every strategy, no
# reference numerics (they add single-device compiles and wall time).
DRYRUN_CHECKS = ("dryrun_dp_tp", "dryrun_parallelism")


def run_checks(n: int, names) -> dict:
    results: dict[str, dict] = {}
    for name in names:
        try:
            results[name] = CHECKS[name](n)
        except Exception as exc:  # noqa: BLE001 — reported, not swallowed
            results[name] = {
                "ok": False,
                "error": f"{type(exc).__name__}: {exc}",
                "traceback": traceback.format_exc(limit=5),
            }
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--n", type=int, default=8, help="mesh size")
    parser.add_argument(
        "--checks",
        default="all",
        help="'all', 'dryrun', or comma-separated check names",
    )
    args = parser.parse_args(argv)

    if args.checks == "all":
        names = list(CHECKS)
    elif args.checks == "dryrun":
        names = list(DRYRUN_CHECKS)
    else:
        names = [c.strip() for c in args.checks.split(",") if c.strip()]
        unknown = [c for c in names if c not in CHECKS]
        if unknown:
            print(json.dumps({"fatal": f"unknown checks: {unknown}"}))
            return 2

    _pin_or_die(args.n)
    results = run_checks(args.n, names)
    ok = all(r.get("ok") for r in results.values())
    print(json.dumps({"n_devices": args.n, "ok": ok, "checks": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
