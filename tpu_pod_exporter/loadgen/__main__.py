"""CLI load generator: drive MXU/HBM/ICI while an exporter watches.

Examples:
    python -m tpu_pod_exporter.loadgen --mode burn --seconds 30
    python -m tpu_pod_exporter.loadgen --mode hbm --gib 8 --seconds 60
    python -m tpu_pod_exporter.loadgen --mode sharded --devices 4 --seconds 30
    python -m tpu_pod_exporter.loadgen --mode parallel --program ulysses --seconds 30
"""

from __future__ import annotations

import argparse
import math
import sys
import time


def main(argv=None) -> int:
    # Cheap import: parallel.py has no top-level jax dependency, and
    # choices= makes a typo'd program name an instant argparse error
    # instead of a traceback after tens of seconds of TPU backend init.
    from tpu_pod_exporter.loadgen.parallel import PARALLEL_PROGRAMS

    p = argparse.ArgumentParser(prog="tpu-loadgen", description=__doc__)
    p.add_argument(
        "--mode", choices=("burn", "hbm", "sharded", "parallel"), default="burn"
    )
    p.add_argument(
        "--program", default="ring", choices=PARALLEL_PROGRAMS,
        help="parallel mode: which collective pattern to loop",
    )
    p.add_argument(
        "--scale", type=int, default=1,
        help="parallel mode: tensor-dimension multiplier (ICI bytes/step)",
    )
    p.add_argument("--seconds", type=float, default=10.0)
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--iters", type=int, default=10, help="forward passes per step (burn)")
    p.add_argument("--gib", type=float, default=1.0, help="HBM to hold (hbm mode)")
    p.add_argument("--devices", type=int, default=0, help="mesh size (sharded); 0=all")
    args = p.parse_args(argv)

    import jax

    # Modes set their own deadline AFTER the warm-up compile — jit compile
    # (20-40 s first time on TPU) must not eat the measurement budget.
    deadline = time.monotonic() + args.seconds
    steps = 0

    if args.mode == "hbm":
        from tpu_pod_exporter.loadgen.workload import hbm_fill

        buf = hbm_fill(int(args.gib * 1024**3))
        print(f"holding {buf.nbytes / 1024**3:.2f} GiB on {next(iter(buf.devices()))}")
        while time.monotonic() < deadline:
            time.sleep(0.5)
        del buf
        return 0

    if args.mode == "burn":
        import jax.numpy as jnp

        from tpu_pod_exporter.loadgen.workload import burn_step, init_params

        params = init_params(width=args.width, depth=args.depth)
        x = jnp.ones((args.batch, args.width), jnp.bfloat16)
        burn_step(params, x, iters=args.iters).block_until_ready()  # compile
        t0 = time.monotonic()
        deadline = t0 + args.seconds
        while time.monotonic() < deadline:
            # Feed the output back in: a real data dependency per step, so
            # no runtime can elide or memoize repeated identical executions.
            x = burn_step(params, x, iters=args.iters)
            # Host readback of one element — the only sync some experimental
            # runtimes honor (block_until_ready can be a no-op over tunnels).
            float(x[0, 0])
            steps += 1
        dt = time.monotonic() - t0
        flops = 2 * args.batch * args.width * args.width * args.depth * args.iters * steps
        print(f"{steps} steps in {dt:.1f}s → {flops / dt / 1e12:.2f} TFLOP/s")
        return 0

    if args.mode == "parallel":
        import jax.numpy as jnp

        from tpu_pod_exporter.loadgen.parallel import build_parallel_program

        n = args.devices or len(jax.devices())
        step, inputs, feed = build_parallel_program(
            args.program, n, scale=args.scale
        )
        out = step(*inputs)  # compile
        jax.block_until_ready(out)
        t0 = time.monotonic()
        deadline = t0 + args.seconds
        while time.monotonic() < deadline:
            out = step(*inputs)
            inputs = feed(inputs, out)
            # Host readback — the sync some experimental runtimes honor
            # (see burn mode); also catches a NaN'd feedback loop early.
            leaf = out[0] if isinstance(out, tuple) else out
            probe = float(jnp.ravel(leaf)[0])
            # Divergence check, not just NaN: a feedback loop that blows up
            # usually passes through ±inf on the way, and `x != x` only
            # catches NaN — abort on any non-finite probe (advisor r5).
            if not math.isfinite(probe):
                print(f"non-finite probe ({probe}) after {steps} steps",
                      file=sys.stderr)
                return 1
            steps += 1
        dt = time.monotonic() - t0
        print(
            f"{args.program} x{args.scale} on {n} devices: "
            f"{steps} steps in {dt:.1f}s → {steps / dt:.1f} steps/s"
        )
        return 0

    # sharded
    from tpu_pod_exporter.loadgen.sharded import make_mesh, sharded_train_step

    n = args.devices or len(jax.devices())
    mesh = make_mesh(n)
    step, params, (x, y) = sharded_train_step(
        mesh, width=args.width, depth=args.depth, batch=args.batch
    )
    params, loss = step(params, x, y)  # compile
    loss.block_until_ready()
    t0 = time.monotonic()
    deadline = t0 + args.seconds
    while time.monotonic() < deadline:
        params, loss = step(params, x, y)
        # Serialize executions: concurrent in-flight collective programs can
        # interleave their rendezvous on oversubscribed (virtual CPU) meshes.
        loss.block_until_ready()
        steps += 1
    dt = time.monotonic() - t0
    print(f"mesh {dict(mesh.shape)} | {steps} steps in {dt:.1f}s | loss {float(loss):.5f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
