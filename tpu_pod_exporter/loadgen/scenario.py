"""Fleet scenario engine — invariant-checked end-to-end drills.

``python -m tpu_pod_exporter.loadgen.scenario`` (``make scenario-demo``) is
the acceptance harness the ROADMAP names for everything built since PR 6:
it stands up the FULL simulated stack —

    SynthTargetFarm (node tier, real HTTP)
      → real LeafAggregator HA pairs (per-shard breakers, state dirs)
        → real RootAggregator (+ /readyz HTTP server, RootQueryPlane)
          → RemoteWriteShipper egress → ChaosReceiver (exactly-once ledger)

— and drives the named scenario timelines from
:mod:`tpu_pod_exporter.scenario` against it, with **invariants asserted at
every tick**, not just at checkpoints:

1. **zero acked-sample loss through egress** — the receiver's ledger must
   end contiguous and duplicate-free for every batch the shipper framed;
2. **bounded staleness per tier** — reachable leaves stay fresh; stale-
   served leaves age monotonically within the --stale-serve-s budget;
3. **root == oracle** rollup equality (flat single-aggregator oracle over
   the same targets file) on every quiet round outside injected windows;
4. **no series/RSS leaks** — the exposition returns to exactly the
   expected series set after churn, and RSS growth stays bounded;
5. **exposition-attributable faults** — every injected fault must be
   readable from the root's exposition alone: partitioned leaves show
   ``leaf_up 0`` + ``stale_served 1`` (+ ``partition_suspected 1`` when
   the HA twin still answers), preempted/restarting targets show
   ``target_up 0``, hotspots dominate the workload rollups, receiver
   outages open the egress breaker with a visible backlog.

Partitions are injected at the HTTP fetch seam via
``chaos.PartitionState``/``PartitionedFetch``/``PartitionedSend`` — the
same wrapper composes over leaf scrape, root scrape, the two-level query
fan-out, and egress send — so asymmetric and flapping cuts exercise every
tier with one mechanism. Deterministic under ``--seed``: event rounds are
fixed by the DSL, flap phases are seeded, and farm telemetry is a pure
function of (target, round).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

from tpu_pod_exporter import utils as _utils
from tpu_pod_exporter.chaos import (
    ChaosReceiver,
    ClockStepper,
    PartitionState,
    PartitionedFetch,
    PartitionedSend,
    ScrapeStorm,
)
from tpu_pod_exporter.pressure import PressureGovernor, dir_usage_bytes
from tpu_pod_exporter.loadgen.fleet import (
    _ShardSim,
    _compare_oracle,
    _family_values,
)
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.metrics.parse import parse_families
from tpu_pod_exporter.scenario import (
    DEFAULT_SCENARIO_ORDER,
    INVARIANTS,
    SCENARIOS,
    Scenario,
    ScenarioEvent,
    total_rounds,
)

# Wall-clock staleness slack for "fresh" tiers: the drills run subsecond
# rounds, so anything beyond this means a tier silently stopped merging.
FRESH_STALENESS_BUDGET_S = 8.0

# INVARIANTS (imported above, re-exported here) names the engine's
# invariant families; _Run tracks which were actually ARMED per run — a
# fuzz trial only counts coverage for invariants that could have failed
# it.
__all__ = ["INVARIANTS", "run_one", "run_scenarios", "main"]

# The alert drills' rule set (tpu_pod_exporter.alerting grammar). Both
# rules fire IMMEDIATELY (no `for` clause): engine rounds are subsecond
# and wall-time pendings would make the fired-set assertion timing-
# dependent. Determinism instead comes from the stack itself — partition
# suspicion latches in the SAME merge round a leaf drops with a
# reachable twin (shard.py stale-serve), so under suppression
# TpuRootLeafDown is held down from the first cut round and only the
# partition alert ever fires.
ALERT_DRILL_RULES = """\
alert TpuRootLeafPartitioned = tpu_root_leaf_partition_suspected == 1
    labels(severity="page", drill="scenario")
    annotations(summary="leaf {{ $labels.leaf }} one-sided-unreachable (twin vouches for the pods)")

alert TpuRootLeafDown = tpu_root_leaf_up == 0
    suppress(tpu_root_leaf_partition_suspected == 1)
    labels(severity="page", drill="scenario")
    annotations(summary="leaf {{ $labels.leaf }} unreachable and nothing vouches for it")
"""


def _get_json(url: str, timeout_s: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — loopback harness
        return json.loads(resp.read())


class _Run:
    """One scenario against one freshly-built stack."""

    # Admission caps on the root's serving tier while the governor is on
    # (the scrape_storm drill's bound; generous for every other scenario).
    STORM_CONN_CAP = 32
    STORM_CLIENT_CAP = 8
    # Store tiers scaled to paced drill rounds (scenario.round_pause_s
    # keeps one finest bucket finalizing per round).
    STORE_TIERS = "0.25:600,2.5:600"
    STORE_FINEST_STEP = 0.25

    # Per-round push-latency p99 budget for the dashboard_storm drill
    # (frame emission ts → in-process subscriber receipt; generous for
    # shared CI runners — the demo harness measures the real number).
    DASH_PUSH_P99_BUDGET_S = 2.5

    def __init__(self, scn: Scenario, n_targets: int, shards: int,
                 chips: int, state_root: str, seed: int,
                 stale_serve_s: float = 30.0,
                 governor: bool = True, store: bool = True,
                 stream: bool = True,
                 alert_suppression: bool = True) -> None:
        from tpu_pod_exporter.egress import (
            RemoteWriteShipper,
            aggregator_egress_metrics,
            build_breaker,
            default_send,
        )
        from tpu_pod_exporter.server import MetricsServer

        self.scn = scn
        self.events = scn.events()
        self.rounds = total_rounds(self.events, scn.settle_rounds)
        self.state_root = state_root
        os.makedirs(state_root, exist_ok=True)
        self.net = PartitionState(seed=seed)
        self.stale_serve_s = stale_serve_s
        # Fleet TSDB-lite under the root (store_continuity drill): tiers
        # scaled to subsecond drill rounds, one recording rule so the
        # rule-backed-query half of the invariant is exercised. --store
        # off is the drill's NEGATIVE CONTROL: the continuity invariant
        # still runs and must fail on the boundary gap.
        self.store = None
        self.store_on = store and scn.uses_store
        self.store_dir = os.path.join(state_root, "store")
        # Breaker backoffs scaled to subsecond drill rounds (production
        # defaults are tens of seconds): a healed partition's quarantined
        # targets must be re-admitted within the settle budget — the
        # quarantine-vs-partition disambiguation half of the drill.
        self.sim = _ShardSim(
            n_targets, shards, True, chips, state_root,
            timeout_s=3.0, net=self.net, stale_serve_s=stale_serve_s,
            leaf_breaker_backoff_s=0.4, leaf_breaker_backoff_max_s=0.8,
            root_breaker_backoff_s=0.4, root_breaker_backoff_max_s=0.8,
            n_slices=4, query_plane=True,
            store_factory=self._make_store if self.store_on else None,
            gpu_slices=scn.gpu_slices,
        )
        self.membership: list[str] = list(self.sim.farm.targets())
        # Root /readyz over real HTTP: partition-aware degradation is an
        # operator contract, so it is asserted through the wire. With the
        # governor on, the serving tier also carries the admission caps
        # the scrape_storm drill storms against. Hooks dereference
        # self.sim.root LATE (lambdas): a root_restart event swaps the
        # root instance mid-run.
        self.governor_on = governor
        # Streaming dashboard hub (dashboard_storm drill): answers
        # through self.plane via the late-deref poll below, so a
        # root_restart's plane rebuild is transparent to live streams.
        # --stream off is the drill's NEGATIVE CONTROL: subscriptions
        # 404 and the drill must fail.
        self.stream_on = stream
        self.hub = None
        if stream:
            from tpu_pod_exporter.stream import StreamHub

            self.hub = StreamHub(
                self._stream_poll, lambda: self.sim.root.rounds,
                heartbeat_s=2.0, full_sync_s=6.0, max_subscribers=4096,
            )
        # Admission caps: the scrape_storm drill's tight bounds, EXCEPT
        # when this scenario holds a dashboard storm — viewers are the
        # workload there, and all its in-process subscribers share one
        # source IP (the hub's subscriber cap is their admission story).
        conn_cap = self.STORM_CONN_CAP if governor else 0
        client_cap = self.STORM_CLIENT_CAP if governor else 0
        dash_counts = [ev.count for ev in self.events
                       if ev.kind == "dashboard_storm"]
        if dash_counts and governor:
            conn_cap = max(conn_cap, 2 * max(dash_counts) + 16)
            client_cap = 0
        # The EFFECTIVE cap, saved for the storm invariant: a composed
        # dashboard storm raises it above STORM_CONN_CAP, and a generated
        # storm smaller than it legitimately draws zero 429s (the fuzzer's
        # sub-cap scrape_storm find — the old check hardcoded the class
        # constant and demanded rejections from ANY storm).
        self.conn_cap = conn_cap
        self.root_server = MetricsServer(
            self.sim.root_store, host="127.0.0.1", port=0,
            ready_detail_fn=lambda: self.sim.root.ready_detail(),
            max_open_connections=conn_cap,
            max_requests_per_client=client_cap,
            stream_hub=self.hub,
        )
        self.root_server.start()
        # Two-level query plane, partitioned at the root→leaf seam.
        port_to_leaf = dict(self.sim.leaf_addr_of)

        def _leaf_of_url(url: str) -> str:
            try:
                hostport = url.split("/", 3)[2]
            except IndexError:
                hostport = ""
            return port_to_leaf.get(hostport, "leaf:?")

        self._leaf_of_url = _leaf_of_url
        self.plane = None
        self._build_planes()
        # Egress: the root's rollups ship to a ChaosReceiver through a
        # partitionable sender; the ledger is the zero-loss oracle.
        self.receiver = None
        self.shipper = None
        self.egress_dir = os.path.join(state_root, "egress")
        # Wall clock the clock_step events step: the shipper ages its
        # backlog against it, so the fence (this-process batches age
        # monotonically) is exercised through a real component.
        self.clock = ClockStepper()
        if scn.uses_egress:
            self.receiver = ChaosReceiver([], seed=seed)
            self.receiver.start()
            self.shipper = RemoteWriteShipper(
                self.receiver.url,
                self.egress_dir,
                metrics=aggregator_egress_metrics(),
                interval_s=0.0,
                timeout_s=2.0,
                breaker=build_breaker(2, 0.3, 1.5),
                extra_labels={"host": "scenario-root"},
                send=PartitionedSend(self.net, "root", "recv", default_send),
                wallclock=self.clock,
            )
            self.shipper.load()
            self.shipper.start()
        # Native alerting plane (alert drills): an in-root AlertEvaluator
        # over the drill rule set, its webhook notifier backed by the
        # same WAL + breaker discipline as egress. The send callable IS
        # the ledger oracle (contiguous seqs = exactly-once), and a
        # recv_outage event wedges it alongside the remote-write
        # receiver so the backlog/drain path is exercised by a fault the
        # engine already injects. suppression=False is the fired-set
        # assertion's NEGATIVE CONTROL (--alert-suppression off).
        self.alert_eval = None
        self.alert_notifier = None
        self.alert_suppression = alert_suppression
        self._alert_outage = False
        self._alert_ledger_lock = threading.Lock()
        self.alert_ledger: list[int] = []
        self.alert_notes: list[dict] = []
        if scn.expected_alerts is not None:
            from tpu_pod_exporter.alerting import (
                SEQ_HEADER,
                AlertEvaluator,
                AlertNotifier,
                parse_alert_rules,
            )

            alert_dir = os.path.join(state_root, "alerts")

            def _alert_send(url: str, body: bytes, headers: dict,
                            timeout_s: float) -> int:
                if self._alert_outage:
                    raise urllib.error.URLError(
                        "drill: alert receiver outage")
                seq = int(headers.get(SEQ_HEADER, "0") or 0)
                with self._alert_ledger_lock:
                    self.alert_ledger.append(seq)
                    self.alert_notes.append(json.loads(body))
                return 200

            self.alert_notifier = AlertNotifier(
                "http://alert-recv.invalid/hook", alert_dir,
                breaker=build_breaker(2, 0.1, 0.8),
                send=_alert_send,
            )
            self.alert_notifier.load()
            self.alert_notifier.start()
            self.alert_eval = AlertEvaluator(
                parse_alert_rules(ALERT_DRILL_RULES),
                alert_dir=alert_dir,
                notifier=self.alert_notifier,
                store=self.store,
                suppression=alert_suppression,
            )
        # Resource-pressure governor over the root-side stack: the disk
        # ladder watches the egress dir (segment compaction rung), the
        # memory ladder the byte-accounted caches (leaf fleet caches
        # first, root stale-serve views second — coarse data last).
        # Budgets start at 0 (no pressure); the disk_full / mem_pressure
        # events squeeze them mid-run. Ticked synchronously per round —
        # deterministic, no governor thread in the engine.
        self.gov: PressureGovernor | None = None
        if governor:
            self.gov = PressureGovernor(
                check_interval_s=0.05, hysteresis_s=0.3)
            if self.shipper is not None:
                self.gov.add_disk_path(self.egress_dir)
                self.gov.add_disk_rung(
                    "egress_compact",
                    lambda: self.shipper.set_disk_pressure(True),
                    lambda: self.shipper.set_disk_pressure(False),
                )
            if self.store is not None:
                # store_thin AFTER egress compaction (acked egress bytes
                # are free to reclaim; store buckets are answerable
                # history) — coarse store tiers shed never. The getter
                # dereferences self.store late: root_restart swaps the
                # instance (which re-applies the pressure hook, see
                # _make_store). One wiring path with production
                # (pressure.register_store_rungs), not a hand-rolled twin.
                from tpu_pod_exporter.pressure import register_store_rungs

                register_store_rungs(self.gov, self.store,
                                     store_fn=lambda: self.store)
            self.gov.register_memory_component(
                "fleet_caches", self._leaf_cache_bytes)
            self.gov.register_memory_component(
                "stale_views",
                # Late deref, like every root hook: a root_restart swaps
                # the instance, and accounting a dead root's frozen views
                # would make the shed rung free nothing measurable.
                lambda: self.sim.root.stale_view_bytes())
            self.gov.add_memory_rung(
                "fleet_cache",
                lambda: self._set_leaf_caches(False),
                lambda: self._set_leaf_caches(True),
            )
            self.gov.add_memory_rung(
                "stale_views",
                lambda: self.sim.root.shed_stale_views(),
                lambda: None,
            )
            if self.hub is not None:
                # Viewers shed LAST among the cheap rungs: dropping a
                # cache costs a re-fan-out; dropping a subscription costs
                # a viewer (who must reconnect against a replica).
                from tpu_pod_exporter.pressure import register_stream_rung

                register_stream_rung(self.gov, self.hub)
        # Pressure-drill state.
        self.disk_usage_at_squeeze = 0
        self.disk_budget_target = 0
        self.disk_batch_est = 4096
        self.mem_budget_target = 0
        # Accounted memory at the last verified quiet round: the WARM
        # steady state. A mem_pressure window that opens right after a
        # root restart would otherwise derive its budget from a cold
        # cache (fuzzer find: root_restart()@2; mem_pressure()@3+2 set
        # an unmeetable budget the legitimate warm-up then breached).
        self.mem_accounted_baseline = 0
        self.storm: ScrapeStorm | None = None
        self.storm_baseline_p99: float | None = None
        self.storm_p99s: list[float] = []
        # dashboard_storm state: the subscriber harness plus running
        # equality/latency tallies (verdict rendered in _finish).
        self.dash = None
        self.dash_eq_checks = 0
        self.dash_eq_failures = 0
        self.dash_push_p99s: list[float] = []
        self.dash_totals: dict = {}
        self._polite_conn = None  # lazy http.client keep-alive connection
        self.baseline_series: set | None = None
        self.baseline_workloads = 0
        self.rss_baseline: float | None = None
        # Targets healed from an injected outage but possibly still
        # quarantined leaf-side; they must come back before the run ends.
        self.recovering: set[str] = set()
        # Targets seen healthy ONCE since their fault ended; pruned from
        # `recovering` only on a second consecutive healthy check. The
        # HA freshest-wins merge can flap a just-revived target back to
        # down for one round under load — one healthy sighting is not
        # yet recovery (fuzzer find, load-dependent).
        self._recovered_once: set[str] = set()
        # Same for leaves after a root-leaf cut heals: the root's leaf
        # breaker holds its quarantine until the next half-open probe —
        # bounded by the settle loop, not an instant flip.
        self.recovering_leaves: set[str] = set()
        self.restart_batches: dict[int, tuple[int, ...]] = {}
        # mixed_wedge parity bookkeeping: per-wedge degradation signature
        # ({family, victims, down, chip drop, other-family drift,
        # quarantined}) captured at each preempt window's last round; the
        # finish asserts the TPU and GPU signatures are identical in kind.
        self.wedge_sigs: list[dict] = []
        self._wedge_chips_before: dict[str, float] = {}
        # store_continuity boundary stamps (root_restart event hooks).
        self.start_wall = 0.0
        self.kill_wall = 0.0
        self.restart_wall = 0.0
        self.trace: list[dict] = []
        self.problems: list[str] = []
        # Which invariant families this run can actually fail on — the
        # fuzzer's coverage ledger records (seam × invariant) only for
        # armed invariants, so a store-off run never claims ledger
        # coverage it didn't buy. oracle_equality arms lazily, on the
        # first compare that actually executes.
        self.invariants_armed: set[str] = {
            "bounded_staleness", "fault_attribution", "series_rss_leaks",
        }
        if self.shipper is not None:
            self.invariants_armed.add("egress_ledger")
        if self.alert_eval is not None:
            self.invariants_armed.add("alerts_correctness")

    # --------------------------------------------------------- store helpers

    def _make_store(self):
        """FleetStore factory handed to _ShardSim: called at boot AND by
        restart_root — the fresh instance replays the same dir, which IS
        the continuity under test."""
        from tpu_pod_exporter.store import FleetStore, parse_rules

        rules = parse_rules(
            "scenario:hbm:by_slice = sum("
            + schema.TPU_SLICE_HBM_USED_BYTES.name + ") by (slice_name)\n"
            # Per-family aggregation through the rule plane: mixed fleets
            # precompute the family split the same way the drills read it.
            "scenario:chips:by_family = sum("
            + schema.TPU_SLICE_CHIP_COUNT.name + ") by (family)\n")
        s = FleetStore(self.store_dir, tiers=self.STORE_TIERS, rules=rules)
        s.open()
        # Hooks and held rung state live on the instance: a restart-
        # swapped store must rejoin the governor's ENOSPC fault window
        # AND re-apply a held store_thin rung (register_store_rungs
        # wired the first instance; the getter covers the rung
        # callbacks, this covers per-instance state — the documented
        # store_fn contract).
        gov = getattr(self, "gov", None)
        if gov is not None:
            s.set_pressure_hook(gov.report_io_error)
            gs = gov.stats()["disk"]
            if "store_thin" in gs["rungs"][:gs["level"]]:
                s.set_thin(True)
        self.store = s
        return s

    def _build_planes(self) -> None:
        """(Re)build the two-level query plane — and its store-backed
        front when a store is attached. Called at boot and after a
        root_restart (the fresh root owns fresh leaf breakers and a fresh
        store instance)."""
        from tpu_pod_exporter.shard import RootQueryPlane

        if self.plane is not None:
            try:
                self.plane.close()
            except Exception:  # noqa: BLE001 — rebuild must proceed
                pass
        from tpu_pod_exporter.fleet import default_api_fetch

        def _plain_api(url: str, timeout_s: float) -> dict:
            return default_api_fetch(url, timeout_s)

        inner = RootQueryPlane(
            self.sim.topology, timeout_s=2.5,
            fetch=PartitionedFetch(self.net, "root", self._leaf_of_url,
                                   _plain_api),
            leaf_breakers=self.sim.root._breakers,
        )
        if self.store is not None:
            from tpu_pod_exporter.store import StoreQueryPlane

            self.plane = StoreQueryPlane(inner, self.store)
        else:
            self.plane = inner
        if self.hub is not None and (
                self.hub.emit not in self.sim.root.emit_hooks):
            # The tpu_stream_* surface rides the root's publish; a
            # root_restart's fresh root needs the hook re-attached.
            self.sim.root.emit_hooks.append(self.hub.emit)

    # --------------------------------------------------------- stream helpers

    def _stream_poll(self, shape, generation):
        """The hub's poll_fn: answers through the CURRENT query plane
        (late deref — root_restart rebuilds self.plane mid-run and live
        streams must follow the fresh instance)."""
        from tpu_pod_exporter.stream import plane_poll_fn

        ev = getattr(self, "alert_eval", None)
        return plane_poll_fn(
            self.plane,
            alerts_fn=ev.rows if ev is not None else None,
        )(shape, generation)

    def _dash_shapes(self):
        from tpu_pod_exporter.stream import QueryShape

        # One panel per farm slice plus a fleet-wide one — a handful of
        # shapes shared by many subscribers, the dashboard's real shape.
        return [
            QueryShape(route="window_stats", metric="tpu_hbm_used_bytes",
                       match=(("slice_name", f"slice-{i}"),), window_s=30.0)
            for i in range(4)
        ] + [QueryShape(route="window_stats",
                        metric="tpu_hbm_used_bytes", window_s=30.0)]

    # ------------------------------------------------------- pressure helpers

    def _leaf_cache_bytes(self) -> int:
        """Summed leaf fleet-cache byte estimates — the memory ladder's
        first component (live dict walk: leaves restart/replace)."""
        total = 0
        for leaf in self.sim.leaves.values():
            if leaf.fleet is not None:
                total += leaf.fleet.cache_bytes()
        return total

    def _set_leaf_caches(self, enabled: bool) -> None:
        for leaf in self.sim.leaves.values():
            if leaf.fleet is not None:
                leaf.fleet.set_cache_enabled(enabled)

    def _accounted_memory(self) -> int:
        """The memory invariant's number, computed directly so the
        governor-off negative control measures the same thing."""
        return self._leaf_cache_bytes() + self.sim.root.stale_view_bytes()

    def _polite_p99(self, n: int) -> float:
        """Latency of a polite scraper against the root's /metrics: ONE
        long-lived keep-alive connection (established before any storm —
        the incumbent-scraper shape admission control protects; its
        source is 127.0.0.1, distinct from the storm's 127.0.0.N pool)."""
        import http.client

        if self._polite_conn is None:
            self._polite_conn = http.client.HTTPConnection(
                "127.0.0.1", self.root_server.port, timeout=10)
        lat: list[float] = []
        for _ in range(n):
            t0 = time.perf_counter()
            self._polite_conn.request("GET", "/metrics")
            resp = self._polite_conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise RuntimeError(f"polite scrape got {resp.status}")
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return lat[min(int(n * 0.99), n - 1)]

    # ------------------------------------------------------------ event hooks

    def _leaf_cut_edges(self, ev: ScenarioEvent) -> list[tuple[str, str]]:
        pair = frozenset(ev.edge or ())
        if pair == frozenset({"leaf", "root"}):
            if ev.mode == "asymmetric":
                return [("root", f"leaf:{name}")
                        for name in self.sim.leaves if name.endswith("a")]
            return [("root", "leaf")]
        if pair == frozenset({"node", "leaf"}):
            if ev.mode == "asymmetric":
                return [(f"leaf:{name}", "node")
                        for name in self.sim.leaves if name.endswith("a")]
            return [("leaf", "node")]
        return [("root", "recv")]

    def _member_indices(self) -> set[int]:
        return {self._idx_of(t) for t in self.membership}

    @staticmethod
    def _idx_of(target: str) -> int:
        try:
            parts = target.split("/")
            return int(parts[parts.index("t") + 1])
        except (ValueError, IndexError):
            return -1

    def _start_event(self, ev: ScenarioEvent) -> None:
        farm = self.sim.farm
        if ev.kind == "partition":
            for src, dst in self._leaf_cut_edges(ev):
                self.net.cut(src, dst, flapping=ev.mode == "flapping")
        elif ev.kind == "preempt":
            sl = int(ev.subject.rsplit("-", 1)[1])
            victims = [i for i in farm.slice_targets(sl)
                       if i in self._member_indices()]
            ev_state = set(victims)
            # Pre-wedge family chip counts, from the root's CURRENT body
            # (last round's publish — the wedge has not bitten yet): the
            # per-family drop baseline for the mixed-wedge parity check.
            self._wedge_chips_before = {
                s.labels.get("family", "?"): s.value
                for s in parse_families(self.sim.root_body()).get(
                    schema.TPU_FLEET_FAMILY_CHIP_COUNT.name, ())
            }
            farm.dead |= ev_state
            self._preempt_victims = ev_state
        elif ev.kind == "hotspot":
            self._resolve_hotspot(ev)
        elif ev.kind == "restart_wave":
            live = sorted(
                i for i in self._member_indices() if i not in farm.dead
            )[:ev.count]
            self.restart_batches = {
                ev.at_round + j: tuple(live[j * ev.stagger:(j + 1) * ev.stagger])
                for j in range(ev.duration)
            }
        elif ev.kind == "recv_outage":
            if self.receiver is not None:
                self.receiver.set_outage(True)
            # The alert webhook lives on the receiver tier too: its
            # notifier must wedge (breaker open, WAL backlog) alongside
            # the remote-write shipper and drain exactly-once after heal.
            self._alert_outage = True
        elif ev.kind == "disk_full":
            # Squeeze the disk budget to half the CURRENT usage: a breach
            # is guaranteed whatever the absolute batch sizes are, and the
            # per-batch estimate anchors the post-shed floor (steady state
            # after compaction is O(one segment + one batch), never an
            # arbitrary fraction of an arbitrary budget).
            usage = dir_usage_bytes(self.egress_dir)
            enq = 1
            if self.shipper is not None:
                enq = max(self.shipper.stats()["enqueued_batches"], 1)
            self.disk_usage_at_squeeze = usage
            self.disk_batch_est = max(usage // enq, 2048)
            self.disk_budget_target = max(usage // 2, 1024)
            if self.gov is not None:
                self.gov.set_disk_budget_bytes(self.disk_budget_target)
        elif ev.kind == "mem_pressure":
            # Budget = WARM accounted + one small delta: the query
            # traffic the window drives adds far more than the delta, so
            # governor-off breaches deterministically while governor-on
            # (caches cleared + disabled) stays under. The quiet-round
            # baseline floors the reference — sampling a cold cache
            # right after a root restart would set a budget the
            # legitimate warm-up alone breaches.
            self.mem_budget_target = max(
                self._accounted_memory(), self.mem_accounted_baseline
            ) + 2048
            if self.gov is not None:
                self.gov.set_memory_budget_bytes(self.mem_budget_target)
        elif ev.kind == "scrape_storm":
            try:
                self.storm_baseline_p99 = self._polite_p99(12)
            except (OSError, RuntimeError) as e:
                self.problems.append(
                    f"polite scraper failed BEFORE the storm: {e}")
                self.storm_baseline_p99 = None
            self.storm_p99s = []
            self.storm = ScrapeStorm(
                "127.0.0.1", self.root_server.port, conns=ev.count,
                pause_s=0.02)
            self.storm.start()
        elif ev.kind == "clock_step":
            self.clock.step(ev.step_s)
        elif ev.kind == "root_restart":
            # SIGKILL-shaped: the serving tier keeps answering the stale
            # snapshot (real kubelet gap), leaves keep polling, the store
            # stops appending — the dead window the store must later fill.
            self.kill_wall = time.time()
            self.sim.kill_root()
        elif ev.kind == "dashboard_storm":
            from tpu_pod_exporter.loadgen.fleet import _StormSubscribers

            self.dash = _StormSubscribers(workers=2)
            self.dash.set_endpoints(
                [("root", ("127.0.0.1", self.root_server.port))])
            self.dash.open(ev.count, self._dash_shapes())
            # With --stream off this wait times out (every subscribe
            # 404s) and the tick invariant below fails the run — the
            # negative control's whole point.
            self.dash.wait_snapshots(ev.count, timeout_s=10.0)

    def _end_event(self, ev: ScenarioEvent) -> None:
        farm = self.sim.farm
        if ev.kind == "partition":
            for src, dst in self._leaf_cut_edges(ev):
                self.net.heal(src, dst)
                if src == "root" and dst.startswith("leaf"):
                    if dst == "leaf":
                        self.recovering_leaves.update(self.sim.leaves)
                    else:
                        self.recovering_leaves.add(dst.split(":", 1)[1])
                if dst == "node" or src == "node":
                    # A healed node-tier cut leaves every member target's
                    # scrape breaker open until its next probe; that
                    # post-heal darkness is attributable to THIS cut, not
                    # an unexplained outage (fuzzer find: a bare
                    # node<->leaf window flagged 18 targets "down without
                    # an injected fault" one round after heal). Over-
                    # marking is self-limiting — recovery pruning drops
                    # any target the moment it is seen healthy.
                    self.recovering |= {
                        farm.url(i) for i in self._member_indices()}
        elif ev.kind == "preempt":
            victims = getattr(self, "_preempt_victims", set())
            farm.dead -= victims
            self.recovering |= {farm.url(i) for i in victims}
        elif ev.kind == "restart_wave":
            # The final batch's hosts come back when the window closes
            # (earlier batches revive on the next wave tick).
            last = set(self.restart_batches.get(ev.end_round - 1, ()))
            farm.dead -= last
            self.recovering |= {farm.url(i) for i in last}
        elif ev.kind == "hotspot":
            farm.hot = set()
        elif ev.kind == "recv_outage":
            if self.receiver is not None:
                self.receiver.set_outage(False)
            self._alert_outage = False
        elif ev.kind == "disk_full":
            # The operator freed space / raised the budget: pressure off,
            # and the settle loop must see the ladder recover to 0.
            if self.gov is not None:
                self.gov.set_disk_budget_bytes(0)
        elif ev.kind == "mem_pressure":
            if self.gov is not None:
                self.gov.set_memory_budget_bytes(0)
        elif ev.kind == "scrape_storm":
            if self.storm is not None:
                self.storm.stop()
        elif ev.kind == "root_restart":
            # Fresh root; with a store factory the fresh FleetStore
            # replays the same dir — planes rebuild onto the new
            # instances (breakers + store identity changed).
            self.sim.restart_root()
            self.restart_wall = time.time()
            self._build_planes()
        elif ev.kind == "dashboard_storm":
            if self.dash is not None:
                self.dash_totals = self.dash.totals()
                self.dash.stop()
                self.dash = None

    def _excused_losses(self, lost: set) -> set:
        """The subset of lost series attributable to targets down or
        recovering from OTHER injected faults. The partition-retention
        invariant must not claim a preempted slice's rollups as
        partition damage (fuzzer find: preempt recovery overlapping a
        dead-root window and a flapping cut — the frozen body still
        lacked the victims' series, and only the partition was left
        standing to blame). Excusal keys on the down targets' slice,
        pod, and URL labels; series of healthy targets stay covered."""
        farm = self.sim.farm
        down = set(farm.dead)
        down |= {self._idx_of(u) for u in self.recovering}
        if not down:
            return set()
        slices = {f"slice-{i % farm.n_slices}" for i in down}
        pods = {farm.pod_of(i) for i in down}
        urls = {farm.url(i) for i in down}
        excused = set()
        for name, labels in lost:
            lab = dict(labels)
            if (lab.get("slice_name") in slices or lab.get("pod") in pods
                    or lab.get("target") in urls):
                excused.add((name, labels))
        return excused

    def _settle_disk(self, bound: int) -> int:
        """Give the async shed/compaction path a bounded window to reach
        steady state before the usage invariant reads it. A short drill
        window (the fuzzer generates one-round disk_full events) can end
        with the shed RECORDED but the segment rewrite still in flight —
        measuring mid-rewrite fails a governor that is working. Returns
        the final usage; gives up as soon as usage stops falling."""
        usage = dir_usage_bytes(self.egress_dir)
        for _ in range(40):
            if usage <= bound:
                break
            if self.gov is None:
                # Governor off (negative control): nothing will ever
                # shed — measure once, fail honestly.
                break
            self.gov.tick()
            if self.shipper is not None:
                # Re-assert the held rung through the public path: the
                # seal reclaims acked bytes the lazy rotation stranded.
                self.shipper.set_disk_pressure(True)
            time.sleep(0.05)
            usage = dir_usage_bytes(self.egress_dir)
        return usage

    def _resolve_hotspot(self, ev: ScenarioEvent) -> None:
        """Re-resolve the hot index set from the CURRENT pod mapping —
        at window start and again every tick. An index set pinned once at
        start silently stops mapping to ``ev.subject`` when a composed
        churn_storm bumps ``pod_gen`` mid-window: the HBM boost lands on
        indices whose pod label has rotated away, the subject rolls up to
        zero, and the attributability invariant trips (the fuzzer's
        hotspot x churn find — the old code admitted the composition was
        unsupported "only by convention")."""
        farm = self.sim.farm
        farm.hot = {
            i for i in self._member_indices()
            if farm.pod_of(i) == ev.subject
        }

    def _tick_event(self, ev: ScenarioEvent, r: int) -> None:
        """Per-round continuation for windowed events."""
        farm = self.sim.farm
        if ev.kind == "restart_wave":
            batch = self.restart_batches.get(r, ())
            prev = self.restart_batches.get(r - 1, ())
            farm.dead -= set(prev)
            self.recovering |= {farm.url(i) for i in prev}
            farm.dead |= set(batch)
        elif ev.kind == "churn_storm":
            k = ev.count // 2
            added = list(farm.add_targets(ev.count - k))
            self.membership = self.membership[k:] + added
            farm.pod_gen += 1  # the label-churn half of the storm
            self.sim.write_targets(self.membership)
            # Churn changes the TRUE series set (members retired, every
            # pod label rotated): the retention baseline is stale the
            # moment this ticks. Drop it — the next verified quiet round
            # re-arms it — so churn's legitimate deletions can't be
            # mis-attributed to a concurrent partition (fuzzer find:
            # churn_storm + root<->recv cut in one round reported the
            # rotated pods as "series lost during partition").
            self.baseline_series = None
        elif ev.kind == "disk_full" and self.shipper is not None:
            # Keep FRESH batches landing through the window (a full extra
            # round, never a re-push of the same snapshot — identical
            # sample timestamps would corrupt the exactly-once ledger this
            # very drill asserts): the negative control's usage growth
            # must be monotone.
            self.sim.run_round()
            self.shipper.on_snapshot(self.sim.root_store.current())
            time.sleep(0.05)  # let the writer thread land the append
        elif ev.kind == "mem_pressure":
            # Drive dashboard-shaped query traffic so the leaf fleet
            # caches actually grow: generation bumps per round make every
            # window a fresh cache key.
            for k in range(3):
                try:
                    self.plane.window_stats(
                        "tpu_hbm_used_bytes",
                        window_s=float(30 + 10 * r + k),
                    )
                except Exception:  # noqa: BLE001 — traffic, not an assertion
                    pass
        elif ev.kind == "scrape_storm" and self.storm is not None:
            try:
                self.storm_p99s.append(self._polite_p99(8))
            except (OSError, RuntimeError) as e:
                # The incumbent polite scraper being rejected/disconnected
                # mid-storm IS an invariant failure — recorded, never a
                # crash that aborts the whole suite.
                self._polite_conn = None  # reconnect on the next probe
                self.problems.append(
                    f"r{r}: polite scraper failed during the storm: {e}")

    # -------------------------------------------------------------- the drive

    def run(self) -> dict:
        result: dict = {"scenario": self.scn.name,
                        "timeline": self.scn.timeline, "ok": False}
        self.start_wall = time.time()
        try:
            for r in range(self.rounds):
                for ev in self.events:
                    if ev.end_round == r:
                        self._end_event(ev)
                for ev in self.events:
                    if ev.at_round == r:
                        self._start_event(ev)
                for ev in self.events:
                    if ev.at_round <= r < ev.end_round:
                        self._tick_event(ev, r)
                # Hotspot resolution LAST, after every event has mutated
                # membership/labels for this round: a churn_storm ticking
                # after the hotspot would bump pod_gen and orphan an
                # already-resolved hot set (event order within a round is
                # timeline order, so the fix cannot live in _tick_event).
                for ev in self.events:
                    if ev.kind == "hotspot" and ev.at_round <= r < ev.end_round:
                        self._resolve_hotspot(ev)
                self.sim.run_round()
                if self.shipper is not None:
                    self.shipper.on_snapshot(self.sim.root_store.current())
                if self.alert_eval is not None:
                    # Ride the round exactly where the root CLI runs it:
                    # after the merge publish, before serving checks.
                    self.alert_eval.evaluate_round(
                        self.sim.root_store.current())
                if self.hub is not None:
                    # Deterministic engine: rounds drive the hub
                    # synchronously (the CLIs ride a StreamPump thread).
                    self.hub.on_round(self.sim.root.rounds)
                if self.gov is not None:
                    # Two synchronous ticks: at most one rung moves per
                    # tick, and the deeper ladders need to climb within a
                    # window measured in rounds.
                    time.sleep(0.06)  # past check_interval + writer drain
                    self.gov.tick()
                    self.gov.tick()
                self._check_tick(r)
                if self.problems:
                    result["failed_round"] = r
                    result["problems"] = self.problems[:8]
                    return result
                if self.scn.round_pause_s:
                    time.sleep(self.scn.round_pause_s)
            ok = self._finish(result)
            result["ok"] = ok and not self.problems
            if self.problems:
                result["problems"] = self.problems[:8]
            return result
        finally:
            result["trace_ticks"] = len(self.trace)
            result["invariants_armed"] = sorted(self.invariants_armed)
            self._close()

    # ---------------------------------------------------------- tick checks

    def _active(self, r: int) -> list[ScenarioEvent]:
        return [ev for ev in self.events if ev.at_round <= r < ev.end_round]

    def _expected_cut_leaves(self) -> set[str]:
        """Leaf names the root cannot reach under the currently-EFFECTIVE
        cuts (flapping cuts only on their cut half-rounds)."""
        out: set[str] = set()
        for src, dst, _flap in self.net.active():
            if src != "root":
                continue
            if dst == "leaf":
                out.update(self.sim.leaves)
            elif dst.startswith("leaf:"):
                out.add(dst.split(":", 1)[1])
        return out

    def _check_tick(self, r: int) -> None:
        farm = self.sim.farm
        active = self._active(r)
        # Warm high-water of accounted memory outside injected mem
        # windows: the reference a later mem_pressure budget is derived
        # from. Without it, a window opening right after a root restart
        # samples a cold cache and sets a budget the legitimate warm-up
        # alone breaches (fuzzer find: root_restart()@2;
        # mem_pressure()@3+2).
        if not any(ev.kind == "mem_pressure" for ev in active):
            self.mem_accounted_baseline = max(
                self.mem_accounted_baseline, self._accounted_memory())
        body = self.sim.root_body()
        fams = parse_families(body)
        series = set(_family_values(body))
        problems: list[str] = []

        leaf_up = {
            (s.labels["shard"], s.labels["leaf"]): s.value
            for s in fams.get(schema.TPU_ROOT_LEAF_UP.name, ())
        }
        stale_served = {
            (s.labels["shard"], s.labels["leaf"]): s.value
            for s in fams.get(schema.TPU_ROOT_LEAF_STALE_SERVED.name, ())
        }
        suspected = {
            (s.labels["shard"], s.labels["leaf"]): s.value
            for s in fams.get(
                schema.TPU_ROOT_LEAF_PARTITION_SUSPECTED.name, ())
        }
        staleness = {
            (s.labels["shard"], s.labels["leaf"]): s.value
            for s in fams.get(
                schema.TPU_ROOT_LEAF_STALENESS_SECONDS.name, ())
        }
        target_up = {
            s.labels["target"]: s.value
            for s in fams.get(schema.TPU_AGG_TARGET_UP.name, ())
        }

        # --- (5) attributability: injected leaf-tier cuts ----------------
        cut_leaves = self._expected_cut_leaves()
        for name, leaf in self.sim.leaves.items():
            shard = self.sim._leaf_meta[name][0]
            key = (shard, leaf.addr)
            if leaf_up.get(key) == 1.0:
                self.recovering_leaves.discard(name)
            if name in cut_leaves:
                if leaf_up.get(key) != 0.0:
                    problems.append(
                        f"r{r}: cut leaf {name} not attributable "
                        f"(leaf_up={leaf_up.get(key)})")
                if self.stale_serve_s > 0 and stale_served.get(key) != 1.0:
                    problems.append(
                        f"r{r}: cut leaf {name} not stale-served")
                twin_reachable = any(
                    n != name and n not in cut_leaves
                    for n in self.sim.leaves
                    if self.sim._leaf_meta[n][0] == shard
                )
                if twin_reachable and suspected.get(key) != 1.0:
                    problems.append(
                        f"r{r}: cut leaf {name} (twin reachable) not "
                        f"marked partition-suspected")
            elif (r >= 1 and leaf_up.get(key) != 1.0
                    and name not in self.recovering_leaves):
                problems.append(
                    f"r{r}: healthy leaf {name} reported down "
                    f"(leaf_up={leaf_up.get(key)})")

        # --- (5) attributability: injected target outages ----------------
        injected_down = {
            farm.url(i) for i in farm.dead if i in self._member_indices()
        }
        for t in injected_down:
            if target_up.get(t) != 0.0:
                problems.append(
                    f"r{r}: injected-down target {t} not attributable "
                    f"(up={target_up.get(t)})")
        reported_down = {t for t, v in target_up.items() if v == 0.0}
        unexplained = reported_down - injected_down - self.recovering
        if unexplained and not cut_leaves and not any(
                ev.kind == "partition" for ev in active):
            problems.append(
                f"r{r}: {len(unexplained)} target(s) down without an "
                f"injected fault: {sorted(unexplained)[:3]}")
        up_now = {t for t in self.recovering if target_up.get(t) == 1.0}
        self.recovering -= up_now & self._recovered_once
        self._recovered_once = up_now - self._recovered_once
        restart_active = [ev for ev in active if ev.kind == "restart_wave"]
        if restart_active:
            ev = restart_active[0]
            batch = set(self.restart_batches.get(r, ()))
            # The 2*stagger blast-radius cap is a claim about the WAVE
            # (current batch + previous batch still recovering) — down
            # targets attributable to a composed fault (active preempt,
            # healed-cut recovery lag) don't count against it, but the
            # wave's own hosts always do.
            wave_urls = {farm.url(i)
                         for b in self.restart_batches.values() for i in b}
            wave_down = (reported_down
                         - (self.recovering - wave_urls)
                         - (injected_down - wave_urls))
            if len(wave_down) > 2 * ev.stagger:
                problems.append(
                    f"r{r}: restart wave (stagger {ev.stagger}) has "
                    f"{len(wave_down)} targets down at once")
            stray = ({self._idx_of(t) for t in reported_down} - batch
                     - {self._idx_of(t) for t in self.recovering}
                     - {self._idx_of(t) for t in injected_down})
            if stray:
                problems.append(
                    f"r{r}: restart wave touched targets outside its "
                    f"batch: {sorted(stray)[:4]}")

        # --- (5) attributability: hotspot dominates the workload rollups -
        for ev in active:
            if ev.kind != "hotspot" or not (farm.hot - farm.dead):
                # All hot hosts are down this round (a composed restart
                # wave can take the hot pod's only host with it): the pod
                # is legitimately absent from the rollups.
                continue
            per_pod: dict[str, float] = {}
            for s in fams.get(schema.TPU_WORKLOAD_HBM_USED_BYTES.name, ()):
                pod = s.labels.get("pod", "?")
                per_pod[pod] = per_pod.get(pod, 0.0) + s.value
            hot = per_pod.get(ev.subject, 0.0)
            others = [v for p, v in per_pod.items() if p != ev.subject]
            if not others or hot <= 2.0 * max(others):
                problems.append(
                    f"r{r}: hotspot {ev.subject} not attributable from "
                    f"workload rollups (hot={hot:g}, "
                    f"max other={max(others) if others else 0:g})")

        # --- (2) bounded staleness per tier ------------------------------
        for key, up in leaf_up.items():
            st = staleness.get(key)
            if up == 1.0 and st is not None and st > FRESH_STALENESS_BUDGET_S:
                problems.append(
                    f"r{r}: reachable leaf {key} staleness {st:.1f}s "
                    f"exceeds {FRESH_STALENESS_BUDGET_S:g}s")
            if stale_served.get(key) == 1.0 and st is not None and (
                    st > self.stale_serve_s + FRESH_STALENESS_BUDGET_S):
                problems.append(
                    f"r{r}: stale-served leaf {key} staleness {st:.1f}s "
                    f"beyond the stale-serve budget")

        # --- (3)+(4) series retention / oracle equality ------------------
        # Retention under partition is a STALE-SERVE claim, so it scopes
        # to the edges stale-serve covers (leaf<->root, root<->recv). A
        # node<->leaf cut is indistinguishable from the targets dying —
        # series withdraw BY SPECIFICATION and the attribution checks
        # above own that contract (fuzzer find: a bare one-round
        # node-cut tripped this as "116 series lost").
        partition_active = any(
            ev.kind == "partition"
            and frozenset(ev.edge or ()) != frozenset({"node", "leaf"})
            for ev in active)
        node_cut_active = any(
            ev.kind == "partition"
            and frozenset(ev.edge or ()) == frozenset({"node", "leaf"})
            for ev in active)
        if (partition_active and not node_cut_active
                and self.baseline_series is not None):
            lost = self.baseline_series - series
            if lost:
                lost -= self._excused_losses(lost)
            if lost:
                problems.append(
                    f"r{r}: {len(lost)} series lost during partition: "
                    f"{sorted(lost)[:3]}")
        quiet = (
            not active
            and not self.net.any_cuts()
            and not farm.dead
            and not self.recovering
            and not self.recovering_leaves
            and r >= 2
        )
        if quiet and not reported_down:
            self.invariants_armed.add("oracle_equality")
            oracle_problems = _compare_oracle(
                _family_values(body), _family_values(self.sim.oracle_body())
            )
            if oracle_problems:
                problems.append(
                    f"r{r}: quiet round diverged from oracle: "
                    f"{oracle_problems[:2]}")
            else:
                self.baseline_series = series
                self.baseline_workloads = len(
                    fams.get(schema.TPU_WORKLOAD_HBM_USED_BYTES.name, ()))
                if self.rss_baseline is None:
                    self.rss_baseline = _utils.process_rss_bytes() or 0.0

        # --- scenario-specific spot checks -------------------------------
        if self.scn.name == "partition_symmetric" and any(
                ev.kind == "partition" and ev.end_round - 1 == r
                for ev in self.events):
            # Last cut round: /readyz over the wire must say degraded
            # while the stale view keeps serving (HTTP 200 either way).
            doc = _get_json(
                f"http://127.0.0.1:{self.root_server.port}/readyz")
            if doc.get("state") != "degraded":
                problems.append(
                    f"r{r}: /readyz state {doc.get('state')!r} during a "
                    f"total root-leaf partition (want degraded)")
        if self.scn.name == "partition_asymmetric" and cut_leaves and (
                r == max(ev.at_round for ev in self.events) + 1):
            env = self.plane.window_stats("tpu_hbm_used_bytes",
                                          window_s=60.0)
            rows = env["data"]["result"]
            if env["partial"]:
                problems.append(
                    f"r{r}: two-level query PARTIAL during asymmetric cut "
                    f"(twins should cover): {env['fleet']}")
            elif len(rows) != len(self.membership):
                problems.append(
                    f"r{r}: two-level query merged {len(rows)} rows, want "
                    f"{len(self.membership)}")
        if (self.scn.name == "alert_partition" and cut_leaves
                and self.alert_eval is not None):
            active_alerts = {
                (row["labels"]["alertname"], row["state"])
                for row in self.alert_eval.rows()
            }
            if ("TpuRootLeafPartitioned", "firing") not in active_alerts:
                problems.append(
                    f"r{r}: leaves cut one-sided but "
                    f"TpuRootLeafPartitioned not firing "
                    f"(active: {sorted(active_alerts)})")
            if self.stream_on:
                # The alerts route is a first-class stream shape: the
                # polled answer must be the evaluator's rows, verbatim.
                from tpu_pod_exporter.stream import QueryShape

                env = self._stream_poll(QueryShape(route="alerts"), 0)
                rows = env.get("data", {}).get("result", [])
                if rows != self.alert_eval.rows():
                    problems.append(
                        f"r{r}: alerts stream route disagrees with the "
                        f"evaluator ({len(rows)} rows vs "
                        f"{len(self.alert_eval.rows())})")
        if self.scn.name == "recv_outage" and any(
                ev.kind == "recv_outage" and ev.end_round - 1 == r
                for ev in self.events):
            if not self._await_egress_wedged():
                problems.append(
                    f"r{r}: receiver outage not attributable from the "
                    f"egress exposition (breaker never opened / no "
                    f"backlog)")

        # --- resource-pressure drills: window-end invariants --------------
        for ev in active:
            if ev.end_round - 1 != r:
                continue
            if ev.kind == "preempt" and self.scn.gpu_slices:
                # mixed_wedge parity: capture this wedge's degradation
                # signature at its last injected round (breakers have had
                # the whole window to open). Asserted pairwise at finish.
                sl = int(ev.subject.rsplit("-", 1)[1])
                fam = farm.family_of_slice(sl)
                other = "gpu" if fam == "tpu" else "tpu"
                victims = getattr(self, "_preempt_victims", set())
                fam_chips = {
                    s.labels.get("family", "?"): s.value
                    for s in fams.get(
                        schema.TPU_FLEET_FAMILY_CHIP_COUNT.name, ())
                }
                quarantined = sum(
                    s.value for s in fams.get(
                        schema.TPU_ROOT_SHARD_QUARANTINED_TARGETS.name, ())
                )
                before = self._wedge_chips_before
                self.wedge_sigs.append({
                    "family": fam,
                    "slice": ev.subject,
                    "victims": len(victims),
                    "victims_down": sum(
                        1 for i in victims
                        if target_up.get(farm.url(i)) == 0.0
                    ),
                    "chips_dropped": (
                        before.get(fam, 0.0) - fam_chips.get(fam, 0.0)
                    ),
                    "other_family_drift": (
                        before.get(other, 0.0) - fam_chips.get(other, 0.0)
                    ),
                    "quarantined": quarantined,
                })
            if ev.kind == "disk_full":
                # Post-shed floor: compaction's steady state is one shed
                # segment plus ~a batch in flight — an absolute budget
                # below one batch is unmeetable BY ANY policy, so the
                # invariant is bounded by physics, not wishes.
                floor = 2 * self.disk_batch_est + (12 << 10)
                usage = self._settle_disk(
                    max(self.disk_budget_target, floor))
                if usage > max(self.disk_budget_target, floor):
                    problems.append(
                        f"r{r}: disk usage {usage}B still over the "
                        f"squeezed budget {self.disk_budget_target}B "
                        f"(floor {floor}B) at window end — nothing shed")
                if self.gov is not None:
                    gs = self.gov.stats()["disk"]
                    # A ladder that shed and already recovered (usage
                    # reclaimed, hysteresis elapsed) is the governor
                    # WORKING — the invariant is that shedding happened
                    # and was counted, not that a rung is still held.
                    if gs["sheds"] < 1:
                        problems.append(
                            f"r{r}: disk_full window ended with zero "
                            f"recorded sheds (ladder inert)")
            elif ev.kind == "mem_pressure":
                accounted = self._accounted_memory()
                if accounted > self.mem_budget_target:
                    problems.append(
                        f"r{r}: accounted memory {accounted}B over the "
                        f"squeezed budget {self.mem_budget_target}B at "
                        f"window end — nothing shed")
                if self.gov is not None:
                    if self.gov.stats()["memory"]["sheds"] < 1:
                        problems.append(
                            f"r{r}: mem_pressure window ended with zero "
                            f"recorded memory sheds (ladder inert)")
            elif ev.kind == "scrape_storm" and self.storm is not None:
                st = self.storm.stats()
                peak = self.root_server.conn_stats["peak"]
                if self.governor_on:
                    if st["rejected"] == 0 and self.storm.conns > self.conn_cap:
                        problems.append(
                            f"r{r}: a {self.storm.conns}-conn storm over "
                            f"the {self.conn_cap}-conn cap drew zero 429s "
                            f"(admission control inert)")
                    if peak > self.conn_cap:
                        problems.append(
                            f"r{r}: open connections peaked at {peak} "
                            f"over the {self.conn_cap} cap")
                base = self.storm_baseline_p99
                if self.storm_p99s and base:
                    worst = max(self.storm_p99s)
                    # Engine budget is generous (shared CI runners); the
                    # strict 5% contract lives in make pressure-demo.
                    if worst > max(3.0 * base, base + 0.25):
                        problems.append(
                            f"r{r}: polite scrape p99 {1e3 * worst:.1f}ms "
                            f"during the storm vs {1e3 * base:.1f}ms "
                            f"baseline — serving latency not protected")
            elif ev.kind == "dashboard_storm":
                problems.extend(self._check_dashboard_tick(ev, r, fams))

        self.problems.extend(problems)
        self.trace.append({
            "round": r,
            "active": [ev.raw for ev in active],
            "pressure": (
                {
                    "disk": self.gov.stats()["disk"]["level"],
                    "memory": self.gov.stats()["memory"]["level"],
                }
                if self.gov is not None else None
            ),
            "cuts": [list(c) for c in self.net.active()],
            "leaf_down": sorted(
                leaf for (_s, leaf), v in leaf_up.items() if v == 0.0),
            "targets_down": len(reported_down),
            "stale_served": sorted(
                leaf for (_s, leaf), v in stale_served.items() if v == 1.0),
            "series": len(series),
            "problems": problems,
        })

    def _check_dashboard_tick(self, ev, r: int, fams) -> list[str]:
        """dashboard_storm per-tick invariants: every subscriber live and
        caught up to this round's generation, delta replay == the polled
        answer at the SAME generation for sampled subscribers, zero seq
        gaps/dups, and the subscription load attributable from the
        tpu_stream_* exposition."""
        from tpu_pod_exporter.stream import rows_map

        problems: list[str] = []
        dash = self.dash
        if dash is None:
            return problems
        live = dash.live()
        if live < ev.count:
            problems.append(
                f"r{r}: only {live}/{ev.count} dashboard subscriptions "
                f"live" + ("" if self.stream_on else
                           " (stream disabled — negative control)"))
            return problems
        lag = dash.wait_caught_up({"root": self.hub.shape_seqs()},
                                  timeout_s=10.0)
        if lag:
            problems.append(
                f"r{r}: {lag} subscribers never caught up to their "
                f"shape's seq this round")
        for label, shape, rows, sgen in dash.sample(10):
            self.dash_eq_checks += 1
            env = self._stream_poll(shape, sgen or 0)
            if rows != rows_map(shape.route, env):
                self.dash_eq_failures += 1
                problems.append(
                    f"r{r}: delta replay != polled answer for a {label} "
                    f"subscriber of {shape.metric} {dict(shape.match)}")
        tot = dash.totals()
        if tot["gaps"] or tot["dups"]:
            problems.append(
                f"r{r}: stream seq discontinuities — {tot['gaps']} gaps, "
                f"{tot['dups']} dups")
        if tot["latencies"]:
            lats = tot["latencies"]
            self.dash_push_p99s.append(lats[int(0.99 * (len(lats) - 1))])
        dash.drain_latencies()
        # Attributable from the exposition alone: the hub's surface rides
        # the root publish (tpu_stream_subscribers is the gauge the
        # RUNBOOK's storm playbook reads first).
        subs_series = fams.get(schema.TPU_STREAM_SUBSCRIBERS.name, ())
        gauge = subs_series[0].value if subs_series else None
        if r > ev.at_round and (gauge is None or gauge < 0.9 * ev.count):
            problems.append(
                f"r{r}: tpu_stream_subscribers reads {gauge!r} with "
                f"{live} live subscriptions — storm not attributable "
                f"from the exposition")
        return problems

    def _egress_exposition(self) -> dict[str, float]:
        """The shipper's self-metric surface AS EXPOSITION (the same
        bytes app.py would publish) — fault attribution reads metrics,
        not private state."""
        from tpu_pod_exporter.metrics import SnapshotBuilder

        b = SnapshotBuilder()
        self.shipper.emit(b)
        text = b.build(timestamp=time.time()).encode().decode()
        out: dict[str, float] = {}
        for fam in parse_families(text).values():
            for s in fam:
                if not s.labels:
                    out[s.name] = s.value
        return out

    def _await_egress_wedged(self, timeout_s: float = 8.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            exp = self._egress_exposition()
            if (exp.get("tpu_exporter_egress_breaker_state", 0.0) != 0.0
                    and exp.get("tpu_exporter_egress_backlog_batches",
                                0.0) >= 1.0):
                return True
            # Keep FRESH batches flowing so the sender keeps probing the
            # outage (a re-pushed identical snapshot would re-frame the
            # same sample timestamps and corrupt the exactly-once ledger
            # the final check reads).
            self.sim.run_round()
            self.shipper.on_snapshot(self.sim.root_store.current())
            time.sleep(0.2)
        return False

    # ------------------------------------------------------------- the finish

    def _finish(self, result: dict) -> bool:
        # Settle: every injected fault healed; quarantined targets must be
        # re-admitted (half-open probes) and the tree must converge back
        # to oracle-equal — bounded, not open-ended.
        deadline = time.monotonic() + 15.0
        recovered = False
        while time.monotonic() < deadline:
            self.sim.run_round()
            if self.shipper is not None:
                self.shipper.on_snapshot(self.sim.root_store.current())
            if self.alert_eval is not None:
                # Keep evaluating through settle: resolution (and its
                # notifications) must happen for the alert verdict below.
                self.alert_eval.evaluate_round(
                    self.sim.root_store.current())
            if self.gov is not None:
                self.gov.tick()
                gs = self.gov.stats()
                if gs["disk"]["level"] or gs["memory"]["level"]:
                    # Ladders must step back to 0 (hysteresis) before the
                    # stack can count as recovered.
                    time.sleep(0.15)
                    continue
            body = self.sim.root_body()
            fams = parse_families(body)
            target_up = {
                s.labels["target"]: s.value
                for s in fams.get(schema.TPU_AGG_TARGET_UP.name, ())
            }
            leaf_up_ok = all(
                s.value == 1.0
                for s in fams.get(schema.TPU_ROOT_LEAF_UP.name, ())
            )
            members_up = (
                set(target_up) == set(self.membership)
                and all(v == 1.0 for v in target_up.values())
            )
            if leaf_up_ok and members_up:
                self.invariants_armed.add("oracle_equality")
                oracle_problems = _compare_oracle(
                    _family_values(body),
                    _family_values(self.sim.oracle_body()),
                )
                if not oracle_problems:
                    recovered = True
                    break
            time.sleep(0.15)
        result["recovered"] = recovered
        if self.gov is not None:
            gs = self.gov.stats()
            result["pressure"] = {
                "disk": {k: gs["disk"][k]
                         for k in ("level", "sheds", "recovers")},
                "memory": {k: gs["memory"][k]
                           for k in ("level", "sheds", "recovers")},
            }
        if any(ev.kind == "dashboard_storm" for ev in self.events):
            tot = self.dash_totals
            push_p99 = (max(self.dash_push_p99s)
                        if self.dash_push_p99s else None)
            result["dashboard"] = {
                "frames": tot.get("frames"),
                "gaps": tot.get("gaps"),
                "dups": tot.get("dups"),
                "push_p99_s": push_p99,
                "push_p99_budget_s": self.DASH_PUSH_P99_BUDGET_S,
                "eq_checks": self.dash_eq_checks,
                "eq_failures": self.dash_eq_failures,
            }
            if self.stream_on and self.dash_eq_checks == 0:
                self.problems.append(
                    "dashboard_storm window produced ZERO replay-equality "
                    "checks — the invariant never ran")
            if push_p99 is not None and (
                    push_p99 > self.DASH_PUSH_P99_BUDGET_S):
                self.problems.append(
                    f"dashboard push p99 {push_p99:.3f}s over the "
                    f"{self.DASH_PUSH_P99_BUDGET_S}s budget")

        if not recovered:
            self.problems.append(
                "stack did not converge back to healthy + oracle-equal "
                "within the settle budget (quarantine black-hole after "
                "heal, or a pressure ladder stuck above level 0?)")
            return False

        if self.scn.name == "store_continuity":
            self._check_store_continuity()

        if self.scn.name == "mixed_wedge":
            # The GPU parity verdict: a wedged GPU node pool must degrade
            # IDENTICALLY to a wedged TPU node pool — same victim
            # accounting, same breaker quarantine, same family-correct
            # chip drop, zero drift on the untouched family. (Zero
            # acked-sample loss rides the standard egress ledger check
            # below.)
            result["wedges"] = self.wedge_sigs
            by_family = {sig["family"]: sig for sig in self.wedge_sigs}
            if set(by_family) != {"tpu", "gpu"}:
                self.problems.append(
                    f"mixed_wedge recorded wedges for {sorted(by_family)}, "
                    f"want one TPU and one GPU")
            else:
                t, g = by_family["tpu"], by_family["gpu"]
                for sig in (t, g):
                    if sig["victims"] == 0:
                        self.problems.append(
                            f"mixed_wedge: {sig['family']} wedge had no "
                            f"victims (slice {sig['slice']} empty?)")
                    if sig["victims_down"] != sig["victims"]:
                        self.problems.append(
                            f"mixed_wedge: {sig['family']} wedge dropped "
                            f"up for {sig['victims_down']}/{sig['victims']} "
                            f"victims")
                    if sig["quarantined"] < 1:
                        self.problems.append(
                            f"mixed_wedge: {sig['family']} wedge opened no "
                            f"leaf breakers (quarantine semantics differ)")
                    if sig["other_family_drift"] > 0.0:
                        # Positive drift only: the violation is the OTHER
                        # family LOSING chips to this wedge. Negative
                        # drift is the other family still re-admitting its
                        # own earlier wedge's victims at window start
                        # (breaker half-open probes lag the heal) — that
                        # is recovery, not cross-family leakage.
                        self.problems.append(
                            f"mixed_wedge: {sig['family']} wedge dropped the "
                            f"OTHER family's chip count by "
                            f"{sig['other_family_drift']:g} (family sums "
                            f"not family-correct)")
                if t["victims"] == g["victims"] and (
                        t["chips_dropped"] != g["chips_dropped"]):
                    self.problems.append(
                        f"mixed_wedge: equal victim counts but unequal "
                        f"chip drops (tpu {t['chips_dropped']:g} vs gpu "
                        f"{g['chips_dropped']:g}) — degradation not "
                        f"identical")
                chips = self.sim.farm.chips
                for sig in (t, g):
                    if sig["chips_dropped"] != sig["victims"] * chips:
                        self.problems.append(
                            f"mixed_wedge: {sig['family']} chip drop "
                            f"{sig['chips_dropped']:g} != victims x chips "
                            f"({sig['victims']} x {chips})")

        # /readyz healthy again, over the wire.
        doc = _get_json(f"http://127.0.0.1:{self.root_server.port}/readyz")
        result["readyz_state"] = doc.get("state")
        if doc.get("state") != "ready":
            self.problems.append(
                f"/readyz stuck at {doc.get('state')!r} after recovery")

        # (4) series accounting after churn: per-target series must match
        # final membership EXACTLY (no ghosts from removed targets), and
        # the workload surface must not have accreted label-churn corpses.
        fams = parse_families(self.sim.root_body())
        target_series = {
            s.labels["target"]
            for s in fams.get(schema.TPU_AGG_TARGET_UP.name, ())
        }
        if target_series != set(self.membership):
            ghosts = target_series - set(self.membership)
            missing = set(self.membership) - target_series
            self.problems.append(
                f"series leak: {len(ghosts)} ghost target series "
                f"({sorted(ghosts)[:3]}), {len(missing)} missing")
        n_workloads = len(
            fams.get(schema.TPU_WORKLOAD_HBM_USED_BYTES.name, ()))
        if self.baseline_workloads and n_workloads > (
                self.baseline_workloads + 2 * len(self.sim.topology) + 8):
            self.problems.append(
                f"workload series grew {self.baseline_workloads} -> "
                f"{n_workloads} across churn (label-set leak)")
        rss = _utils.process_rss_bytes()
        if self.rss_baseline and rss and (
                rss - self.rss_baseline > 128 * 2**20):
            self.problems.append(
                f"RSS grew {(rss - self.rss_baseline) / 2**20:.0f} MiB "
                f"across the scenario (leak)")
        result["rss_growth_mb"] = (
            round((rss - self.rss_baseline) / 2**20, 1)
            if rss and self.rss_baseline else None
        )

        # (1) egress exactly-once: everything framed must have landed,
        # contiguous and duplicate-free, after the backlog drains.
        if self.shipper is not None:
            drained = self._await_drain()
            stats = self.shipper.stats()
            ledger = self.receiver.stats()
            seqs = ledger["accepted_seqs"]
            result["egress"] = {
                "batches": stats["enqueued_batches"],
                "accepted": len(seqs),
                "duplicate_seqs": len(ledger["duplicate_seqs"]),
                "duplicate_samples": ledger["duplicate_samples"],
                "breaker_reopens": stats["breaker_reopens"],
                "drained": drained,
            }
            if not drained:
                self.problems.append(
                    f"egress backlog failed to drain after heal "
                    f"({stats['backlog_batches']} batches stuck, breaker "
                    f"{stats['breaker_state']})")
            if sorted(seqs) != list(range(1, len(seqs) + 1)):
                self.problems.append(
                    f"acked-sample loss: accepted seqs not contiguous "
                    f"({sorted(seqs)[:5]}…)")
            if stats["enqueued_batches"] != len(seqs):
                self.problems.append(
                    f"acked-sample loss: {stats['enqueued_batches']} "
                    f"batches framed, {len(seqs)} delivered")
            if ledger["duplicate_seqs"] or ledger["duplicate_samples"]:
                self.problems.append(
                    f"egress re-sent acked data: "
                    f"{len(ledger['duplicate_seqs'])} duplicate batches, "
                    f"{ledger['duplicate_samples']} duplicate samples")

        if self.alert_eval is not None:
            self._finish_alerts(result)
        return not self.problems

    def _finish_alerts(self, result: dict) -> None:
        """The alerting verdict: exactly the expected alerts fired (and
        NO others), everything resolved after heal + settle, the webhook
        ledger is contiguous exactly-once after the backlog drains, and
        the firing window is answerable as ALERTS series from the store
        (source=store — honest tags, no live plane involved)."""
        from tpu_pod_exporter.alerting import FIRING

        expected = set(self.scn.expected_alerts or ())
        tag = ("" if self.alert_suppression
               else " (suppression OFF — negative control)")
        fired = {
            str(t["alert"])
            for t in self.alert_eval.transitions(limit=10_000)
            if t["to"] == FIRING
        }
        if self.scn.allowed_alerts is not None:
            # Suppress-aware BOUND mode (generated timelines): required
            # alerts must fire, nothing outside the derived envelope may
            # fire, and nothing outside it may even have been SUPPRESSED
            # — a rule engaging silently where the generator's model says
            # it can't is the same disagreement as a stray firing.
            envelope = expected | set(self.scn.allowed_alerts)
            if not expected <= fired:
                self.problems.append(
                    f"alerts fired {sorted(fired)}, missing required "
                    f"{sorted(expected - fired)} (generated-timeline "
                    f"bound mode){tag}")
            elif not fired <= envelope:
                self.problems.append(
                    f"alerts fired {sorted(fired)} outside the derived "
                    f"envelope {sorted(envelope)} (generated-timeline "
                    f"bound mode){tag}")
            suppressed = set(self.alert_eval.suppressed_names())
            if not suppressed <= envelope:
                self.problems.append(
                    f"alerts suppressed {sorted(suppressed)} outside the "
                    f"derived envelope {sorted(envelope)} — the evaluator "
                    f"engaged where the timeline model says it cannot"
                    f"{tag}")
        elif fired != expected:
            self.problems.append(
                f"alerts fired {sorted(fired)}, want exactly "
                f"{sorted(expected)} — 'the right alerts, and no "
                f"others' broken{tag}")
        firing, pending = self.alert_eval.counts()
        if firing or pending:
            self.problems.append(
                f"{firing} firing / {pending} pending alert instances "
                f"left after heal + settle (resolution never came){tag}")
        drained = self._await_alert_drain()
        nstats = self.alert_notifier.stats()
        with self._alert_ledger_lock:
            seqs = sorted(self.alert_ledger)
        result["alerts"] = {
            "fired": sorted(fired),
            "expected": sorted(expected),
            "suppressed": self.alert_eval.stats()["suppressed_total"],
            "notifications": nstats["enqueued"],
            "delivered": len(seqs),
            "failed_sends": nstats["failed"],
            "breaker_reopens": nstats["breaker_reopens"],
            "drained": drained,
        }
        if not drained:
            self.problems.append(
                f"alert notification backlog failed to drain after heal "
                f"({nstats['backlog_records']} records stuck, breaker "
                f"{nstats['breaker_state']})")
        if self.scn.name == "alert_partition" and nstats["failed"] < 1:
            # The drill's outage window covers the partition onset: the
            # firing notifications MUST have hit the dead webhook and
            # buffered. `failed` is the monotonic witness (breaker
            # reopens reset once post-heal probation successes land); a
            # zero means the wedge never happened and the exactly-once
            # claim went untested.
            self.problems.append(
                "alert notifier never saw a failed send — the outage "
                "window missed every notification, backlog/drain "
                "untested")
        if seqs != list(range(1, len(seqs) + 1)):
            self.problems.append(
                f"alert ledger not contiguous exactly-once: {seqs[:6]}…")
        elif drained and nstats["enqueued"] != len(seqs):
            self.problems.append(
                f"alert notification loss: {nstats['enqueued']} framed, "
                f"{len(seqs)} delivered")
        if self.store is not None and expected:
            env = self.plane.query_range(
                "ALERTS", start=self.start_wall, end=time.time(),
                step=0.0, source="store")
            rows = env.get("data", {}).get("result", [])
            names = {
                (row.get("labels") or {}).get("alertname")
                for row in rows if isinstance(row, dict)
            }
            if not expected <= names:
                self.problems.append(
                    f"ALERTS series missing from the store: have "
                    f"{sorted(n for n in names if n)}, want at least "
                    f"{sorted(expected)}")

    def _await_alert_drain(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            s = self.alert_notifier.stats()
            with self._alert_ledger_lock:
                delivered = len(self.alert_ledger)
            if s["backlog_records"] == 0 and delivered >= s["enqueued"]:
                return True
            time.sleep(0.1)
        return False

    def _check_store_continuity(self) -> None:
        """The store_continuity drill's boundary invariant, run with the
        store ON and OFF alike (off is the negative control: the same
        checks must then FAIL on the gap). A bucket-sample query (step=0 —
        no grid carry-forward masking holes) over [run start, now] must
        have real points on BOTH sides of the root's dead window, with no
        internal hole wider than the downtime itself, sources honest per
        row, and the recording-rule series answerable store-only."""
        problems: list[str] = []
        rollup = schema.TPU_SLICE_HBM_USED_BYTES.name
        end = time.time()
        try:
            env = self.plane.query_range(rollup, start=self.start_wall,
                                         end=end, step=0.0)
        except Exception as e:  # noqa: BLE001 — a broken plane IS the finding
            self.problems.append(f"store continuity: boundary query "
                                 f"failed: {e}")
            return
        rows = env.get("data", {}).get("result", [])
        pts = sorted(
            float(t) for row in rows if isinstance(row, dict)
            for t, _v in (row.get("values") or [])
        )
        downtime = max(self.restart_wall - self.kill_wall, 0.1)
        tag = "" if self.store is not None else " [store OFF]"
        if not any(t <= self.kill_wall for t in pts):
            problems.append(
                f"store continuity{tag}: no samples before the root kill "
                f"— the dead window is a gap, nothing fills it")
        if not any(t >= self.restart_wall for t in pts):
            problems.append(
                f"store continuity{tag}: no samples after the restart")
        allowed = downtime + 2.0 * self.STORE_FINEST_STEP + 2.0
        for a, b in zip(pts, pts[1:]):
            if b - a > allowed:
                problems.append(
                    f"store continuity{tag}: {b - a:.1f}s hole in the "
                    f"boundary query (allowed {allowed:.1f}s = downtime "
                    f"+ bucket slack)")
                break
        if self.store is not None:
            bad = [row for row in rows
                   if row.get("source") not in ("live", "store")]
            if bad:
                problems.append(
                    f"store continuity: {len(bad)} row(s) without honest "
                    f"source attribution")
            store_pts = [
                float(t) for row in rows if row.get("source") == "store"
                for t, _v in (row.get("values") or [])
            ]
            if not any(t <= self.kill_wall for t in store_pts):
                problems.append(
                    "store continuity: pre-kill coverage not attributed "
                    "source=store (who answered it?)")
            if env.get("source") not in ("merged", "store"):
                problems.append(
                    f"store continuity: envelope source "
                    f"{env.get('source')!r} despite store fills")
            # Store-only + recording-rule halves: ?source=store must
            # answer alone, and the rule series must live in the store.
            senv = self.plane.query_range(rollup, start=self.start_wall,
                                          end=end, step=0.0,
                                          source="store")
            srows = senv.get("data", {}).get("result", [])
            if not srows or any(
                    row.get("source") != "store" for row in srows):
                problems.append("store continuity: ?source=store did not "
                                "answer store-only")
            renv = self.plane.query_range("scenario:hbm:by_slice",
                                          start=self.start_wall, end=end,
                                          step=0.5, source="store")
            if not renv.get("data", {}).get("result"):
                problems.append("store continuity: recording-rule series "
                                "not served from the store")
        self.problems.extend(problems)

    def _await_drain(self, timeout_s: float = 20.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            stats = self.shipper.stats()
            if (stats["backlog_batches"] == 0
                    and self.receiver.accepted_batches()
                    >= stats["enqueued_batches"]):
                return True
            time.sleep(0.2)
        return False

    def _close(self) -> None:
        if self.storm is not None:
            self.storm.stop()
        if self.dash is not None:
            self.dash.stop()
        if self._polite_conn is not None:
            self._polite_conn.close()
        try:
            self.root_server.stop()
        except Exception:  # noqa: BLE001 — teardown must finish
            pass
        if self.hub is not None:
            self.hub.close()
        self.plane.close()
        if self.shipper is not None:
            self.shipper.close()
        if self.alert_eval is not None:
            self.alert_eval.close()  # closes the notifier + its WAL
        if self.receiver is not None:
            self.receiver.stop()
        self.sim.close()


def run_one(scn: Scenario, n_targets: int, shards: int, chips: int,
            state_root: str, seed: int,
            governor: bool = True, store: bool = True,
            stream: bool = True,
            alert_suppression: bool = True) -> tuple[dict, list[dict]]:
    """One scenario on one fresh stack, returning (result, per-tick
    trace). The fuzz harness's entrypoint: run_scenarios wraps the NAMED
    drill set, but a generated trial is an ad-hoc Scenario object and the
    minimizer needs the trace back for its failure artifacts — same _Run,
    same invariants, zero drift between fuzzed and hand-written drills."""
    run = _Run(scn, n_targets, shards, chips, state_root, seed,
               governor=governor, store=store, stream=stream,
               alert_suppression=alert_suppression)
    result = run.run()
    return result, run.trace


def run_scenarios(names: list[str], n_targets: int, shards: int,
                  chips: int, state_root: str, seed: int,
                  governor: bool = True, store: bool = True,
                  stream: bool = True,
                  alert_suppression: bool = True) -> dict:
    """Run the named scenarios back to back, each on a fresh stack (own
    state dir under ``state_root``); returns the summary dict the demo
    prints and writes as the CI artifact. ``governor=False`` is the
    pressure drills' negative control, ``store=False`` the
    store-continuity drill's, ``stream=False`` the dashboard-storm
    drill's, and ``alert_suppression=False`` the alert drills': the
    invariants still run, and the run is EXPECTED to fail them."""
    os.makedirs(state_root, exist_ok=True)
    summary: dict = {
        "ok": True, "targets": n_targets, "shards": shards,
        "seed": seed, "governor": governor, "store": store,
        "stream": stream, "alert_suppression": alert_suppression,
        "scenarios": {},
    }
    all_traces: dict[str, list] = {}
    for name in names:
        scn = SCENARIOS[name]
        t0 = time.monotonic()
        run = _Run(scn, n_targets, shards, chips,
                   os.path.join(state_root, name), seed,
                   governor=governor, store=store, stream=stream,
                   alert_suppression=alert_suppression)
        result = run.run()
        result["wall_s"] = round(time.monotonic() - t0, 2)
        all_traces[name] = run.trace
        summary["scenarios"][name] = result
        summary["ok"] = summary["ok"] and result["ok"]
        status = "ok" if result["ok"] else "FAILED"
        print(f"  {name:<22} {status:<7} {result['wall_s']:6.1f}s  "
              f"{'; '.join(result.get('problems', [])[:1])}",
              flush=True)
        if not result["ok"]:
            break  # later scenarios would only bury the first failure
    try:
        with open(os.path.join(state_root, "result.json"), "w",
                  encoding="utf-8") as f:
            json.dump(summary, f, indent=1)
        # The per-tick invariant record IS the forensics: which rounds
        # had which cuts, what the exposition said, what failed.
        with open(os.path.join(state_root, "scenario-trace.json"), "w",
                  encoding="utf-8") as f:
            json.dump(all_traces, f, indent=1)
    except OSError:
        pass
    return summary


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-loadgen-scenario",
        description="Fleet scenario engine: declarative chaos timelines "
                    "with per-tick invariants against the full "
                    "node→leaf→root→egress stack (make scenario-demo).",
    )
    p.add_argument("--scenarios", default="all",
                   help="comma-separated scenario names, or 'all' "
                        f"(known: {', '.join(SCENARIOS)})")
    p.add_argument("--timeline", default="",
                   help="ad-hoc scenario: run this DSL timeline instead "
                        "of the named set (see tpu_pod_exporter.scenario "
                        "for the grammar)")
    p.add_argument("--fuzz-replay", default="", metavar="SEED:TRIAL",
                   help="replay one generated fuzz trial deterministically "
                        "from its (seed, trial) coordinates alone — the "
                        "timeline is regenerated, the stack rebuilt, and "
                        "the same invariants asserted (delegates to "
                        "tpu_pod_exporter.fuzz; see RUNBOOK 'Reproducing "
                        "a fuzzer failure')")
    p.add_argument("--targets", type=int, default=120)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--chips", type=int, default=2)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--state-root", default="scenario-demo-state",
                   help="per-scenario state dirs + result.json + "
                        "scenario-trace.json (uploaded as a CI artifact "
                        "on failure)")
    p.add_argument("--governor", default="on", choices=("on", "off"),
                   help="off = the pressure drills' NEGATIVE CONTROL: no "
                        "governor, no admission caps — the invariants "
                        "still run and the drill is expected to FAIL "
                        "(CI asserts the non-zero exit)")
    p.add_argument("--store", default="on", choices=("on", "off"),
                   help="off = the store_continuity drill's NEGATIVE "
                        "CONTROL: no fleet store under the root — the "
                        "boundary-gap invariant still runs and the drill "
                        "is expected to FAIL (CI asserts the non-zero "
                        "exit)")
    p.add_argument("--stream", default="on", choices=("on", "off"),
                   help="off = the dashboard_storm drill's NEGATIVE "
                        "CONTROL: no stream hub on the root — the "
                        "subscriptions cannot register, the invariants "
                        "still run and the drill is expected to FAIL "
                        "(CI asserts the non-zero exit)")
    p.add_argument("--alert-suppression", default="on",
                   choices=("on", "off"),
                   help="off = the alert drills' NEGATIVE CONTROL: "
                        "deliberately broken suppression — "
                        "TpuRootLeafDown fires alongside "
                        "TpuRootLeafPartitioned during a one-sided cut, "
                        "the fired-set assertion ('exactly the right "
                        "alerts, and no others') still runs and the "
                        "drill is expected to FAIL (CI asserts the "
                        "non-zero exit)")
    p.add_argument("--log-level", default="warning")
    ns = p.parse_args(argv)
    _utils.setup_logging(ns.log_level)

    if ns.fuzz_replay:
        from tpu_pod_exporter import fuzz

        try:
            seed_s, _, trial_s = ns.fuzz_replay.partition(":")
            seed, trial = int(seed_s), int(trial_s)
        except ValueError:
            p.error(f"--fuzz-replay wants SEED:TRIAL "
                    f"(got {ns.fuzz_replay!r})")
        return fuzz.replay(seed, trial, state_root=ns.state_root)

    if ns.timeline:
        adhoc = Scenario(name="adhoc", timeline=ns.timeline,
                         description="operator-supplied timeline")
        SCENARIOS["adhoc"] = adhoc
        names = ["adhoc"]
    elif ns.scenarios == "all":
        names = list(DEFAULT_SCENARIO_ORDER)
    else:
        names = [s.strip() for s in ns.scenarios.split(",") if s.strip()]
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            p.error(f"unknown scenario(s) {unknown}; "
                    f"known: {', '.join(SCENARIOS)}")
    print(f"scenario engine: {len(names)} scenario(s), {ns.targets} "
          f"targets / {ns.shards} HA shards, seed {ns.seed}"
          + (" — GOVERNOR OFF (negative control)"
             if ns.governor == "off" else "")
          + (" — STORE OFF (negative control)"
             if ns.store == "off" else "")
          + (" — ALERT SUPPRESSION OFF (negative control)"
             if ns.alert_suppression == "off" else ""))
    summary = run_scenarios(names, ns.targets, ns.shards, ns.chips,
                            ns.state_root, ns.seed,
                            governor=ns.governor == "on",
                            store=ns.store == "on",
                            stream=ns.stream == "on",
                            alert_suppression=ns.alert_suppression == "on")
    if not summary["ok"]:
        failed = [n for n, r in summary["scenarios"].items()
                  if not r["ok"]]
        print(f"SCENARIO DEMO FAILED: {failed} — see "
              f"{ns.state_root}/scenario-trace.json", file=sys.stderr)
        return 1
    total = sum(r["wall_s"] for r in summary["scenarios"].values())
    print(f"scenario-demo OK: {len(names)} scenario(s) in {total:.1f}s — "
          f"all per-tick invariants held (zero acked-sample loss, bounded "
          f"staleness, oracle-equal outside windows, no series leaks, "
          f"faults exposition-attributable)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
