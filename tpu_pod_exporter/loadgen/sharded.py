"""Multi-chip sharded training step — the ICI traffic generator.

A data-parallel × tensor-parallel SGD step over a ``jax.sharding.Mesh``:
batch sharded over the ``data`` axis, every layer's weight matrix sharded
over the ``model`` axis. Shardings are declared with ``NamedSharding`` and
the collectives (gradient all-reduce over ``data``, activation collectives
over ``model``) are inserted by XLA — the scaling-book recipe: pick a mesh,
annotate shardings, let the compiler place the communication on ICI.

This is both the driver's multi-chip dry-run target and the instrument for
validating ``tpu_ici_*`` metrics: running it on a real slice produces known
all-reduce traffic per step that the exporter must observe.
"""

from __future__ import annotations

from tpu_pod_exporter.loadgen.workload import init_params, loss_fn


def pick_devices(n: int, platform: str | None = None):
    """n devices. With ``platform`` given, only that platform is consulted.

    Otherwise the choice keys off the *configured* platform list
    (``jax.config.jax_platforms``), not device counts: when the process is
    pinned to CPU (the sanitized dry-run/test path — see
    ``tpu_pod_exporter.jaxenv``), use the virtual CPU mesh; in every other
    configuration use the default platform, so a leaked
    ``xla_force_host_platform_device_count`` can never silently steal a
    real-TPU run onto CPU devices.
    """
    import jax

    if platform is not None:
        devs = jax.devices(platform)
        if len(devs) >= n:
            return devs[:n]
        raise ValueError(f"need {n} {platform} devices, have {len(devs)}")
    configured = (jax.config.jax_platforms or "").split(",")
    if configured[0] == "cpu":
        cpus = jax.devices("cpu")
        if len(cpus) >= n:
            return cpus[:n]
    devs = jax.devices()
    if len(devs) >= n:
        return devs[:n]
    raise ValueError(
        f"need {n} devices, have {len(devs)} on "
        f"{devs[0].platform if devs else 'no'} platform; "
        "set XLA_FLAGS=--xla_force_host_platform_device_count"
    )


def make_mesh(n_devices: int, dp: int | None = None, tp: int | None = None,
              platform: str | None = None):
    """A (data, model) mesh over n devices. dp×tp must equal n; defaults to
    the most-square factorization with dp ≥ tp. ``platform`` pins device
    selection (e.g. ``"tpu"`` on a real slice)."""
    import numpy as np
    from jax.sharding import Mesh

    if dp is None or tp is None:
        tp = 1
        for cand in range(int(n_devices**0.5), 0, -1):
            if n_devices % cand == 0:
                tp = cand
                break
        dp = n_devices // tp
    if dp * tp != n_devices:
        raise ValueError(f"dp({dp}) * tp({tp}) != n_devices({n_devices})")
    devices = np.array(pick_devices(n_devices, platform=platform)).reshape(dp, tp)
    return Mesh(devices, axis_names=("data", "model"))


def sharded_train_step(mesh, width: int = 128, depth: int = 4, batch: int = 32,
                       lr: float = 1e-2):
    """Build (jitted step, sharded params, sharded batch) on the mesh.

    Returns ``step(params, x, y) -> (params, loss)`` with donated params —
    the full training step the driver dry-runs over N virtual devices.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    param_sharding = NamedSharding(mesh, P(None, None, "model"))  # shard width_out
    batch_sharding = NamedSharding(mesh, P("data", None))
    replicated = NamedSharding(mesh, P())

    # Sharded dims must divide evenly; round up so any mesh shape works
    # (dp=3 → batch 32→33, etc.).
    dp = mesh.shape["data"]
    tp = mesh.shape["model"]
    batch = ((batch + dp - 1) // dp) * dp
    width = ((width + tp - 1) // tp) * tp

    params = init_params(width=width, depth=depth)
    params = {"layers": jax.device_put(params["layers"], param_sharding)}
    x = jax.device_put(jnp.ones((batch, width), jnp.bfloat16), batch_sharding)
    y = jax.device_put(jnp.zeros((batch, width), jnp.bfloat16), batch_sharding)

    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p.astype(jnp.float32) - lr * g).astype(p.dtype),
            params,
            grads,
        )
        return new_params, loss

    jitted = jax.jit(
        step,
        in_shardings=({"layers": param_sharding}, batch_sharding, batch_sharding),
        out_shardings=({"layers": param_sharding}, replicated),
        donate_argnums=(0,),
    )
    return jitted, params, (x, y)


def run_dryrun(n_devices: int, steps: int = 1) -> float:
    """Jit + execute the sharded step on an n-device mesh; returns final loss.

    Used by ``__graft_entry__.dryrun_multichip`` and the sharding tests.
    """
    mesh = make_mesh(n_devices)
    step, params, (x, y) = sharded_train_step(mesh)
    loss = None
    for _ in range(steps):
        params, loss = step(params, x, y)
    return float(loss)
