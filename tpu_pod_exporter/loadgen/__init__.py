"""Synthetic TPU workloads for telemetry validation and benchmarking.

The reference has no analog (it observes whatever happens to be running).
For a metrics exporter, a controllable load source is the missing test
instrument: drive the MXU (duty cycle), fill HBM (memory gauges), and push
ICI traffic (link counters) with known shapes, then assert the exporter
reports them. TPU-first by construction: bf16 matmuls sized for the
systolic array, ``lax.scan`` instead of Python loops, static shapes, and
multi-chip variants expressed as shardings over a ``jax.sharding.Mesh`` so
XLA inserts the collectives.
"""

from tpu_pod_exporter.loadgen.workload import (
    burn_step,
    flagship,
    hbm_fill,
    init_params,
)

__all__ = ["burn_step", "flagship", "hbm_fill", "init_params"]
