"""Fleet-mode load generation: N simulated exporters in one process.

The other loadgen modes drive real accelerators; this one drives the
*observability plane at fleet shape*. It runs N lightweight simulated
exporters (real ``Collector`` + ``HistoryStore`` + ``MetricsServer`` over a
scripted ``FakeBackend``, each on its own ephemeral port with a distinct
host topology) inside one process, so tests and CI can stand up a 64-host
slice in a couple of seconds and point a real aggregator at it.

``python -m tpu_pod_exporter.loadgen.fleet`` is the fleet-query acceptance
harness (``make fleet-query-demo``): it builds the fleet, aggregates it,
runs federated ``/api/v1/query_range`` queries through the real HTTP
stack with tracing and persistence ON, kills one target mid-run, and
asserts (1) a full merge with per-target staleness, (2) ``partial: true``
with the remaining targets merged after the kill, and (3) a fleet-query
p99 latency budget — the CI gate for the federated query plane.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
import urllib.request


def _build_exporter(idx: int, chips: int, state_dir: str | None,
                    trace: bool):
    """One simulated exporter: scripted fake backend, real collector,
    history (tiers on), optional persistence, HTTP server on port 0."""
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.server import MetricsServer
    from tpu_pod_exporter.topology import detect_host_topology

    # Distinct, deterministic telemetry per host so merged fleet answers
    # are checkable: duty ramps with the poll index offset by host, HBM
    # grows host-dependently.
    script = FakeChipScript(
        hbm_used_bytes=lambda step, i=idx: float((i + 1) * 2**30 + step * 2**20),
        duty_cycle_percent=lambda step, i=idx: float((i * 7 + step) % 100),
        ici_bytes_per_step=1e6,
    )
    backend = FakeBackend(chips=chips, script=script)
    topo = detect_host_topology(
        env={}, accelerator="v5p-64", slice_name="sim-slice",
        host=f"sim-host-{idx:02d}", worker_id=str(idx),
    )
    store = SnapshotStore()
    history = HistoryStore(capacity=256, max_series=2048, retention_s=0.0)
    trace_store = tracer = None
    if trace:
        from tpu_pod_exporter.trace import Tracer, TraceStore

        trace_store = TraceStore(max_traces=16)
        tracer = Tracer(trace_store, slow_poll_s=0.0)
    persister = None
    if state_dir:
        from tpu_pod_exporter.persist import StatePersister

        persister = StatePersister(
            state_dir, history=history,
            exposition_fn=lambda s=store: s.current(),
        )
        persister.start()
    collector = Collector(
        backend, FakeAttribution(), store, topology=topo,
        history=history, tracer=tracer, persister=persister,
    )
    server = MetricsServer(store, host="127.0.0.1", port=0,
                           history=history, trace=trace_store)
    server.start()
    return {
        "idx": idx,
        "collector": collector,
        "history": history,
        "server": server,
        "trace_store": trace_store,
        "persister": persister,
        "target": f"127.0.0.1:{server.port}",
        "alive": True,
    }


class FleetSim:
    """N simulated exporters, ticked from the caller's thread (scripted
    scenario timelines need deterministic poll ordering, not N loops)."""

    def __init__(self, n_targets: int, chips: int = 4,
                 persist: bool = True, trace: bool = True,
                 state_root: str | None = None) -> None:
        self._tmp = None
        if persist and state_root is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="fleet-sim-")
            state_root = self._tmp.name
        self.state_root = state_root
        self.exporters = [
            _build_exporter(
                i, chips,
                f"{state_root}/target-{i:02d}" if persist and state_root else None,
                trace,
            )
            for i in range(n_targets)
        ]
        self.chips = chips

    @property
    def targets(self) -> tuple[str, ...]:
        return tuple(e["target"] for e in self.exporters)

    def tick(self) -> None:
        for e in self.exporters:
            if e["alive"]:
                e["collector"].poll_once()

    def kill(self, idx: int) -> str:
        """Stop one exporter's HTTP server (its port starts refusing —
        the clean-death shape; wedges are chaos.py's job)."""
        e = self.exporters[idx]
        if e["alive"]:
            e["alive"] = False
            e["server"].stop()
        return e["target"]

    def scrape_spans_recorded(self) -> int:
        """Node-side /api/v1 serve spans recorded under REMOTE (fleet
        query) trace contexts — proof the traceparent propagated."""
        total = 0
        for e in self.exporters:
            ts = e["trace_store"]
            if ts is not None:
                total += len(ts.scrapes(64))
        return total

    def close(self) -> None:
        for e in self.exporters:
            if e["alive"]:
                e["server"].stop()
            if e["persister"] is not None:
                e["persister"].close()
            e["collector"].close()
        if self._tmp is not None:
            self._tmp.cleanup()


def _get_json(url: str, timeout_s: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — loopback demo
        return json.loads(resp.read())


def _percentile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    return ys[min(int(q * (len(ys) - 1) + 0.5), len(ys) - 1)]


def run_demo(n_targets: int, chips: int, polls: int, interval_s: float,
             queries: int, budget_ms: float, kill_one: bool,
             persist: bool) -> dict:
    """The acceptance scenario; returns a result dict with ``ok``."""
    from tpu_pod_exporter.aggregate import SliceAggregator
    from tpu_pod_exporter.fleet import FleetQueryPlane
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.persist import BreakerStateFile
    from tpu_pod_exporter.server import MetricsServer
    from tpu_pod_exporter.trace import Tracer, TraceStore

    result: dict = {"targets": n_targets, "chips": chips, "ok": False,
                    "tracing": True, "persistence": persist}
    sim = FleetSim(n_targets, chips=chips, persist=persist, trace=True)
    agg_server = None
    fleet = None
    agg = None
    try:
        for _ in range(polls):
            sim.tick()
            time.sleep(interval_s)

        trace_store = TraceStore(max_traces=128)
        store = SnapshotStore()
        agg = SliceAggregator(
            sim.targets, store, timeout_s=1.0,
            tracer=Tracer(trace_store, slow_poll_s=0.0, root_name="round"),
            breaker_store=(
                BreakerStateFile(f"{sim.state_root}/agg-breakers.json")
                if persist and sim.state_root else None
            ),
        )
        fleet = FleetQueryPlane(
            sim.targets, timeout_s=1.0, breakers=agg.breakers,
            tracer=Tracer(trace_store, slow_poll_s=0.0, root_name="query"),
            generation_fn=lambda: agg.rounds,
        )
        agg.set_fleet(fleet)
        agg.poll_once()
        agg_server = MetricsServer(store, host="127.0.0.1", port=0,
                                   fleet=fleet, trace=trace_store,
                                   debug_vars=agg.debug_vars)
        agg_server.start()
        base = f"http://127.0.0.1:{agg_server.port}"

        # --- full merge: one query answers for the whole fleet ----------
        now = time.time()
        # .3f, not .0f: rounding `end` to whole seconds can land it BEFORE
        # the just-primed samples and fake an empty fleet.
        doc = _get_json(
            f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
            f"&start={now - 120:.3f}&end={now:.3f}&step=1"
        )
        result["full_merge"] = {
            "merged_series": doc["fleet"]["merged_series"],
            "ok_targets": doc["fleet"]["ok"],
            "partial": doc["partial"],
            "staleness_present": all(
                st.get("staleness_s") is not None
                for st in doc["targets"].values()
            ),
        }
        if doc["partial"] or doc["fleet"]["ok"] != n_targets:
            result["error"] = f"expected full merge from {n_targets}: {doc['fleet']}"
            return result
        if doc["fleet"]["merged_series"] != n_targets * chips:
            result["error"] = (
                f"merged {doc['fleet']['merged_series']} series, "
                f"expected {n_targets * chips}"
            )
            return result
        if not result["full_merge"]["staleness_present"]:
            result["error"] = "per-target staleness missing"
            return result

        # --- p99 latency budget (cache-busted: every query a fresh grid) -
        metrics = ("tpu_tensorcore_duty_cycle_percent", "tpu_hbm_used_bytes")
        tails: list[float] = []
        for q in range(queries):
            sim.tick()  # keep data moving while querying
            now = time.time()
            url = (
                f"{base}/api/v1/query_range?metric={metrics[q % 2]}"
                f"&start={now - 60 - q:.3f}&end={now:.3f}&step=1"
            )
            t0 = time.perf_counter()
            doc = _get_json(url)
            tails.append(time.perf_counter() - t0)
            if doc["partial"]:
                result["error"] = f"unexpected partial at query {q}: {doc['targets']}"
                return result
        p99 = _percentile(tails, 0.99)
        result["query_p99_ms"] = round(p99 * 1e3, 2)
        result["query_p50_ms"] = round(_percentile(tails, 0.5) * 1e3, 2)
        result["budget_ms"] = budget_ms

        # --- traceparent propagation: node-side serve spans joined -------
        result["node_side_query_spans"] = sim.scrape_spans_recorded()
        if result["node_side_query_spans"] == 0:
            result["error"] = "no node-side /api/v1 spans recorded (traceparent lost)"
            return result

        # --- kill one target mid-query → partial, remainder merged -------
        if kill_one:
            victim_idx = n_targets // 2
            killed = {}

            def _kill() -> None:
                time.sleep(0.002)  # land inside the fan-out below
                killed["target"] = sim.kill(victim_idx)

            # New aggregator round first: the result cache keys on the
            # round generation, and the kill assertions below must observe
            # live fan-outs, not a pre-kill cached envelope.
            agg.poll_once()
            killer = threading.Thread(target=_kill, name="fleet-demo-kill",
                                      daemon=True)
            killer.start()
            now = time.time()
            _get_json(
                f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
                f"&start={now - 120:.3f}&end={now:.3f}&step=1"
            )  # the mid-kill query: partial OR full depending on the race
            killer.join(timeout=5)
            agg.poll_once()  # next round: fresh generation after the kill
            now = time.time()
            doc = _get_json(
                f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
                f"&start={now - 120:.3f}&end={now:.3f}&step=1"
            )
            result["after_kill"] = {
                "killed": killed.get("target"),
                "partial": doc["partial"],
                "ok_targets": doc["fleet"]["ok"],
                "merged_series": doc["fleet"]["merged_series"],
                "victim_state": doc["targets"][killed["target"]]["state"],
            }
            if not doc["partial"]:
                result["error"] = "killed target did not yield partial=true"
                return result
            if doc["fleet"]["ok"] != n_targets - 1:
                result["error"] = (
                    f"expected {n_targets - 1} ok targets after kill, "
                    f"got {doc['fleet']['ok']}"
                )
                return result
            if doc["fleet"]["merged_series"] != (n_targets - 1) * chips:
                result["error"] = (
                    f"expected {(n_targets - 1) * chips} merged series "
                    f"after kill, got {doc['fleet']['merged_series']}"
                )
                return result

        if p99 > budget_ms / 1e3:
            result["error"] = (
                f"fleet query p99 {p99 * 1e3:.1f}ms exceeds budget "
                f"{budget_ms:.0f}ms"
            )
            return result
        result["ok"] = True
        return result
    finally:
        if agg_server is not None:
            agg_server.stop()
        if fleet is not None:
            fleet.close()
        if agg is not None:
            agg.close()
        sim.close()


# --- Sharded aggregation tree harness (make shard-demo) ----------------------
#
# The fleet-query demo above runs REAL per-target collectors; at 1000
# targets that shape is all overhead and no signal. This harness keeps the
# leaf/root tier fully real (real LeafAggregator/RootAggregator processes-
# in-threads, real HTTP between every tier) and makes only the NODE tier
# synthetic: one ThreadingHTTPServer serving a deterministic exposition
# per target path, so a 1000-target fleet stands up in milliseconds and a
# flat single-aggregator ORACLE over the same scrape set is cheap enough
# to assert byte-level rollup equality at every checkpoint.


class SynthTargetFarm:
    """N synthetic node targets behind ONE HTTP server.

    ``/t/<idx>/metrics`` answers a deterministic exposition for target
    ``idx`` at the farm's current round — values are pure functions of
    (idx, round), so every scraper (leaf A, its HA twin, the oracle) that
    scrapes within one farm round sees identical bytes, which is what
    makes exact root-vs-oracle comparison possible. ``tick()`` advances
    the round (HBM grows, duty cycles shift). Targets in ``dead`` answer
    503 — permanently-down hosts for the breaker-carryover assertions."""

    def __init__(self, n_targets: int, chips: int = 2, n_slices: int = 8,
                 host: str = "127.0.0.1", port: int = 0,
                 gpu_slices: int = 0) -> None:
        import http.server

        self.n_targets = n_targets
        self.chips = chips
        self.n_slices = n_slices
        # Mixed fleet: the LAST gpu_slices slices are GPU node pools —
        # their targets publish the gpu_* node surface (backend/nvml.py's
        # namespace) instead of tpu_*, still pure functions of
        # (idx, round), so the flat oracle sees identical bytes and
        # per-family root-vs-oracle equality stays exact.
        if not 0 <= gpu_slices <= n_slices:
            raise ValueError("gpu_slices must be within [0, n_slices]")
        self.gpu_slices = gpu_slices
        self.round = 0
        self.dead: set[int] = set()
        self.allocated = n_targets  # grows via add_targets
        # Scenario-engine knobs (tpu_pod_exporter.loadgen.scenario):
        # `hot` targets publish spiked duty/HBM (the hotspot(pod) event —
        # values stay pure functions of (idx, round), so the oracle sees
        # the same spike and rollup equality is preserved); `pod_gen`
        # rotates every target's pod name (the label-churn half of a
        # churn storm — workload label sets turn over wholesale).
        self.hot: set[int] = set()
        self.pod_gen = 0
        # Dashboard-storm realism knob: a target's /api/v1 value advances
        # only every api_churn-th round for it (staggered by idx), so per
        # round only ~1/api_churn of the fleet's series change — the
        # changed-series-only delta stream has something to be sparse
        # about. 1 (default) = every value changes every round, the
        # pre-existing behavior every other harness assumes.
        self.api_churn = 1
        self._api_epoch = time.time()
        farm = self

        class _FarmHandler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self) -> None:  # noqa: N802 — stdlib API
                path, _, query = self.path.partition("?")
                parts = path.split("/")
                # /t/<idx>/metrics
                if (len(parts) == 4 and parts[1] == "t"
                        and parts[3] == "metrics"):
                    try:
                        idx = int(parts[2])
                    except ValueError:
                        idx = -1
                    if 0 <= idx < farm.allocated and idx not in farm.dead:
                        body = farm.body(idx).encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/plain; charset=utf-8")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                # /t/<idx>/api/v1/<route> — a minimal node-side history
                # answer so the federated query plane (leaf FleetQueryPlane
                # → RootQueryPlane) can be exercised over real HTTP at
                # fleet shape (the scenario engine's query-seam drills).
                if (len(parts) >= 6 and parts[1] == "t" and parts[3] == "api"
                        and parts[4] == "v1"):
                    try:
                        idx = int(parts[2])
                    except ValueError:
                        idx = -1
                    if 0 <= idx < farm.allocated and idx not in farm.dead:
                        body = farm.api_body(idx, parts[5], query).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, fmt: str, *args) -> None:
                pass  # 3k requests/round; access logs would drown the demo

        class _FarmServer(http.server.ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 256

        self._httpd = _FarmServer((host, port), _FarmHandler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="tpu-synth-farm", daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def url(self, idx: int) -> str:
        return f"http://127.0.0.1:{self.port}/t/{idx}/metrics"

    def targets(self, n: int | None = None) -> tuple[str, ...]:
        return tuple(self.url(i) for i in range(n or self.n_targets))

    def add_targets(self, k: int) -> tuple[str, ...]:
        """Allocate k new target indices (a scale-up churn wave)."""
        start = self.allocated
        self.allocated += k
        return tuple(self.url(i) for i in range(start, self.allocated))

    def slice_targets(self, sl: int) -> tuple[int, ...]:
        """Target indices of one slice (the preempt(slice-N) victim set)."""
        return tuple(
            i for i in range(self.allocated) if i % self.n_slices == sl
        )

    def pod_of(self, idx: int) -> str:
        return f"job-{(idx + self.pod_gen) % 31}"

    def family_of_slice(self, sl: int) -> str:
        return "gpu" if sl >= self.n_slices - self.gpu_slices else "tpu"

    def family_of(self, idx: int) -> str:
        return self.family_of_slice(idx % self.n_slices)

    def tick(self) -> None:
        self.round += 1

    def body(self, idx: int) -> str:
        """Deterministic exposition for one target at the current round.
        Shapes every family the aggregator tier folds: per-chip presence/
        HBM(-or-GPU-memory)/duty(-or-utilization)/ICI, host identity with
        a multislice group, pod rollups. GPU-slice targets publish the
        gpu_* node surface — no ICI (GPUs serve none here) and no
        multislice group (a TPU-fabric concept)."""
        r = self.round
        sl = idx % self.n_slices
        gpu = self.family_of_slice(sl) == "gpu"
        host = f"host-{idx:04d}"
        accel = "a100-sim" if gpu else "v5p-sim"
        base = (
            f'accelerator="{accel}",slice_name="slice-{sl}",host="{host}",'
            f'worker_id="{idx}"'
        )
        pod = self.pod_of(idx)
        hot = idx in self.hot
        lines: list[str] = []
        hbm_total = float((80 if gpu else 96) * 2**30)
        pod_hbm = 0.0
        p = "gpu" if gpu else "tpu"
        duty_name = ("gpu_utilization_percent" if gpu
                     else "tpu_tensorcore_duty_cycle_percent")
        for c in range(self.chips):
            cl = (f'chip_id="{c}",device_path="",{base},pod="{pod}",'
                  f'namespace="sim",container="worker"')
            hbm = float((idx + 1) * 2**20 + r * 65536 + c * 4096)
            if hot:
                # A hotspot pod near-fills its HBM (additive, not a
                # factor: normal values scale with idx, and a hotspot
                # must dominate the workload rollups at ANY fleet size).
                hbm += float(64 * 2**30)
            pod_hbm += hbm
            duty = float((idx * 7 + c * 13 + r) % 100)
            if hot:
                duty = 90.0 + float((idx * 7 + c * 13 + r) % 10)
            kind = 'device_kind="A100-sim"' if gpu else 'device_kind=""'
            lines.append(f'{p}_chip_info{{{cl},{kind},coords=""}} 1')
            lines.append(f'{p}_hbm_used_bytes{{{cl}}} {hbm:.1f}')
            lines.append(f'{p}_hbm_total_bytes{{{cl}}} {hbm_total:.1f}')
            lines.append(f'{duty_name}{{{cl}}} {duty:.1f}')
            if not gpu:
                lines.append(
                    f'tpu_ici_link_bandwidth_bytes_per_second{{{cl},link="0"}} '
                    f'{float((idx + r) % 7) * 1e6:.1f}')
        if gpu:
            lines.append(
                f'tpu_host_info{{{base},multislice_group="",num_slices=""}} 1')
            lines.append(
                f'gpu_pod_chip_count{{pod="{pod}",namespace="sim",{base}}} '
                f'{self.chips}')
            lines.append(
                f'gpu_pod_memory_used_bytes{{pod="{pod}",namespace="sim",'
                f'{base}}} {pod_hbm:.1f}')
        else:
            lines.append(
                f'tpu_host_info{{{base},multislice_group="ms-{sl % 2}",'
                f'num_slices="{(self.n_slices - self.gpu_slices + 1) // 2}"}} 1')
            lines.append(
                f'tpu_pod_chip_count{{pod="{pod}",namespace="sim",{base}}} '
                f'{self.chips}')
            lines.append(
                f'tpu_pod_hbm_used_bytes{{pod="{pod}",namespace="sim",{base}}} '
                f'{pod_hbm:.1f}')
        return "\n".join(lines) + "\n"

    def api_body(self, idx: int, route: str, query: str) -> str:
        """One deterministic /api/v1 JSON answer for a target: a single
        per-host series row in the node-local window_stats/query_range
        shape (labels + stats + last_sample_wall_ts — the fields the
        federated merge and its freshest-wins keying consume)."""
        import urllib.parse

        params = dict(urllib.parse.parse_qsl(query))
        metric = params.get("metric", "tpu_hbm_used_bytes")
        sl = f"slice-{idx % self.n_slices}"
        want_slice = params.get("match[slice_name]")
        if want_slice and want_slice != sl:
            # Label-matched queries cut the row set the way real node
            # history does — a dashboard panel watching one slice must
            # not stream every host in the fleet.
            if route == "series":
                return json.dumps([])
            return json.dumps({"status": "ok", "data": {"result": []}})
        churn = max(self.api_churn, 1)
        # The value's round component advances when (round + idx) % churn
        # wraps — staggered per target, pure function of (idx, round).
        vround = self.round - (self.round + idx) % churn
        value = float((idx + 1) * 2**20 + vround * 65536)
        row = {
            "metric": metric,
            "labels": {"host": f"host-{idx:04d}",
                       "slice_name": sl},
            # samples rides the VALUE round too: a live `self.round` here
            # would mark every row changed every round and defeat the
            # api_churn sparsity the delta drills measure.
            "stats": {"last": value, "min": value, "max": value,
                      "mean": value, "samples": max(vround, 1)},
            # Deterministic per (idx, value round): a row whose value did
            # not advance is byte-identical across polls, so the delta
            # stream ships ONLY genuinely-changed series (a wall-clock
            # stamp here would mark every row changed every round).
            "last_sample_wall_ts": round(self._api_epoch + vround, 3),
        }
        if route == "series":
            return json.dumps([row])
        return json.dumps({"status": "ok", "data": {"result": [row]}})

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def _node_addr_of(target: str) -> str:
    """Farm target URL (``…/t/<idx>/metrics``) → partition-switchboard
    address ``node:<idx>`` (chaos.PartitionState selectors)."""
    parts = target.split("/")
    if len(parts) >= 2 and "t" in parts:
        try:
            return f"node:{int(parts[parts.index('t') + 1])}"
        except (ValueError, IndexError):
            pass
    return "node:?"


class _SimLeaf:
    """One in-process leaf: a real :class:`~tpu_pod_exporter.shard.\
LeafAggregator` plus its own real HTTP server (the root scrapes it over
    the wire). ``kill()`` is SIGKILL-shaped from every observer's view:
    the HTTP port stops answering and the in-flight round is never served;
    ``restart`` (see _ShardSim.restart) builds a FRESH leaf on the same
    state dir and the same port."""

    def __init__(self, name: str, shard_id: str, leaf_id: str, smap,
                 targets_file: str, state_dir: str, hook,
                 round_ref: list[int], timeout_s: float,
                 port: int = 0, net=None,
                 breaker_backoff_s: float = 30.0,
                 breaker_backoff_max_s: float = 60.0,
                 query_plane: bool = False) -> None:
        from tpu_pod_exporter.aggregate import default_fetch
        from tpu_pod_exporter.chaos import PartitionedFetch
        from tpu_pod_exporter.metrics import SnapshotStore
        from tpu_pod_exporter.persist import BreakerStateFile, ShardMapFile
        from tpu_pod_exporter.server import MetricsServer
        from tpu_pod_exporter.shard import LeafAggregator

        self.name = name
        self.alive = True
        self.hook = hook
        self._round_ref = round_ref
        self._calls = 0
        self._lock = threading.Lock()
        self._default_fetch = default_fetch
        self.store = SnapshotStore()
        # The leaf→node scrape seam: scenario partitions are injected by
        # wrapping the SAME fetch the leaf would use anyway (chaos.
        # PartitionedFetch) — the aggregator cannot tell chaos from a
        # genuinely unreachable node, which is the point.
        fetch = self._fetch
        if net is not None:
            fetch = PartitionedFetch(
                net, f"leaf:{name}", _node_addr_of, self._fetch)
        self.agg = LeafAggregator(
            shard_id, leaf_id, smap,
            shard_map_store=ShardMapFile(f"{state_dir}/{name}-shardmap.json"),
            targets_file=targets_file,
            store=self.store,
            timeout_s=timeout_s,
            fetch=fetch,
            breaker_failures=2,
            # Long by default: the shard-demo's quarantine must outlive the
            # demo; the scenario engine shortens it so healed partitions
            # re-admit their targets within the settle budget.
            breaker_backoff_s=breaker_backoff_s,
            breaker_backoff_max_s=breaker_backoff_max_s,
            breaker_store=BreakerStateFile(
                f"{state_dir}/{name}-breakers.json"),
        )
        # The leaf's federated /api/v1 plane — the fan-out seam of the
        # two-level query path, partitioned through the SAME switchboard.
        self.fleet = None
        if query_plane:
            from tpu_pod_exporter.fleet import (
                FleetQueryPlane,
                default_api_fetch,
            )

            api_fetch = default_api_fetch
            if net is not None:
                def _plain_api(url: str, timeout_s: float) -> dict:
                    return default_api_fetch(url, timeout_s)

                api_fetch = PartitionedFetch(
                    net, f"leaf:{name}", _node_addr_of, _plain_api)
            self.fleet = FleetQueryPlane(
                self.agg.targets,
                timeout_s=timeout_s,
                fetch=api_fetch,
                breakers=self.agg.breakers,
                generation_fn=lambda: self.agg.rounds,
                targets_fn=lambda: self.agg.targets,
            )
        self.server = MetricsServer(self.store, host="127.0.0.1", port=port,
                                    ready_detail_fn=self.agg.ready_detail,
                                    fleet=self.fleet)
        self.server.start()
        self.addr = f"127.0.0.1:{self.server.port}"

    def _fetch(self, target: str, timeout_s: float) -> str:
        with self._lock:
            idx = self._calls
            self._calls += 1
        if self.hook is not None:
            self.hook.on_scrape(self.name, self._round_ref[0], idx)
        if not self.alive:
            raise ConnectionError("leaf dead (chaos kill)")
        return self._default_fetch(target, timeout_s)

    def begin_round(self) -> None:
        with self._lock:
            self._calls = 0

    def kill(self) -> None:
        if self.alive:
            self.alive = False
            self.server.stop()

    def close(self) -> None:
        if self.alive:
            self.server.stop()
            self.alive = False
        if self.fleet is not None:
            self.fleet.close()
        self.agg.close()

    def discard(self) -> None:
        """Tear down WITHOUT the graceful hooks: agg.close() force-saves
        breaker state, and a SIGKILLed process gets no close() — the
        demo's carryover assertion must prove the TRANSITION-TIME saves
        alone, or a regression there would be masked by this very
        harness. Only the worker threads are reaped."""
        if self.alive:
            self.server.stop()
            self.alive = False
        self.agg._pool.shutdown(wait=False)


class _ShardSim:
    """The whole tree, in one process: synthetic target farm, real leaf
    tier (HA pairs, each with HTTP server + state dir), real root, plus a
    flat single-aggregator ORACLE over the same targets file. Rounds are
    caller-driven (the scenario timeline needs deterministic ordering);
    leaves poll concurrently, the way independent processes would."""

    def __init__(self, n_targets: int, shards: int, ha: bool,
                 chips: int, state_root: str, timeout_s: float = 5.0,
                 net=None, stale_serve_s: float = 0.0,
                 leaf_breaker_backoff_s: float = 30.0,
                 leaf_breaker_backoff_max_s: float = 60.0,
                 root_breaker_backoff_s: float = 10.0,
                 root_breaker_backoff_max_s: float = 120.0,
                 n_slices: int = 8, query_plane: bool = False,
                 store_factory=None, gpu_slices: int = 0) -> None:
        import os

        from tpu_pod_exporter.aggregate import SliceAggregator, default_fetch
        from tpu_pod_exporter.chaos import PartitionedFetch
        from tpu_pod_exporter.metrics import SnapshotStore
        from tpu_pod_exporter.persist import ShardMapFile
        from tpu_pod_exporter.shard import (
            ShardMap,
            default_shards,
        )

        os.makedirs(state_root, exist_ok=True)
        self.state_root = state_root
        self.timeout_s = timeout_s
        self.net = net
        self.farm = SynthTargetFarm(n_targets, chips=chips,
                                    n_slices=n_slices,
                                    gpu_slices=gpu_slices)
        self.targets_file = os.path.join(state_root, "targets.txt")
        self.write_targets(self.farm.targets())
        self.smap = ShardMap(default_shards(shards))
        self.round_ref = [0]
        self.hook = None  # set via arm_timeline before the driver runs
        self.leaves: dict[str, _SimLeaf] = {}
        self._leaf_meta: dict[str, tuple[str, str, int]] = {}
        self._leaf_kw = {
            "net": net,
            "breaker_backoff_s": leaf_breaker_backoff_s,
            "breaker_backoff_max_s": leaf_breaker_backoff_max_s,
            "query_plane": query_plane,
        }
        self.topology: dict[str, tuple[str, ...]] = {}
        for si in range(shards):
            shard_id = f"shard-{si}"
            addrs = []
            for suffix in ("a", "b") if ha else ("a",):
                name = f"{si}{suffix}"
                leaf = _SimLeaf(
                    name, shard_id, name, self.smap, self.targets_file,
                    state_root, None, self.round_ref, timeout_s,
                    **self._leaf_kw,
                )
                self.leaves[name] = leaf
                self._leaf_meta[name] = (shard_id, name, leaf.server.port)
                addrs.append(leaf.addr)
            self.topology[shard_id] = tuple(addrs)
        self.root_store = SnapshotStore()
        # The root→leaf scrape seam, same PartitionedFetch wrapper as the
        # leaf→node seam (addresses are fixed, so addr→leaf is a dict).
        self.leaf_addr_of = {
            leaf.addr: f"leaf:{name}" for name, leaf in self.leaves.items()
        }
        root_fetch = default_fetch
        if net is not None:
            root_fetch = PartitionedFetch(
                net, "root",
                lambda t: self.leaf_addr_of.get(t, "leaf:?"),
                default_fetch,
            )
        # Root construction goes through _build_root so root_restart
        # events (the store-continuity drill) can rebuild a FRESH root —
        # and a fresh FleetStore replaying the same dir — mid-run. The
        # SnapshotStore is shared across rebuilds: the engine's root
        # MetricsServer keeps serving the last published (stale) view
        # through the downtime, exactly like a real root's kubelet gap.
        self._store_factory = store_factory
        self._root_kwargs = dict(
            timeout_s=timeout_s,
            fetch=root_fetch,
            targets_file=self.targets_file,
            shard_map=self.smap,
            breaker_backoff_s=root_breaker_backoff_s,
            breaker_backoff_max_s=root_breaker_backoff_max_s,
            stale_serve_s=stale_serve_s,
        )
        self._root_shardmap_path = os.path.join(
            state_root, "root-shardmap.json")
        self.root_down = False
        self.root = self._build_root()
        # The correctness oracle: ONE flat aggregator over the same
        # targets file (breakers off so it re-scrapes dead targets every
        # round, matching what "a target is down" means to the fleet).
        self.oracle_store = SnapshotStore()
        self.oracle = SliceAggregator(
            (), self.oracle_store, timeout_s=timeout_s,
            breaker_failures=0, targets_file=self.targets_file,
        )
        self._pool = None

    # -------------------------------------------------------------- plumbing

    def _build_root(self):
        from tpu_pod_exporter.persist import ShardMapFile
        from tpu_pod_exporter.shard import RootAggregator

        fleet_store = (self._store_factory()
                       if self._store_factory is not None else None)
        return RootAggregator(
            self.topology, self.root_store,
            shard_map_store=ShardMapFile(self._root_shardmap_path),
            fleet_store=fleet_store,
            **self._root_kwargs,
        )

    def kill_root(self) -> None:
        """SIGKILL-shaped root death: no graceful close (a killed process
        force-saves nothing — the store must prove its per-append WAL
        durability alone). Worker threads are reaped; the store's file
        handles close (flushed appends are already in the page cache,
        which survives a process kill)."""
        if self.root_down:
            return
        self.root_down = True
        self.root._pool.shutdown(wait=False)
        if self.root._fleet_store is not None:
            for buf in self.root._fleet_store._buffers:
                buf.close()

    def restart_root(self) -> None:
        """A fresh root on the same state dirs; with a store factory the
        fresh FleetStore replays its tiers from disk — the continuity
        boundary the store_continuity drill queries across."""
        self.root = self._build_root()
        self.root_down = False

    def write_targets(self, targets) -> None:
        import os

        from tpu_pod_exporter.persist import atomic_write

        atomic_write(
            self.targets_file, ("\n".join(targets) + "\n").encode("utf-8"))
        # mtime granularity on some filesystems is 1s; the reload check is
        # mtime-based, and demo rounds are subsecond — force a visible bump.
        st = os.stat(self.targets_file)
        os.utime(self.targets_file, (st.st_atime, st.st_mtime + 2.0))

    def arm_timeline(self, timeline: str) -> None:
        from tpu_pod_exporter.chaos import LeafKillHook, parse_leaf_timeline

        self.hook = LeafKillHook(
            parse_leaf_timeline(timeline),
            kill_fn=lambda name: self.leaves[name].kill(),
            restart_fn=self.restart,
        )
        for leaf in self.leaves.values():
            leaf.hook = self.hook

    def restart(self, name: str) -> None:
        """A fresh leaf on the same state dir AND the same port (the root's
        topology is fixed addresses) — the restart half of the kill event."""
        shard_id, leaf_id, port = self._leaf_meta[name]
        old = self.leaves[name]
        # discard(), never close(): the dead leaf must leave behind only
        # what its transition-time saves already fsynced (see discard).
        old.discard()
        self.leaves[name] = _SimLeaf(
            name, shard_id, leaf_id, self.smap, self.targets_file,
            self.state_root, self.hook, self.round_ref, self.timeout_s,
            port=port, **self._leaf_kw,
        )

    def run_round(self) -> dict:
        """One driver round: advance the farm, fire timeline events, poll
        every live leaf concurrently, then the root. Returns timings."""
        from concurrent.futures import ThreadPoolExecutor

        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max(len(self.leaves), 1),
                thread_name_prefix="tpu-shard-sim",
            )
        self.farm.tick()
        r = self.round_ref[0]
        if self.net is not None:
            # Flapping cuts key off the driver round (chaos.Cut).
            self.net.advance(r)
        if self.hook is not None:
            self.hook.begin_round(r)
        t0 = time.perf_counter()
        live = [l for l in self.leaves.values() if l.alive]
        for leaf in live:
            leaf.begin_round()
        list(self._pool.map(lambda l: l.agg.poll_once(), live))
        t1 = time.perf_counter()
        if not self.root_down:
            self.root.poll_once()
        t2 = time.perf_counter()
        self.round_ref[0] = r + 1
        return {"leaf_tier_s": t1 - t0, "root_s": t2 - t1,
                "full_s": t2 - t0}

    def poll_leaves(self, names) -> None:
        for name in names:
            leaf = self.leaves[name]
            if leaf.alive:
                leaf.begin_round()
                leaf.agg.poll_once()

    def root_body(self) -> str:
        return self.root_store.current().encode().decode()

    def oracle_body(self) -> str:
        self.oracle.poll_once()
        return self.oracle_store.current().encode().decode()

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        for leaf in self.leaves.values():
            leaf.close()
        self.root.close()
        self.oracle.close()
        self.farm.close()


# Rollup families the oracle comparison covers — everything emit_rollups
# produces plus the per-target passthrough both tiers publish.
_ORACLE_FAMILIES = (
    "tpu_fleet_family_hosts_reporting",
    "tpu_fleet_family_chip_count",
    "tpu_fleet_family_hbm_used_bytes",
    "tpu_fleet_family_hbm_total_bytes",
    "tpu_slice_hosts_reporting",
    "tpu_slice_chip_count",
    "tpu_slice_hbm_used_bytes",
    "tpu_slice_hbm_total_bytes",
    "tpu_slice_hbm_used_percent",
    "tpu_slice_tensorcore_duty_cycle_avg_percent",
    "tpu_slice_ici_bytes_per_second",
    "tpu_multislice_slices_reporting",
    "tpu_multislice_expected_slices",
    "tpu_multislice_hosts_reporting",
    "tpu_multislice_chip_count",
    "tpu_multislice_hbm_used_bytes",
    "tpu_multislice_ici_bytes_per_second",
    "tpu_workload_chip_count",
    "tpu_workload_hbm_used_bytes",
    "tpu_workload_hosts",
    "tpu_aggregator_target_up",
)


def _family_values(text: str, families=_ORACLE_FAMILIES) -> dict:
    from tpu_pod_exporter.metrics.parse import parse_families

    fams = parse_families(text)
    out = {}
    for name in families:
        for s in fams.get(name, ()):
            out[(name, tuple(sorted(s.labels.items())))] = s.value
    return out


def _compare_oracle(root_map: dict, oracle_map: dict) -> list[str]:
    """Root-vs-flat-oracle rollup diff (empty = identical modulo float
    summation order, hence the 1e-9 relative tolerance)."""
    import math

    problems = []
    missing = set(oracle_map) - set(root_map)
    extra = set(root_map) - set(oracle_map)
    for k in sorted(missing)[:5]:
        problems.append(f"missing from root: {k}")
    for k in sorted(extra)[:5]:
        problems.append(f"extra at root: {k}")
    for k in oracle_map:
        if k in root_map and not math.isclose(
            root_map[k], oracle_map[k], rel_tol=1e-9, abs_tol=1e-9
        ):
            problems.append(
                f"value drift {k}: root={root_map[k]!r} "
                f"oracle={oracle_map[k]!r}")
            if len(problems) > 8:
                break
    return problems


def run_shard_demo(n_targets: int, shards: int, ha: bool, chips: int,
                   churn: int, round_budget_s: float, stale_budget_s: float,
                   state_root: str, gpu_slices: int = 2) -> dict:
    """The sharded-tree acceptance scenario (``make shard-demo``):

    1. prime the tree; two permanently-dead targets teach the owning
       leaves a quarantine (breaker carryover fodder);
    2. baseline: root rollups equal the flat single-aggregator oracle;
    3. freshest-wins: every HA pair is staggered one farm round apart —
       the root must publish the FRESHER half's values;
    4. kill one HA leaf MID-ROUND (chaos LeafKillHook) → zero series
       lost vs the pre-kill layout, values still oracle-equal, twin
       staleness within budget;
    5. restart the leaf on its state dir → quarantines carried over,
       leaf_up recovers;
    6. churn wave: remove/add ``churn`` targets via the targets file →
       assignment moves ≤ changed + targets/shards, every tier reshards
       live (no restarts), rollups oracle-equal again;
    7. round-time budget over the whole run.
    """
    import math

    from tpu_pod_exporter.metrics.parse import parse_families
    from tpu_pod_exporter.shard import count_moves

    result: dict = {
        "ok": False, "targets": n_targets, "shards": shards, "ha": ha,
        "chips": chips, "gpu_slices": gpu_slices,
    }
    if not ha:
        result["error"] = "shard demo needs --ha (the failover is the point)"
        return result
    # Mixed fleet by default (gpu_slices of the farm's 8 slices are GPU
    # node pools): both device families ride one tree, and the oracle
    # comparison below covers the per-family rollups too.
    sim = _ShardSim(n_targets, shards, ha, chips, state_root,
                    gpu_slices=gpu_slices)
    timings: list[dict] = []
    try:
        # Two permanently-dead targets (and their leaf quarantines).
        sim.farm.dead = {0, 1}
        dead_urls = [sim.farm.url(0), sim.farm.url(1)]
        victim_shard = sim.smap.assign(dead_urls[0])
        victim = f"{victim_shard.rsplit('-', 1)[1]}a"
        twin = f"{victim_shard.rsplit('-', 1)[1]}b"
        shard_size = sum(
            1 for t in sim.farm.targets()
            if sim.smap.assign(t) == victim_shard
        )
        # Rounds 0-2 prime, 3-4 are the staggered freshest-wins phase,
        # the kill lands mid-round 5, the restart in round 7.
        kill_round = 5
        sim.arm_timeline(
            f"kill:{victim}@{kill_round}#{max(shard_size // 2, 1)},"
            f"restart:{victim}@{kill_round + 2}"
        )
        result["victim"] = {"leaf": victim, "twin": twin,
                            "shard": victim_shard,
                            "shard_targets": shard_size}

        # --- rounds 0-2: prime; breakers learn the dead targets --------
        for _ in range(3):
            timings.append(sim.run_round())

        # --- baseline: root == flat oracle ------------------------------
        root_map = _family_values(sim.root_body())
        oracle_map = _family_values(sim.oracle_body())
        problems = _compare_oracle(root_map, oracle_map)
        if problems:
            result["error"] = f"baseline oracle mismatch: {problems[:3]}"
            return result
        result["baseline"] = {"rollup_series": len(root_map),
                              "oracle_equal": True}
        # Per-family rollups against the arithmetic ground truth: every
        # live target contributes `chips` chips to exactly its own
        # family's fleet count — mixed sums that crossed families would
        # land on the right total while being family-wrong, so the split
        # is checked against first principles, not just the oracle.
        fam_expected: dict[str, float] = {}
        for i in range(sim.farm.allocated):
            if i not in sim.farm.dead:
                fam = sim.farm.family_of(i)
                fam_expected[fam] = fam_expected.get(fam, 0.0) + chips
        fam_reported = {
            s.labels["family"]: s.value
            for s in parse_families(sim.root_body()).get(
                "tpu_fleet_family_chip_count", ())
        }
        result["baseline"]["family_chips"] = fam_reported
        if fam_reported != fam_expected:
            result["error"] = (
                f"per-family fleet chips {fam_reported} != expected "
                f"{fam_expected} (family-correctness violated)")
            return result
        if gpu_slices > 0 and "gpu" not in fam_reported:
            result["error"] = "mixed demo reported no GPU family chips"
            return result
        baseline_series = set(root_map)
        quarantined = [
            t for t, br in (sim.leaves[victim].agg.breakers or {}).items()
            if t in dead_urls and br.state != "closed"
        ]
        result["baseline"]["quarantined_dead_targets"] = len(quarantined)

        # --- freshest-wins: stagger every HA pair one farm round --------
        sim.farm.tick()
        sim.poll_leaves([n for n in sim.leaves if n.endswith("a")])
        sim.round_ref[0] += 1
        sim.farm.tick()
        sim.poll_leaves([n for n in sim.leaves if n.endswith("b")])
        sim.round_ref[0] += 1
        sim.root.poll_once()
        fresh_map = _family_values(sim.root_body())
        fresh_oracle = _family_values(sim.oracle_body())
        problems = _compare_oracle(fresh_map, fresh_oracle)
        if problems:
            result["error"] = (
                f"freshest-wins violated (root served the stale HA half): "
                f"{problems[:3]}")
            return result
        result["freshest_wins"] = {"oracle_equal_at_newer_round": True}

        # --- kill one HA leaf mid-round ---------------------------------
        t_kill = sim.run_round()  # the hook fires inside the victim's poll
        timings.append(t_kill)
        if (sim.round_ref[0] - 1, "kill", victim) not in sim.hook.executed:
            result["error"] = (
                f"timeline did not fire the kill: {sim.hook.executed}")
            return result
        body = sim.root_body()
        kill_map = _family_values(body)
        lost = baseline_series - set(kill_map)
        result["kill"] = {
            "executed": list(sim.hook.executed),
            "series_before": len(baseline_series),
            "series_after": len(kill_map),
            "series_lost": sorted(lost)[:5],
        }
        if lost:
            result["error"] = f"{len(lost)} series lost after leaf kill"
            return result
        problems = _compare_oracle(kill_map, _family_values(sim.oracle_body()))
        if problems:
            result["error"] = f"post-kill oracle mismatch: {problems[:3]}"
            return result
        fams = parse_families(body)
        leaf_up = {
            (s.labels["shard"], s.labels["leaf"]): s.value
            for s in fams.get("tpu_root_leaf_up", ())
        }
        victim_addr = sim.leaves[victim].addr
        twin_addr = sim.leaves[twin].addr
        if leaf_up.get((victim_shard, victim_addr)) != 0.0:
            result["error"] = f"victim leaf_up should be 0: {leaf_up}"
            return result
        if leaf_up.get((victim_shard, twin_addr)) != 1.0:
            result["error"] = f"twin leaf_up should be 1: {leaf_up}"
            return result
        stale = {
            s.labels["leaf"]: s.value
            for s in fams.get("tpu_root_leaf_staleness_seconds", ())
            if s.labels["shard"] == victim_shard
        }
        twin_stale = stale.get(twin_addr, math.inf)
        result["kill"]["twin_staleness_s"] = round(twin_stale, 3)
        budget = max(stale_budget_s, 2.0 * t_kill["full_s"])
        if twin_stale > budget:
            result["error"] = (
                f"twin staleness {twin_stale:.2f}s exceeds one-round budget "
                f"{budget:.2f}s")
            return result

        # one more round with the leaf down: the shard stays covered.
        timings.append(sim.run_round())

        # --- restart: state carryover -----------------------------------
        timings.append(sim.run_round())  # restart event fires, leaf re-polls
        if (kill_round + 2, "restart", victim) not in sim.hook.executed:
            result["error"] = (
                f"timeline did not fire the restart: {sim.hook.executed}")
            return result
        restarted = sim.leaves[victim].agg
        carried = [
            t for t, br in (restarted.breakers or {}).items()
            if t in dead_urls and br.state != "closed"
        ]
        fams = parse_families(sim.root_body())
        leaf_up = {
            s.labels["leaf"]: s.value
            for s in fams.get("tpu_root_leaf_up", ())
            if s.labels["shard"] == victim_shard
        }
        result["restart"] = {
            "dead_target_quarantines_carried": len(carried),
            "leaf_up_after": leaf_up.get(victim_addr),
        }
        if len(quarantined) and not carried:
            result["error"] = (
                "restarted leaf re-learned its quarantines from scratch "
                "(breaker carryover broken)")
            return result
        if leaf_up.get(victim_addr) != 1.0:
            result["error"] = f"restarted leaf not up at root: {leaf_up}"
            return result

        # --- churn wave --------------------------------------------------
        old_targets = sim.farm.targets(sim.farm.allocated)
        old_live = tuple(
            t for i, t in enumerate(old_targets) if i not in sim.farm.dead
        )
        removed = list(old_live[2:2 + churn // 2])
        added = list(sim.farm.add_targets(churn - churn // 2))
        new_targets = tuple(
            t for t in old_targets if t not in removed
        ) + tuple(added)
        moves = count_moves(
            sim.smap.assignments(old_targets),
            sim.smap.assignments(new_targets),
        )
        bound = churn + max(len(new_targets) // shards, 1)
        result["churn"] = {
            "removed": len(removed), "added": len(added),
            "assignment_moves": moves, "bound": bound,
        }
        if moves > bound:
            result["error"] = (
                f"churn wave moved {moves} assignments, bound {bound}")
            return result
        sim.write_targets(new_targets)
        timings.append(sim.run_round())  # reload + reshard + re-aggregate
        fams = parse_families(sim.root_body())
        leaf_targets = sum(
            s.value for s in fams.get("tpu_root_shard_targets", ())
        )
        result["churn"]["leaf_reported_targets"] = int(leaf_targets)
        if int(leaf_targets) != len(new_targets):
            result["error"] = (
                f"leaves report {int(leaf_targets)} targets after churn, "
                f"want {len(new_targets)}")
            return result
        reshard_total = sum(
            s.value for s in fams.get("tpu_root_reshard_moves_total", ())
        )
        result["churn"]["root_reshard_moves_total"] = reshard_total
        if reshard_total < moves:
            result["error"] = (
                f"root reshard counter {reshard_total} below the observed "
                f"{moves} moves")
            return result
        problems = _compare_oracle(
            _family_values(sim.root_body()), _family_values(sim.oracle_body())
        )
        if problems:
            result["error"] = f"post-churn oracle mismatch: {problems[:3]}"
            return result

        # --- budgets ------------------------------------------------------
        result["timings"] = {
            "rounds": len(timings),
            "full_max_s": round(max(t["full_s"] for t in timings), 3),
            "full_mean_s": round(
                sum(t["full_s"] for t in timings) / len(timings), 3),
            "root_max_s": round(max(t["root_s"] for t in timings), 3),
            "budget_s": round_budget_s,
        }
        if result["timings"]["full_max_s"] > round_budget_s:
            result["error"] = (
                f"round time {result['timings']['full_max_s']}s exceeds "
                f"budget {round_budget_s}s")
            return result
        result["ok"] = True
        return result
    finally:
        sim.close()


# ------------------------------------------------- dashboard storm (mode 3)


class _ReplicaSim:
    """One in-process stateless read replica: a read-only RootAggregator
    over the same leaf topology, its own two-level query plane with the
    generation-keyed cache, a stream hub, and a real HTTP server —
    exactly what ``tpu-pod-exporter-shard --role replica`` builds.
    Rounds are caller-ticked like everything else in the sim."""

    def __init__(self, name: str, topology, root_url: str,
                 timeout_s: float = 5.0, max_subscribers: int = 20000,
                 heartbeat_s: float = 5.0, full_sync_s: float = 20.0) -> None:
        from tpu_pod_exporter.metrics import SnapshotStore
        from tpu_pod_exporter.server import MetricsServer
        from tpu_pod_exporter.shard import (
            ReplicaSourceProxy,
            RootAggregator,
            RootQueryPlane,
        )
        from tpu_pod_exporter.stream import StreamHub, plane_poll_fn

        self.name = name
        self.alive = True
        self.store = SnapshotStore()
        self.root = RootAggregator(topology, self.store,
                                   timeout_s=timeout_s)
        self.plane = ReplicaSourceProxy(
            RootQueryPlane(topology, timeout_s=timeout_s + 0.5,
                           leaf_breakers=self.root._breakers,
                           generation_fn=lambda: self.root.rounds),
            replica_id=name, root_url=root_url,
        )
        self.root.emit_hooks.append(self.plane.emit)
        self.poll_fn = plane_poll_fn(self.plane)
        self.hub = StreamHub(self.poll_fn, lambda: self.root.rounds,
                             heartbeat_s=heartbeat_s,
                             full_sync_s=full_sync_s,
                             max_subscribers=max_subscribers)
        self.root.emit_hooks.append(self.hub.emit)
        self.server = MetricsServer(self.store, host="127.0.0.1", port=0,
                                    fleet=self.plane, stream_hub=self.hub)
        self.server.start()
        self.addr = ("127.0.0.1", self.server.port)

    def tick_round(self) -> None:
        if not self.alive:
            return
        self.root.poll_once()
        self.hub.on_round(self.root.rounds)

    def kill(self) -> None:
        """Replica death mid-stream: the server drops every subscriber
        connection (they must reconnect to a peer); nothing durable is
        lost because a replica owns nothing durable."""
        if not self.alive:
            return
        self.alive = False
        self.server.stop()

    def close(self) -> None:
        self.kill()
        self.hub.close()
        self.plane.close()
        self.root.close()


class _StormSubscribers:
    """5-10k concurrent SSE subscriptions across a few selector loops.

    Each connection applies its frames through a
    :class:`~tpu_pod_exporter.stream.StreamReplay` (so gaps/dups/replay
    state are tracked per subscriber), records per-frame push latency
    (receiver wall clock minus the frame's emission ts — one process, one
    clock), and on EOF reconnects to a live peer endpoint — the
    replica-kill degradation story. Connections are sharded over
    ``workers`` independent selector threads so the measurement harness
    itself does not become the latency bottleneck at 5k+ subscribers.
    ``drop_one_delta`` is the NEGATIVE control: one delta frame per
    connection is discarded before replay, which the equality invariant
    must catch."""

    def __init__(self, drop_one_delta: bool = False,
                 workers: int = 4) -> None:
        import selectors
        import socket as socket_mod

        self._selectors = selectors
        self._socket = socket_mod
        self._lock = threading.Lock()
        self._stopping = False
        self.drop_one_delta = drop_one_delta
        self.conns: dict[int, dict] = {}
        self._next_id = 0
        self._endpoints: list[tuple[str, tuple[str, int]]] = []
        self._dead_endpoints: set[str] = set()
        self.connect_failures = 0
        self._workers: list[dict] = []
        for i in range(max(1, workers)):
            sel = selectors.DefaultSelector()
            wr, ww = socket_mod.socketpair()
            wr.setblocking(False)
            ww.setblocking(False)
            sel.register(wr, selectors.EVENT_READ, None)
            w = {"sel": sel, "wake_r": wr, "wake_w": ww, "pending": [],
                 "idx": i}
            w["thread"] = threading.Thread(
                target=self._run, args=(w,),
                name=f"tpu-dash-storm-{i}", daemon=True)
            self._workers.append(w)
            w["thread"].start()

    # ------------------------------------------------------------- control

    def _post(self, w, fn) -> None:
        with self._lock:
            w["pending"].append(fn)
        try:
            w["wake_w"].send(b"\x00")
        except OSError:
            pass

    def set_endpoints(self, endpoints) -> None:
        """[(label, (host, port)), ...] — reconnect targets."""
        with self._lock:
            self._endpoints = list(endpoints)

    def mark_dead(self, label: str) -> None:
        with self._lock:
            self._dead_endpoints.add(label)

    def open(self, n: int, shapes, spread=None) -> None:
        """Open n subscriptions round-robin across live endpoints (or
        ``spread``, a list of labels) and shapes, sharded over the
        worker loops."""
        from tpu_pod_exporter.stream import stream_path

        with self._lock:
            eps = {label: addr for label, addr in self._endpoints}
            labels = spread or [label for label, _ in self._endpoints]
        per = [[] for _ in self._workers]
        for i in range(n):
            per[i % len(per)].append(
                (labels[i % len(labels)], shapes[i % len(shapes)]))
        for w, batch in zip(self._workers, per):
            def start(w=w, batch=batch) -> None:
                for label, shape in batch:
                    self._connect(w, label, eps[label], shape,
                                  stream_path(shape))
            self._post(w, start)

    def _connect(self, w, label, addr, shape, path) -> int | None:
        from tpu_pod_exporter.stream import SseParser, StreamReplay

        sock = self._socket.socket()
        sock.setblocking(False)
        try:
            sock.connect_ex(addr)
        except OSError:
            self.connect_failures += 1
            sock.close()
            return None
        with self._lock:
            cid = self._next_id
            self._next_id += 1
        conn = {
            "id": cid, "sock": sock, "label": label, "shape": shape,
            "worker": w,
            "out": bytearray(
                f"GET {path} HTTP/1.1\r\nHost: storm\r\n"
                f"Accept: text/event-stream\r\n\r\n".encode()),
            "head": bytearray(), "in_body": False,
            "parser": SseParser(), "replay": StreamReplay(),
            "latencies": [], "reconnects": 0, "dropped": False,
            "status": 0, "closed": False,
        }
        with self._lock:
            self.conns[cid] = conn
        w["sel"].register(
            sock,
            self._selectors.EVENT_READ | self._selectors.EVENT_WRITE,
            conn,
        )
        return cid

    # ---------------------------------------------------------------- loop

    def _run(self, w) -> None:
        sel = w["sel"]
        EVENT_READ = self._selectors.EVENT_READ
        EVENT_WRITE = self._selectors.EVENT_WRITE
        while not self._stopping:
            for key, mask in sel.select(0.2):
                if key.fileobj is w["wake_r"]:
                    try:
                        while w["wake_r"].recv(4096):
                            pass
                    except OSError:
                        pass
                    continue
                conn = key.data
                if conn["closed"]:
                    continue
                if mask & EVENT_WRITE and conn["out"]:
                    try:
                        n = conn["sock"].send(conn["out"])
                        del conn["out"][:n]
                    except (BlockingIOError, InterruptedError):
                        pass
                    except OSError:
                        self._drop(conn)
                        continue
                    if not conn["out"]:
                        sel.modify(conn["sock"], EVENT_READ, conn)
                if mask & EVENT_READ:
                    self._readable(conn)
            with self._lock:
                pending, w["pending"] = w["pending"], []
            for fn in pending:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — storm must keep running
                    pass
        for conn in list(self.conns.values()):
            if conn["worker"] is w:
                self._drop(conn, reconnect=False)
        sel.close()
        w["wake_r"].close()
        w["wake_w"].close()

    def _readable(self, conn) -> None:
        try:
            data = conn["sock"].recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not data:
            self._drop(conn)
            return
        if not conn["in_body"]:
            conn["head"] += data
            idx = conn["head"].find(b"\r\n\r\n")
            if idx < 0:
                return
            head = bytes(conn["head"][:idx])
            rest = bytes(conn["head"][idx + 4:])
            parts = head.split(b"\r\n", 1)[0].split()
            conn["status"] = int(parts[1]) if len(parts) > 1 else 0
            conn["in_body"] = True
            conn["head"] = bytearray()
            if conn["status"] != 200:
                self._drop(conn, reconnect=False)
                return
            data = rest
            if not data:
                return
        now_wall = time.time()
        frames = conn["parser"].feed(data)
        with self._lock:
            for frame in frames:
                if (self.drop_one_delta and not conn["dropped"]
                        and frame.get("type") == "delta"):
                    # NEGATIVE CONTROL: a lost delta the client never
                    # applied — the replay-equality invariant must flag
                    # this subscriber.
                    conn["dropped"] = True
                    continue
                conn["replay"].apply(frame, recv_wall=now_wall)
                if frame.get("type") in ("delta", "full_sync"):
                    lat = conn["replay"].last_latency_s
                    if lat is not None:
                        conn["latencies"].append(lat)

    def _drop(self, conn, reconnect: bool = True) -> None:
        if conn["closed"]:
            return
        conn["closed"] = True
        try:
            conn["worker"]["sel"].unregister(conn["sock"])
        except (KeyError, ValueError):
            pass
        try:
            conn["sock"].close()
        except OSError:
            pass
        with self._lock:
            self.conns.pop(conn["id"], None)
        if not reconnect or self._stopping:
            return
        # Reconnect to a LIVE peer: the kill degradation contract — a
        # dead replica's viewers land on the survivors with a fresh
        # snapshot; everyone else's stream is untouched.
        from tpu_pod_exporter.stream import stream_path

        with self._lock:
            live = [(label, addr) for label, addr in self._endpoints
                    if label not in self._dead_endpoints]
        if not live:
            return
        label, addr = live[conn["id"] % len(live)]
        cid = self._connect(conn["worker"], label, addr, conn["shape"],
                            stream_path(conn["shape"]))
        if cid is not None:
            with self._lock:
                self.conns[cid]["reconnects"] = conn["reconnects"] + 1

    # ------------------------------------------------------------ snapshots

    def live(self) -> int:
        with self._lock:
            return sum(1 for c in self.conns.values() if not c["closed"])

    def wait_snapshots(self, n: int, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                ready = sum(1 for c in self.conns.values()
                            if c["replay"].seq is not None)
            if ready >= n:
                return True
            time.sleep(0.05)
        return False

    def wait_caught_up(self, label_seqs: dict, timeout_s: float) -> int:
        """Block until every live subscriber of each label has replayed
        up to its SHAPE's current seq (``label_seqs``: label → the
        endpoint hub's ``shape_seqs()``); returns the laggard count left
        at timeout. Seq-based, not generation-based: a shape whose rows
        did not change this round legitimately ships nothing."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                lag = 0
                for c in self.conns.values():
                    if c["closed"] or c["replay"].seq is None:
                        continue
                    seqs = label_seqs.get(c["label"])
                    if seqs is None:
                        continue
                    want = seqs.get(c["shape"].key)
                    if want is not None and c["replay"].seq < want:
                        lag += 1
            if lag == 0 or time.monotonic() >= deadline:
                return lag
            time.sleep(0.02)

    def sample(self, k: int):
        """(label, shape, rows-by-key copy, generation) for k live,
        synced subscribers — the replay-equality check's subjects."""
        out = []
        with self._lock:
            for c in self.conns.values():
                if c["closed"] or c["replay"].seq is None:
                    continue
                out.append((c["label"], c["shape"],
                            dict(c["replay"].rows), c["replay"].generation))
                if len(out) >= k:
                    break
        return out

    def totals(self) -> dict:
        with self._lock:
            conns = list(self.conns.values())
            return {
                "live": sum(1 for c in conns if not c["closed"]),
                "gaps": sum(c["replay"].gaps for c in conns),
                "dups": sum(c["replay"].dups for c in conns),
                "desynced": sum(1 for c in conns if c["replay"].desynced),
                "reconnects": sum(c["reconnects"] for c in conns),
                "sheds_seen": sum(
                    1 for c in conns
                    if c["replay"].shed_reason is not None),
                "frames": sum(c["replay"].frames for c in conns),
                "latencies": sorted(
                    lat for c in conns for lat in c["latencies"]),
            }

    def drain_latencies(self) -> None:
        with self._lock:
            for c in self.conns.values():
                c["latencies"] = []

    def stop(self) -> None:
        self._stopping = True
        for w in self._workers:
            try:
                w["wake_w"].send(b"\x00")
            except OSError:
                pass
        for w in self._workers:
            w["thread"].join(timeout=10.0)


def _raise_nofile(need: int) -> None:
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < need <= hard:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ImportError, ValueError, OSError):
        pass


def run_dashboard_demo(
    n_targets: int,
    shards: int,
    chips: int,
    subs: int,
    rounds: int,
    replicas: int,
    state_root: str,
    push_p99_budget_s: float = 1.0,
    rss_cap_mb: float = 128.0,
    negative: bool = False,
    kill_replica: bool = True,
) -> dict:
    """The dashboard-storm acceptance drill (``make dashboard-demo``).

    Holds ``subs`` concurrent stream subscriptions against one root +
    ``replicas`` stateless read replicas over a real leaf tier, drives
    caller-ticked rounds, and asserts: bounded per-round push p99, flat
    RSS, zero duplicate/missed rounds per subscriber, delta replay equal
    to the polled answer for every sampled subscriber every round, and a
    replica kill mid-stream degrading ONLY its own subscribers (they
    reconnect to a peer and resync). ``negative=True`` drops one delta
    frame client-side per subscriber — the equality invariant must catch
    it, proving the drill can fail."""
    import os

    from tpu_pod_exporter import utils
    from tpu_pod_exporter.metrics import SnapshotBuilder
    from tpu_pod_exporter.server import MetricsServer
    from tpu_pod_exporter.shard import RootQueryPlane
    from tpu_pod_exporter.stream import (
        QueryShape,
        StreamHub,
        plane_poll_fn,
        rows_map,
    )

    _raise_nofile(2 * subs + 4 * n_targets + 512)
    os.makedirs(state_root, exist_ok=True)
    result: dict = {
        "ok": False, "mode": "dashboard", "targets": n_targets,
        "shards": shards, "subs": subs, "rounds": rounds,
        "replicas": replicas, "negative": negative,
        "failures": [],
    }
    fails: list = result["failures"]
    t_start = time.perf_counter()
    # A dropped delta (negative mode) leaves its subscriber behind the
    # round generation until the next frame — don't ride out the full
    # production wait on a lag the control CREATED.
    gen_wait_s = 5.0 if negative else 30.0
    sim = _ShardSim(n_targets, shards, ha=False, chips=chips,
                    state_root=state_root, timeout_s=10.0,
                    query_plane=True)
    # ~1/4 of the fleet's api series change per round: the delta stream
    # has real sparsity to exploit (and to be measured on).
    sim.farm.api_churn = 4
    storm = _StormSubscribers(drop_one_delta=negative)
    root_plane = RootQueryPlane(
        sim.topology, timeout_s=10.5,
        leaf_breakers=sim.root._breakers,
        generation_fn=lambda: sim.root.rounds,
    )
    per_hub_cap = subs  # admission headroom; shed is exercised explicitly
    root_hub = StreamHub(plane_poll_fn(root_plane),
                         lambda: sim.root.rounds,
                         heartbeat_s=5.0, full_sync_s=20.0,
                         max_subscribers=per_hub_cap)
    root_server = MetricsServer(sim.root_store, host="127.0.0.1", port=0,
                                fleet=root_plane, stream_hub=root_hub)
    root_server.start()
    reps: list[_ReplicaSim] = []
    try:
        for i in range(replicas):
            reps.append(_ReplicaSim(
                f"replica-{i}", sim.topology,
                root_url=f"127.0.0.1:{root_server.port}",
                timeout_s=10.0, max_subscribers=per_hub_cap))
        endpoints = [("root", ("127.0.0.1", root_server.port))] + [
            (rep.name, rep.addr) for rep in reps
        ]
        storm.set_endpoints(endpoints)
        planes = {"root": plane_poll_fn(root_plane)}
        hubs = {"root": root_hub}
        for rep in reps:
            planes[rep.name] = rep.poll_fn
            hubs[rep.name] = rep.hub

        def tick_all() -> dict:
            sim.run_round()
            for rep in reps:
                rep.tick_round()
            t0 = time.perf_counter()
            root_hub.on_round(sim.root.rounds)
            return {"root_push_s": time.perf_counter() - t0}

        # Prime: two rounds before any viewer shows up.
        tick_all()
        tick_all()

        # Dashboard panels: a handful of query shapes shared by thousands
        # of subscribers — per round the plane evaluates each shape ONCE
        # per serving endpoint, not once per viewer.
        shapes = [
            QueryShape(route="window_stats", metric="tpu_hbm_used_bytes",
                       match=(("slice_name", f"slice-{i}"),), window_s=60.0)
            for i in range(4)
        ] + [QueryShape(route="window_stats", metric="tpu_hbm_used_bytes",
                        window_s=60.0)]
        storm.open(subs, shapes)
        if not storm.wait_snapshots(subs, timeout_s=60.0):
            fails.append(
                f"only {storm.live()} of {subs} subscriptions "
                f"reached their snapshot")
        result["connected"] = storm.live()
        # RSS baseline AFTER the subscriptions exist: the flat-RSS
        # invariant hunts leaks DURING the storm (per-round growth), not
        # the one-time footprint of 2×subs in-process sockets this
        # single-process harness deliberately carries on both sides.
        rss_before = utils.process_rss_bytes() or 0

        kill_round = (rounds // 2
                      if (kill_replica and reps and rounds > 0) else -1)
        round_push_p99: list[float] = []
        equality_checked = 0
        equality_failures = 0
        for r in range(rounds):
            storm.drain_latencies()
            tick_all()
            expect = {label: hub.shape_seqs() for label, hub in hubs.items()}
            laggards = storm.wait_caught_up(expect,
                                            timeout_s=gen_wait_s)
            if laggards:
                fails.append(
                    f"round {r}: {laggards} subscribers never caught up "
                    f"to their shape's seq")
            # Replay == polled answer, per sampled subscriber. Same
            # generation + the generation-keyed plane cache ⇒ the polled
            # answer is byte-identical to what the hub diffed from.
            for label, shape, rows, gen in storm.sample(12):
                env = planes[label](shape, gen or 0)
                equality_checked += 1
                if rows != rows_map(shape.route, env):
                    equality_failures += 1
                    fails.append(
                        f"round {r}: replay != polled answer for a "
                        f"{label} subscriber of {shape.metric} "
                        f"{dict(shape.match)}")
            tot = storm.totals()
            lats = tot["latencies"]
            if lats:
                round_push_p99.append(lats[int(0.99 * (len(lats) - 1))])
            if r == kill_round:
                pre_kill = storm.totals()
                victim = reps[0]
                with storm._lock:
                    victim_subs = sum(
                        1 for c in storm.conns.values()
                        if c["label"] == victim.name and not c["closed"])
                storm.mark_dead(victim.name)
                victim.kill()
                result["replica_kill"] = {
                    "victim": victim.name,
                    "subscribers_at_kill": victim_subs,
                    "reconnects_before": pre_kill["reconnects"],
                }
        # Post-kill: every orphaned subscriber must be back on a live
        # peer with a fresh snapshot (degradation contained to the
        # victim's own viewers).
        if kill_round >= 0:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                tot = storm.totals()
                if tot["live"] >= result["connected"]:
                    break
                time.sleep(0.1)
            tot = storm.totals()
            rk = result["replica_kill"]
            rk["reconnects_after"] = tot["reconnects"]
            rk["live_after"] = tot["live"]
            if tot["live"] < result["connected"]:
                fails.append(
                    f"replica kill: only {tot['live']} of "
                    f"{result['connected']} subscribers live after "
                    f"reconnect window")
            if tot["reconnects"] < rk["subscribers_at_kill"]:
                fails.append(
                    f"replica kill: {rk['subscribers_at_kill']} "
                    f"subscribers orphaned but only {tot['reconnects']} "
                    f"reconnected")
            # Survivors' streams untouched: reconnect count equals the
            # victim's subscriber count (no collateral drops).
            if tot["reconnects"] > rk["subscribers_at_kill"] + max(
                    2, rk["subscribers_at_kill"] // 10):
                fails.append(
                    f"replica kill: {tot['reconnects']} reconnects for "
                    f"{rk['subscribers_at_kill']} orphaned subscribers — "
                    f"survivors were disrupted too")
            # One settle round so reconnected subscribers resync, then
            # re-verify equality across every endpoint.
            tick_all()
            expect = {label: h.shape_seqs() for label, h in hubs.items()
                      if label != reps[0].name}
            storm.wait_caught_up(expect, timeout_s=gen_wait_s)
            for label, shape, rows, gen in storm.sample(12):
                env = planes[label](shape, gen or 0)
                equality_checked += 1
                if rows != rows_map(shape.route, env):
                    equality_failures += 1
                    fails.append(
                        f"post-kill settle: replay != polled answer on "
                        f"{label}")

        # Subscriber-shed semantics: pressure on the root hub sheds the
        # oldest half with a labeled shed frame; the shed viewers
        # reconnect (to any live endpoint) and the counter records it.
        root_subs_before = root_hub.subscribers
        shed_n = root_hub.shed_oldest(0.5, reason="pressure")
        time.sleep(0.5)
        b = SnapshotBuilder()
        root_hub.emit(b)
        snap = b.build(timestamp=time.time())
        shed_counted = snap.value("tpu_stream_sheds_total",
                                  ("pressure",)) or 0.0
        result["shed"] = {"root_subs_before": root_subs_before,
                          "shed": shed_n, "counted": shed_counted}
        if shed_n and shed_counted < shed_n:
            fails.append(
                f"shed {shed_n} subscribers but counter shows "
                f"{shed_counted}")

        # Pull baseline: what the same viewers would cost as polling —
        # one keep-alive client hammering the polled route (generation-
        # cache-hot, the PRE-inversion best case). The storm's per-round
        # cost for comparison: one delta computation per shape plus one
        # small write per subscriber.
        import http.client

        poll_path = ("/api/v1/window_stats?metric=tpu_hbm_used_bytes"
                     "&window=60")
        conn = http.client.HTTPConnection("127.0.0.1", root_server.port,
                                          timeout=10)
        pull_n = min(max(subs // 4, 50), 1000)
        pull_bytes = 0
        t0 = time.perf_counter()
        try:
            for _ in range(pull_n):
                conn.request("GET", poll_path)
                r_ = conn.getresponse()
                pull_bytes += len(r_.read())
        finally:
            conn.close()
        pull_took = time.perf_counter() - t0
        result["pull_baseline"] = {
            "requests": pull_n,
            "qps_one_client": round(pull_n / max(pull_took, 1e-9), 1),
            "bytes_per_answer": pull_bytes // max(pull_n, 1),
            "note": ("full body per viewer per refresh, cache-hot; the "
                     "push plane ships changed rows only, once per round "
                     "per subscriber"),
        }

        tot = storm.totals()
        rss_after = utils.process_rss_bytes() or 0
        result["push_p99_s"] = (max(round_push_p99)
                                if round_push_p99 else None)
        result["push_p99_budget_s"] = push_p99_budget_s
        result["gaps"] = tot["gaps"]
        result["dups"] = tot["dups"]
        result["desynced"] = tot["desynced"]
        result["frames_delivered"] = tot["frames"]
        result["equality_checked"] = equality_checked
        result["equality_failures"] = equality_failures
        result["rss_before_mb"] = round(rss_before / 2**20, 1)
        result["rss_after_mb"] = round(rss_after / 2**20, 1)
        result["rss_delta_mb"] = round((rss_after - rss_before) / 2**20, 1)
        stats = root_hub.stats()
        result["root_hub"] = stats
        if negative:
            # The negative control PASSES only by FAILING: dropped deltas
            # must surface as equality failures (or explicit gaps).
            if equality_failures == 0 and tot["gaps"] == 0:
                fails.append(
                    "NEGATIVE CONTROL: deltas were dropped client-side "
                    "but no invariant caught it")
            else:
                result["ok"] = True
                result["negative_detected"] = equality_failures + tot["gaps"]
                result["took_s"] = round(time.perf_counter() - t_start, 3)
                return result
        if equality_failures:
            pass  # already recorded per round
        if tot["gaps"] or tot["dups"]:
            fails.append(
                f"seq discontinuities: {tot['gaps']} gaps, "
                f"{tot['dups']} dups across subscribers")
        if result["push_p99_s"] is not None and (
                result["push_p99_s"] > push_p99_budget_s):
            fails.append(
                f"per-round push p99 {result['push_p99_s']:.3f}s over "
                f"budget {push_p99_budget_s}s")
        if result["rss_delta_mb"] > rss_cap_mb:
            fails.append(
                f"RSS grew {result['rss_delta_mb']} MiB under the storm "
                f"(cap {rss_cap_mb})")
        result["ok"] = not fails
        result["took_s"] = round(time.perf_counter() - t_start, 3)
        return result
    finally:
        storm.stop()
        root_server.stop()
        root_hub.close()
        root_plane.close()
        for rep in reps:
            rep.close()
        sim.close()
        try:
            with open(os.path.join(state_root, "dashboard-result.json"),
                      "w", encoding="utf-8") as f:
                json.dump(result, f, indent=1, default=str)
        except OSError:
            pass


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-loadgen-fleet",
        description="Simulated-fleet acceptance harnesses: the federated "
                    "query plane (make fleet-query-demo) and the sharded "
                    "HA aggregation tree (make shard-demo).",
    )
    p.add_argument("--mode", default="query",
                   choices=("query", "shard", "dashboard"),
                   help="query = fleet-query demo (default); shard = "
                        "sharded-tree churn/kill demo; dashboard = "
                        "streaming viewer-storm drill (subscriptions vs "
                        "one root + N read replicas)")
    p.add_argument("--shards", type=int, default=8,
                   help="[shard] consistent-hash shard count")
    p.add_argument("--no-ha", dest="ha", action="store_false", default=True,
                   help="[shard] single leaf per shard (no HA pairs)")
    p.add_argument("--churn", type=int, default=32,
                   help="[shard] churn-wave size (targets removed + added)")
    p.add_argument("--round-budget-s", type=float, default=15.0,
                   help="[shard] max full-round (leaf tier + root) wall time")
    p.add_argument("--stale-budget-s", type=float, default=5.0,
                   help="[shard] max HA-twin staleness after a leaf kill")
    p.add_argument("--state-root", default="shard-demo-state",
                   help="[shard] state dir (breaker/shard-map carryover; "
                        "uploaded as a CI artifact on failure)")
    p.add_argument("--gpu-slices", type=int, default=2,
                   help="[shard] farm slices (of 8) that are GPU node "
                        "pools — the mixed-fleet half of the demo; 0 for "
                        "a homogeneous TPU farm")
    p.add_argument("--targets", type=int, default=64)
    p.add_argument("--chips", type=int, default=4, help="chips per host")
    p.add_argument("--polls", type=int, default=10,
                   help="history-priming polls before aggregation")
    p.add_argument("--interval-s", type=float, default=0.02,
                   help="pause between priming polls")
    p.add_argument("--queries", type=int, default=40,
                   help="latency-measurement queries (cache-busted)")
    p.add_argument("--budget-ms", type=float, default=1500.0,
                   help="fleet query p99 budget")
    p.add_argument("--kill-one", action="store_true", default=True)
    p.add_argument("--no-kill", dest="kill_one", action="store_false",
                   help="skip the mid-run target kill")
    p.add_argument("--no-persist", dest="persist", action="store_false",
                   default=True, help="disable per-target persistence")
    p.add_argument("--subs", type=int, default=5000,
                   help="[dashboard] concurrent stream subscriptions")
    p.add_argument("--replicas", type=int, default=2,
                   help="[dashboard] stateless read replicas beside the "
                        "root")
    p.add_argument("--rounds", type=int, default=10,
                   help="[dashboard] storm rounds to drive")
    p.add_argument("--push-p99-budget-s", type=float, default=1.0,
                   help="[dashboard] per-round push latency p99 budget")
    p.add_argument("--rss-cap-mb", type=float, default=128.0,
                   help="[dashboard] max RSS growth under the storm")
    p.add_argument("--negative", action="store_true",
                   help="[dashboard] NEGATIVE CONTROL: drop one delta "
                        "frame per subscriber client-side; the run "
                        "passes only if the replay-equality invariant "
                        "catches it")
    p.add_argument("--no-replica-kill", dest="replica_kill",
                   action="store_false", default=True,
                   help="[dashboard] skip the mid-storm replica kill")
    ns = p.parse_args(argv)

    if ns.mode == "dashboard":
        result = run_dashboard_demo(
            ns.targets, ns.shards, ns.chips, ns.subs, ns.rounds,
            ns.replicas, ns.state_root,
            push_p99_budget_s=ns.push_p99_budget_s,
            rss_cap_mb=ns.rss_cap_mb, negative=ns.negative,
            kill_replica=ns.replica_kill,
        )
        print(json.dumps({k: v for k, v in result.items()
                          if k != "root_hub"}, indent=1, default=str))
        if not result["ok"]:
            print(f"DASHBOARD DEMO FAILED: {result['failures']}",
                  file=sys.stderr)
            return 1
        mode = "negative control" if ns.negative else "storm"
        print(
            f"dashboard-demo OK ({mode}): {result['connected']} "
            f"subscriptions vs 1 root + {ns.replicas} replica(s) at "
            f"{ns.targets} targets, {result['frames_delivered']} frames, "
            f"push p99 {result['push_p99_s']}s "
            f"(budget {ns.push_p99_budget_s}s), gaps {result['gaps']}, "
            f"dups {result['dups']}, RSS {result['rss_delta_mb']:+} MiB, "
            f"equality {result['equality_checked']} checks / "
            f"{result['equality_failures']} failures"
        )
        return 0

    if ns.mode == "shard":
        result = run_shard_demo(
            ns.targets, ns.shards, ns.ha, ns.chips, ns.churn,
            ns.round_budget_s, ns.stale_budget_s, ns.state_root,
            gpu_slices=ns.gpu_slices,
        )
        print(json.dumps(result, indent=1))
        try:
            # Into the state root: CI uploads the dir on failure, and the
            # executed timeline + per-phase verdicts ARE the forensics.
            with open(f"{ns.state_root}/result.json", "w",
                      encoding="utf-8") as f:
                json.dump(result, f, indent=1)
        except OSError:
            pass
        if not result["ok"]:
            print(f"SHARD DEMO FAILED: {result.get('error')}",
                  file=sys.stderr)
            return 1
        t = result["timings"]
        print(
            f"shard-demo OK: {ns.targets} targets / {ns.shards} shards "
            f"(HA={'on' if ns.ha else 'off'}, families "
            f"{result['baseline']['family_chips']}), mid-round leaf kill → "
            f"0 series lost, churn {ns.churn} → "
            f"{result['churn']['assignment_moves']} moves "
            f"(bound {result['churn']['bound']}), round max "
            f"{t['full_max_s']}s (budget {t['budget_s']}s)"
        )
        return 0

    result = run_demo(
        ns.targets, ns.chips, ns.polls, ns.interval_s,
        ns.queries, ns.budget_ms, ns.kill_one, ns.persist,
    )
    print(json.dumps(result, indent=1))
    if not result["ok"]:
        print(f"FLEET QUERY DEMO FAILED: {result.get('error')}",
              file=sys.stderr)
        return 1
    print(
        f"fleet-query-demo OK: {ns.targets} targets, "
        f"p99 {result['query_p99_ms']}ms (budget {ns.budget_ms:.0f}ms), "
        f"kill→partial asserted" if ns.kill_one else "kill skipped",
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
