"""Fleet-mode load generation: N simulated exporters in one process.

The other loadgen modes drive real accelerators; this one drives the
*observability plane at fleet shape*. It runs N lightweight simulated
exporters (real ``Collector`` + ``HistoryStore`` + ``MetricsServer`` over a
scripted ``FakeBackend``, each on its own ephemeral port with a distinct
host topology) inside one process, so tests and CI can stand up a 64-host
slice in a couple of seconds and point a real aggregator at it.

``python -m tpu_pod_exporter.loadgen.fleet`` is the fleet-query acceptance
harness (``make fleet-query-demo``): it builds the fleet, aggregates it,
runs federated ``/api/v1/query_range`` queries through the real HTTP
stack with tracing and persistence ON, kills one target mid-run, and
asserts (1) a full merge with per-target staleness, (2) ``partial: true``
with the remaining targets merged after the kill, and (3) a fleet-query
p99 latency budget — the CI gate for the federated query plane.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time
import urllib.request


def _build_exporter(idx: int, chips: int, state_dir: str | None,
                    trace: bool):
    """One simulated exporter: scripted fake backend, real collector,
    history (tiers on), optional persistence, HTTP server on port 0."""
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.history import HistoryStore
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.server import MetricsServer
    from tpu_pod_exporter.topology import detect_host_topology

    # Distinct, deterministic telemetry per host so merged fleet answers
    # are checkable: duty ramps with the poll index offset by host, HBM
    # grows host-dependently.
    script = FakeChipScript(
        hbm_used_bytes=lambda step, i=idx: float((i + 1) * 2**30 + step * 2**20),
        duty_cycle_percent=lambda step, i=idx: float((i * 7 + step) % 100),
        ici_bytes_per_step=1e6,
    )
    backend = FakeBackend(chips=chips, script=script)
    topo = detect_host_topology(
        env={}, accelerator="v5p-64", slice_name="sim-slice",
        host=f"sim-host-{idx:02d}", worker_id=str(idx),
    )
    store = SnapshotStore()
    history = HistoryStore(capacity=256, max_series=2048, retention_s=0.0)
    trace_store = tracer = None
    if trace:
        from tpu_pod_exporter.trace import Tracer, TraceStore

        trace_store = TraceStore(max_traces=16)
        tracer = Tracer(trace_store, slow_poll_s=0.0)
    persister = None
    if state_dir:
        from tpu_pod_exporter.persist import StatePersister

        persister = StatePersister(
            state_dir, history=history,
            exposition_fn=lambda s=store: s.current(),
        )
        persister.start()
    collector = Collector(
        backend, FakeAttribution(), store, topology=topo,
        history=history, tracer=tracer, persister=persister,
    )
    server = MetricsServer(store, host="127.0.0.1", port=0,
                           history=history, trace=trace_store)
    server.start()
    return {
        "idx": idx,
        "collector": collector,
        "history": history,
        "server": server,
        "trace_store": trace_store,
        "persister": persister,
        "target": f"127.0.0.1:{server.port}",
        "alive": True,
    }


class FleetSim:
    """N simulated exporters, ticked from the caller's thread (scripted
    scenario timelines need deterministic poll ordering, not N loops)."""

    def __init__(self, n_targets: int, chips: int = 4,
                 persist: bool = True, trace: bool = True,
                 state_root: str | None = None) -> None:
        self._tmp = None
        if persist and state_root is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="fleet-sim-")
            state_root = self._tmp.name
        self.state_root = state_root
        self.exporters = [
            _build_exporter(
                i, chips,
                f"{state_root}/target-{i:02d}" if persist and state_root else None,
                trace,
            )
            for i in range(n_targets)
        ]
        self.chips = chips

    @property
    def targets(self) -> tuple[str, ...]:
        return tuple(e["target"] for e in self.exporters)

    def tick(self) -> None:
        for e in self.exporters:
            if e["alive"]:
                e["collector"].poll_once()

    def kill(self, idx: int) -> str:
        """Stop one exporter's HTTP server (its port starts refusing —
        the clean-death shape; wedges are chaos.py's job)."""
        e = self.exporters[idx]
        if e["alive"]:
            e["alive"] = False
            e["server"].stop()
        return e["target"]

    def scrape_spans_recorded(self) -> int:
        """Node-side /api/v1 serve spans recorded under REMOTE (fleet
        query) trace contexts — proof the traceparent propagated."""
        total = 0
        for e in self.exporters:
            ts = e["trace_store"]
            if ts is not None:
                total += len(ts.scrapes(64))
        return total

    def close(self) -> None:
        for e in self.exporters:
            if e["alive"]:
                e["server"].stop()
            if e["persister"] is not None:
                e["persister"].close()
            e["collector"].close()
        if self._tmp is not None:
            self._tmp.cleanup()


def _get_json(url: str, timeout_s: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — loopback demo
        return json.loads(resp.read())


def _percentile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    return ys[min(int(q * (len(ys) - 1) + 0.5), len(ys) - 1)]


def run_demo(n_targets: int, chips: int, polls: int, interval_s: float,
             queries: int, budget_ms: float, kill_one: bool,
             persist: bool) -> dict:
    """The acceptance scenario; returns a result dict with ``ok``."""
    from tpu_pod_exporter.aggregate import SliceAggregator
    from tpu_pod_exporter.fleet import FleetQueryPlane
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.persist import BreakerStateFile
    from tpu_pod_exporter.server import MetricsServer
    from tpu_pod_exporter.trace import Tracer, TraceStore

    result: dict = {"targets": n_targets, "chips": chips, "ok": False,
                    "tracing": True, "persistence": persist}
    sim = FleetSim(n_targets, chips=chips, persist=persist, trace=True)
    agg_server = None
    fleet = None
    agg = None
    try:
        for _ in range(polls):
            sim.tick()
            time.sleep(interval_s)

        trace_store = TraceStore(max_traces=128)
        store = SnapshotStore()
        agg = SliceAggregator(
            sim.targets, store, timeout_s=1.0,
            tracer=Tracer(trace_store, slow_poll_s=0.0, root_name="round"),
            breaker_store=(
                BreakerStateFile(f"{sim.state_root}/agg-breakers.json")
                if persist and sim.state_root else None
            ),
        )
        fleet = FleetQueryPlane(
            sim.targets, timeout_s=1.0, breakers=agg.breakers,
            tracer=Tracer(trace_store, slow_poll_s=0.0, root_name="query"),
            generation_fn=lambda: agg.rounds,
        )
        agg.set_fleet(fleet)
        agg.poll_once()
        agg_server = MetricsServer(store, host="127.0.0.1", port=0,
                                   fleet=fleet, trace=trace_store,
                                   debug_vars=agg.debug_vars)
        agg_server.start()
        base = f"http://127.0.0.1:{agg_server.port}"

        # --- full merge: one query answers for the whole fleet ----------
        now = time.time()
        # .3f, not .0f: rounding `end` to whole seconds can land it BEFORE
        # the just-primed samples and fake an empty fleet.
        doc = _get_json(
            f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
            f"&start={now - 120:.3f}&end={now:.3f}&step=1"
        )
        result["full_merge"] = {
            "merged_series": doc["fleet"]["merged_series"],
            "ok_targets": doc["fleet"]["ok"],
            "partial": doc["partial"],
            "staleness_present": all(
                st.get("staleness_s") is not None
                for st in doc["targets"].values()
            ),
        }
        if doc["partial"] or doc["fleet"]["ok"] != n_targets:
            result["error"] = f"expected full merge from {n_targets}: {doc['fleet']}"
            return result
        if doc["fleet"]["merged_series"] != n_targets * chips:
            result["error"] = (
                f"merged {doc['fleet']['merged_series']} series, "
                f"expected {n_targets * chips}"
            )
            return result
        if not result["full_merge"]["staleness_present"]:
            result["error"] = "per-target staleness missing"
            return result

        # --- p99 latency budget (cache-busted: every query a fresh grid) -
        metrics = ("tpu_tensorcore_duty_cycle_percent", "tpu_hbm_used_bytes")
        tails: list[float] = []
        for q in range(queries):
            sim.tick()  # keep data moving while querying
            now = time.time()
            url = (
                f"{base}/api/v1/query_range?metric={metrics[q % 2]}"
                f"&start={now - 60 - q:.3f}&end={now:.3f}&step=1"
            )
            t0 = time.perf_counter()
            doc = _get_json(url)
            tails.append(time.perf_counter() - t0)
            if doc["partial"]:
                result["error"] = f"unexpected partial at query {q}: {doc['targets']}"
                return result
        p99 = _percentile(tails, 0.99)
        result["query_p99_ms"] = round(p99 * 1e3, 2)
        result["query_p50_ms"] = round(_percentile(tails, 0.5) * 1e3, 2)
        result["budget_ms"] = budget_ms

        # --- traceparent propagation: node-side serve spans joined -------
        result["node_side_query_spans"] = sim.scrape_spans_recorded()
        if result["node_side_query_spans"] == 0:
            result["error"] = "no node-side /api/v1 spans recorded (traceparent lost)"
            return result

        # --- kill one target mid-query → partial, remainder merged -------
        if kill_one:
            victim_idx = n_targets // 2
            killed = {}

            def _kill() -> None:
                time.sleep(0.002)  # land inside the fan-out below
                killed["target"] = sim.kill(victim_idx)

            # New aggregator round first: the result cache keys on the
            # round generation, and the kill assertions below must observe
            # live fan-outs, not a pre-kill cached envelope.
            agg.poll_once()
            killer = threading.Thread(target=_kill, name="fleet-demo-kill",
                                      daemon=True)
            killer.start()
            now = time.time()
            _get_json(
                f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
                f"&start={now - 120:.3f}&end={now:.3f}&step=1"
            )  # the mid-kill query: partial OR full depending on the race
            killer.join(timeout=5)
            agg.poll_once()  # next round: fresh generation after the kill
            now = time.time()
            doc = _get_json(
                f"{base}/api/v1/query_range?metric=tpu_tensorcore_duty_cycle_percent"
                f"&start={now - 120:.3f}&end={now:.3f}&step=1"
            )
            result["after_kill"] = {
                "killed": killed.get("target"),
                "partial": doc["partial"],
                "ok_targets": doc["fleet"]["ok"],
                "merged_series": doc["fleet"]["merged_series"],
                "victim_state": doc["targets"][killed["target"]]["state"],
            }
            if not doc["partial"]:
                result["error"] = "killed target did not yield partial=true"
                return result
            if doc["fleet"]["ok"] != n_targets - 1:
                result["error"] = (
                    f"expected {n_targets - 1} ok targets after kill, "
                    f"got {doc['fleet']['ok']}"
                )
                return result
            if doc["fleet"]["merged_series"] != (n_targets - 1) * chips:
                result["error"] = (
                    f"expected {(n_targets - 1) * chips} merged series "
                    f"after kill, got {doc['fleet']['merged_series']}"
                )
                return result

        if p99 > budget_ms / 1e3:
            result["error"] = (
                f"fleet query p99 {p99 * 1e3:.1f}ms exceeds budget "
                f"{budget_ms:.0f}ms"
            )
            return result
        result["ok"] = True
        return result
    finally:
        if agg_server is not None:
            agg_server.stop()
        if fleet is not None:
            fleet.close()
        if agg is not None:
            agg.close()
        sim.close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-loadgen-fleet",
        description="Simulated-fleet acceptance harness for the federated "
                    "query plane (make fleet-query-demo).",
    )
    p.add_argument("--targets", type=int, default=64)
    p.add_argument("--chips", type=int, default=4, help="chips per host")
    p.add_argument("--polls", type=int, default=10,
                   help="history-priming polls before aggregation")
    p.add_argument("--interval-s", type=float, default=0.02,
                   help="pause between priming polls")
    p.add_argument("--queries", type=int, default=40,
                   help="latency-measurement queries (cache-busted)")
    p.add_argument("--budget-ms", type=float, default=1500.0,
                   help="fleet query p99 budget")
    p.add_argument("--kill-one", action="store_true", default=True)
    p.add_argument("--no-kill", dest="kill_one", action="store_false",
                   help="skip the mid-run target kill")
    p.add_argument("--no-persist", dest="persist", action="store_false",
                   default=True, help="disable per-target persistence")
    ns = p.parse_args(argv)

    result = run_demo(
        ns.targets, ns.chips, ns.polls, ns.interval_s,
        ns.queries, ns.budget_ms, ns.kill_one, ns.persist,
    )
    print(json.dumps(result, indent=1))
    if not result["ok"]:
        print(f"FLEET QUERY DEMO FAILED: {result.get('error')}",
              file=sys.stderr)
        return 1
    print(
        f"fleet-query-demo OK: {ns.targets} targets, "
        f"p99 {result['query_p99_ms']}ms (budget {ns.budget_ms:.0f}ms), "
        f"kill→partial asserted" if ns.kill_one else "kill skipped",
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
