"""Streaming dashboard plane — per-round delta subscriptions over /api/v1.

PR 13 made a *pull* cheap (cached-bytes scrapes off the event loop) and the
fleet plane's generation-keyed result cache already collapses N dashboard
panels into one fan-out per round — but every viewer refresh still re-sends
a full body, and there is exactly one root to send it. This module inverts
pull into push: a client registers a query **once**
(``GET /api/v1/stream?metric=...``) and thereafter receives per-round
*deltas* — the changed series only, the same "ship what changed" idiom the
exposition splice (``metrics.registry.ExpositionTemplate``) and the egress
delta batches (``egress.py``) already use.

Cost model, the whole point of the inversion:

- **One delta computation per query shape per round**, shared by every
  subscriber of that shape (the hub answers through the tier's existing
  query plane, whose generation-keyed cache makes the underlying fan-out
  once-per-round too).
- **One small write per subscriber per round**, handed to the event loop
  (``server.py``) — no per-viewer threads, no per-viewer fan-outs, and a
  stalled viewer costs a write-progress deadline, never a handler thread.

Stream rot defenses, all lessons already paid for elsewhere in the tree:

- an initial **snapshot** frame at registration (delta streams need a base);
- periodic **full_sync** frames (``full_sync_s`` — the egress lesson:
  delta-only streams rot; a missed frame or a bug on either side
  self-heals within one sync period);
- **heartbeat** frames while rounds are quiet (idle TCP streams die
  silently behind NATs and proxies);
- a **shape-level ``seq``** on every data frame so a client can prove it
  missed nothing (the dashboard-storm drill's zero-missed/zero-duplicate
  invariant reads it);
- a subscriber cap (admission) plus a ``stream_shed`` memory-ladder rung
  (``pressure.register_stream_rung``) that sheds the oldest subscriptions,
  counted — policy, never silent.

Transports: SSE (``event:``/``data:`` frames on a close-delimited response)
is the default; ``?transport=longpoll`` is the chunked long-poll fallback —
each request carries a ``cursor`` (the last seq seen) and the server holds
it until a newer frame exists, then answers with the missed frames.

Delta semantics (exactness by construction): the hub keys every row of the
polled answer by its series identity ``(metric, sorted labels)``; a delta
carries the rows whose content changed plus the keys that vanished.
Replaying snapshot + deltas therefore reproduces the polled answer's row
set *bit for bit* (``StreamReplay``; property-tested in
``tests/test_stream.py`` against seeded value/layout/membership churn).

Thread contract: ``on_round`` is called by the tier's ONE round thread
(after publish); ``subscribe``/``poll_frames`` run on server worker
threads; ``tick`` runs on the event loop. The hub lock guards registry
state only — query evaluation, JSON serialization and subscriber writes
all happen outside it.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Mapping

from tpu_pod_exporter.metrics import CounterStore, HistogramStore, schema
from tpu_pod_exporter.metrics.registry import SnapshotBuilder
from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.stream")

STREAM_ROUTES: tuple[str, ...] = ("series", "query_range", "window_stats",
                                 "alerts")

# Frame types a data-bearing frame may carry (heartbeats repeat the last
# seq instead of consuming one; continuity is asserted over these three).
DATA_FRAME_TYPES: tuple[str, ...] = ("snapshot", "delta", "full_sync")


class HubFull(Exception):
    """Subscriber cap reached — the caller answers 429 and the client
    should retry against a read replica."""


class StreamDisabled(Exception):
    """No hub attached on this tier (the server answers 404)."""


# ------------------------------------------------------------------ shapes


@dataclass(frozen=True)
class QueryShape:
    """One registered query: the canonical identity every subscriber of
    the same dashboard panel shares. ``window_s`` is the trailing span the
    per-round evaluation covers (``end=now`` each round; ``query_range``
    grid-aligns through the plane's existing step snapping, so successive
    rounds inside one step bucket produce identical grids and ship no
    bytes)."""

    route: str
    metric: str = ""
    match: tuple[tuple[str, str], ...] = ()
    window_s: float = 60.0
    step: float = 0.0
    agg: str = "last"

    @property
    def key(self) -> tuple:
        return (self.route, self.metric, self.match, self.window_s,
                self.step, self.agg)

    def params_doc(self) -> dict[str, Any]:
        """JSON-able echo of the registered query (rides the snapshot
        frame so a client can prove what the server heard)."""
        doc: dict[str, Any] = {"route": self.route}
        if self.route not in ("series", "alerts"):
            doc["metric"] = self.metric
            doc["match"] = dict(self.match)
            doc["window"] = self.window_s
        if self.route == "query_range":
            doc["step"] = self.step
            doc["agg"] = self.agg
        return doc

    @classmethod
    def from_params(cls, param: Callable[[str], str | None],
                    match: Mapping[str, str] | None = None) -> "QueryShape":
        """Validated construction from HTTP query params; raises
        ValueError with a message naming the offending token (the server
        maps it to the same 400 contract as the polled routes)."""
        route = param("route") or "window_stats"
        if route not in STREAM_ROUTES:
            raise ValueError(
                f"route must be one of {'/'.join(STREAM_ROUTES)}"
            )
        if route in ("series", "alerts"):
            # Parameterless shapes: every subscriber shares one canonical
            # identity (alerts rows are keyed by alertname + instance
            # labels; transitions arrive as row deltas).
            return cls(route=route)
        metric = param("metric")
        if not metric:
            raise ValueError("missing required parameter: metric")
        window = float(param("window") or
                       (300.0 if route == "query_range" else 60.0))
        if not window > 0 or window != window or window == float("inf"):
            raise ValueError("window must be a finite number > 0")
        step = 0.0
        agg = "last"
        if route == "query_range":
            # Streams REQUIRE a step: step=0 (raw samples) re-anchors the
            # grid at every round's wall clock, so every row would change
            # every round (full-body "deltas") and the plane's grid-
            # aligned generation cache could never hit — the whole
            # one-evaluation-per-shape cost model needs a grid to share.
            step = float(param("step") or 0.0)
            if not (step > 0 and step == step and step != float("inf")):
                raise ValueError(
                    "query_range streams need a finite step > 0 (a "
                    "stepless sliding window re-ships every row every "
                    "round; use route=window_stats for scalar panels)"
                )
            if window / step > 11000:
                raise ValueError(
                    "query resolution too high: window / step must be "
                    "<= 11000"
                )
            agg = param("agg") or "last"
            if agg not in ("last", "min", "max", "mean"):
                raise ValueError("agg must be one of last/min/max/mean")
        return cls(
            route=route, metric=metric,
            match=tuple(sorted((match or {}).items())),
            window_s=window, step=step, agg=agg,
        )


def row_key(row: Mapping[str, Any]) -> tuple:
    """Series identity of one answer row — the label-identity keying every
    merge tier already uses (``fleet._merge`` / ``RootQueryPlane``)."""
    labels = row.get("labels")
    if not isinstance(labels, Mapping):
        labels = {}
    return (str(row.get("metric", "")),
            tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def key_doc(key: tuple) -> list:
    """JSON-able form of a row key (rides delta frames' ``removed``)."""
    return [key[0], [[k, v] for k, v in key[1]]]


def doc_key(doc: Any) -> tuple:
    """Inverse of :func:`key_doc` (client side)."""
    metric, pairs = doc
    return (str(metric), tuple((str(k), str(v)) for k, v in pairs))


def sse_bytes(frame_json: str, frame_type: str) -> bytes:
    return (b"event: " + frame_type.encode("ascii")
            + b"\ndata: " + frame_json.encode("utf-8") + b"\n\n")


# ------------------------------------------------------------- hub internals


@dataclass
class _Subscriber:
    """One live SSE subscription. ``writer`` hands frame bytes to the
    event loop; ``closer`` asks the loop to flush-then-close the
    connection (used by shed). Both must be thread-safe (the server's
    are call_soon posts). ``base_seq`` is the seq the snapshot was built
    at; frames committed before :meth:`StreamHub.activate` flips
    ``started`` are caught up from the shape ring, never lost."""

    shape_key: tuple
    writer: Callable[[bytes], None]
    closer: Callable[[], None]
    created: float
    base_seq: int = 0
    started: bool = False
    closed: bool = False


@dataclass
class _Waiter:
    """One parked long-poll request: answered by the next data frame past
    ``cursor``, or by a heartbeat when ``deadline`` passes."""

    shape_key: tuple
    cursor: int
    callback: Callable[[dict], None]
    deadline: float
    done: bool = False


class _ShapeState:
    """Per-shape registry entry. ``seq``/``rows_by_key``/``ring`` are
    written only under the hub lock (commit step of ``on_round`` /
    first-subscribe init); readers take the lock briefly and never hold
    it across serialization."""

    __slots__ = ("shape", "seq", "generation", "rows_by_key", "meta",
                 "ring", "subscribers", "waiters", "last_full_wall",
                 "last_push_wall", "last_used_mono", "bytes_est")

    RING_FRAMES = 32

    def __init__(self, shape: QueryShape) -> None:
        self.shape = shape
        self.seq = 0
        self.generation = -1
        self.rows_by_key: dict[tuple, dict] | None = None
        self.meta: dict[str, Any] = {}
        # (seq, frame_type, frame_json, sse) of recent data frames — the
        # long-poll catch-up window.
        self.ring: deque[tuple[int, str, str, bytes]] = deque(
            maxlen=self.RING_FRAMES)
        self.subscribers: list[_Subscriber] = []
        self.waiters: list[_Waiter] = []
        self.last_full_wall = 0.0
        self.last_push_wall = 0.0
        self.last_used_mono = 0.0
        self.bytes_est = 0


def _frame_meta(env: Mapping[str, Any], full: bool) -> dict[str, Any]:
    """Envelope extras worth shipping. Full frames carry the fleet health
    summary AND the per-target status map (status --watch's degraded-
    target footer reads it; refreshed once per full_sync_s); deltas carry
    only the two flags a renderer needs — per-target durations change
    every round and would make every delta fat."""
    meta: dict[str, Any] = {
        "partial": bool(env.get("partial")),
        "source": env.get("source", "live"),
    }
    if full:
        fl = env.get("fleet")
        if isinstance(fl, Mapping):
            meta["fleet"] = dict(fl)
        tg = env.get("targets")
        if isinstance(tg, Mapping):
            meta["targets"] = dict(tg)
    return meta


class StreamHub:
    """The subscription registry plus per-round delta fan-in/fan-out.

    ``poll_fn(shape, generation)`` answers one registered query with the
    tier's regular envelope (the server wires it to the same plane the
    polled ``/api/v1`` routes use, so streamed and polled answers cannot
    drift). ``generation_fn`` is the tier's round counter (the same value
    the result cache keys on).
    """

    # A slow subscriber's pending-bytes cap lives in the server (it owns
    # the buffers); the hub's own bound is the subscriber cap.
    def __init__(
        self,
        poll_fn: Callable[[QueryShape, int], dict],
        generation_fn: Callable[[], int],
        heartbeat_s: float = 10.0,
        full_sync_s: float = 60.0,
        max_subscribers: int = 10000,
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self._poll_fn = poll_fn
        self._generation_fn = generation_fn
        self.heartbeat_s = heartbeat_s
        self.full_sync_s = full_sync_s
        self._max_subscribers = max_subscribers
        # The admission cap as configured — pressure shed halves the
        # EFFECTIVE cap; recovery restores this one.
        self._configured_max = max_subscribers
        self._clock = clock
        self._wallclock = wallclock
        self._lock = threading.Lock()
        self._shapes: dict[tuple, _ShapeState] = {}
        self._n_subscribers = 0
        self._rlog = RateLimitedLogger(log)
        self._counters = CounterStore()
        self._hist = HistogramStore(schema.TPU_STREAM_PUSH_SECONDS)
        # Pre-seed the conditional surface (stable from first exposition).
        for t in ("snapshot", "delta", "full_sync", "heartbeat"):
            self._counters.inc(schema.TPU_STREAM_FRAMES_TOTAL.name, (t,), 0.0)
        for tr in ("sse", "longpoll"):
            self._counters.inc(schema.TPU_STREAM_SUBSCRIBES_TOTAL.name,
                               (tr,), 0.0)
        self._counters.inc(schema.TPU_STREAM_REJECTS_TOTAL.name, ("cap",),
                           0.0)
        for r in ("pressure", "slow", "cap"):
            self._counters.inc(schema.TPU_STREAM_SHEDS_TOTAL.name, (r,), 0.0)
        self._counters.inc(schema.TPU_STREAM_FRAME_BYTES_TOTAL.name, (), 0.0)
        # Push-latency witnesses for the storm drill: wall ts of the last
        # on_round entry (frames also carry their emission wall ts).
        self.last_round_wall = 0.0

    # ------------------------------------------------------------ lifecycle

    @property
    def subscribers(self) -> int:
        with self._lock:
            return self._n_subscribers

    @property
    def max_subscribers(self) -> int:
        return self._max_subscribers

    def set_max_subscribers(self, n: int) -> None:
        """Runtime cap change: updates BOTH the configured cap (what
        pressure release restores / apply halves) and the effective one —
        otherwise the next pressure cycle would silently revert it."""
        self._configured_max = max(0, int(n))
        self._max_subscribers = self._configured_max

    # ------------------------------------------------------------ subscribe

    def _shape_state(self, shape: QueryShape) -> _ShapeState:
        """Registry entry for ``shape``, created (and primed with an
        initial evaluation) on first use. The evaluation runs OUTSIDE the
        lock; a racing first-subscriber's result commits only if the slot
        is still unprimed."""
        with self._lock:
            st = self._shapes.get(shape.key)
            if st is None:
                st = self._shapes[shape.key] = _ShapeState(shape)
                # The heartbeat countdown starts at creation, not at the
                # epoch — a fresh stream must not open with a heartbeat.
                st.last_push_wall = self._wallclock()
            st.last_used_mono = self._clock()
            if st.rows_by_key is not None:
                return st
        generation = self._generation_fn()
        env = self._poll_fn(shape, generation)
        rows = _env_rows(shape.route, env)
        new_map: dict[tuple, dict] = {}
        for row in rows:
            if isinstance(row, dict):
                new_map[row_key(row)] = row
        meta = _frame_meta(env, full=True)
        now_wall = self._wallclock()
        with self._lock:
            if st.rows_by_key is None:
                st.rows_by_key = new_map
                st.meta = meta
                st.generation = generation
                st.last_full_wall = now_wall
        return st

    def subscribe(
        self,
        shape: QueryShape,
        writer: Callable[[bytes], None],
        closer: Callable[[], None],
        auto_start: bool = True,
    ) -> tuple[_Subscriber, bytes]:
        """Register one SSE subscription; returns the subscriber handle
        plus the initial bytes (snapshot frame, and with ``auto_start``
        any data frames that landed while it was being serialized) the
        caller must write first. Raises :class:`HubFull` at the cap.

        ``auto_start=False`` (the server's mode) defers the catch-up +
        push enablement to :meth:`activate`, which the caller runs ONLY
        once its transport is ready to accept writer() frames — a round
        committed between subscribe and transport-ready would otherwise
        race the writer against the transport setup and silently drop a
        frame (a permanent seq gap until the next full sync)."""
        with self._lock:
            if self._n_subscribers >= self._max_subscribers:
                self._counters.inc(schema.TPU_STREAM_REJECTS_TOTAL.name,
                                   ("cap",))
                raise HubFull(
                    f"stream subscriber cap reached "
                    f"({self._max_subscribers})"
                )
            self._n_subscribers += 1
        try:
            st = self._shape_state(shape)
        except Exception:
            with self._lock:
                self._n_subscribers -= 1
            raise
        sub = _Subscriber(shape_key=shape.key, writer=writer, closer=closer,
                          created=self._clock())
        with self._lock:
            base_seq = st.seq
            rows = list((st.rows_by_key or {}).values())
            meta = dict(st.meta)
            generation = st.generation
            st.subscribers.append(sub)
        # Serialize OUTSIDE the lock (lock-io discipline); frames that
        # commit meanwhile are caught up from the ring below.
        frame = {
            "type": "snapshot", "seq": base_seq, "gen": generation,
            "ts": self._wallclock(), "shape": shape.params_doc(),
            "rows": rows, "meta": meta,
        }
        payload = sse_bytes(_dumps(frame), "snapshot")
        sub.base_seq = base_seq
        catchup: list[bytes] = []
        with self._lock:
            if auto_start:
                catchup = [s for q, _t, _j, s in st.ring if q > base_seq]
                sub.started = True
            if st.bytes_est == 0:
                # Memory accounting from the first subscriber on — a
                # shape that never full-synced must not read as free.
                st.bytes_est = len(payload)
        self._counters.inc(schema.TPU_STREAM_SUBSCRIBES_TOTAL.name, ("sse",))
        self._counters.inc(schema.TPU_STREAM_FRAMES_TOTAL.name,
                           ("snapshot",))
        out = payload + b"".join(catchup)
        self._counters.inc(schema.TPU_STREAM_FRAME_BYTES_TOTAL.name, (),
                           float(len(out)))
        return sub, out

    def activate(self, sub: _Subscriber) -> bytes:
        """Second half of ``subscribe(auto_start=False)``: atomically
        collect every data frame committed since the snapshot's base seq
        (from the shape ring) and enable round pushes. Returns the
        catch-up bytes the caller must append after the snapshot — a
        frame is either in the catch-up or pushed via writer(), never
        dropped and never duplicated."""
        with self._lock:
            if sub.closed or sub.started:
                return b""
            st = self._shapes.get(sub.shape_key)
            if st is None:
                return b""
            catchup = [s for q, _t, _j, s in st.ring if q > sub.base_seq]
            sub.started = True
        if catchup:
            self._counters.inc(schema.TPU_STREAM_FRAME_BYTES_TOTAL.name, (),
                               float(sum(len(c) for c in catchup)))
        return b"".join(catchup)

    def detach(self, sub: _Subscriber) -> None:
        """Connection closed (client drop, write deadline, server stop)."""
        with self._lock:
            if sub.closed:
                return
            sub.closed = True
            st = self._shapes.get(sub.shape_key)
            if st is not None:
                try:
                    st.subscribers.remove(sub)
                except ValueError:
                    pass
            self._n_subscribers -= 1

    def count_slow_shed(self) -> None:
        """The server shed a subscriber whose pending write buffer blew
        the cap (it owns the buffers; the hub owns the counter)."""
        self._counters.inc(schema.TPU_STREAM_SHEDS_TOTAL.name, ("slow",))

    # -------------------------------------------------------------- rounds

    def on_round(self, generation: int | None = None) -> None:
        """One round happened: evaluate every live shape once, push the
        delta to its subscribers, answer its parked long-polls. Called by
        the tier's round thread AFTER publish (single caller by contract —
        seq/ring have one writer)."""
        if generation is None:
            generation = self._generation_fn()
        now_wall = self._wallclock()
        self.last_round_wall = now_wall
        with self._lock:
            live = [st for st in self._shapes.values()
                    if st.subscribers or st.waiters]
        for st in live:
            t0 = self._clock()
            try:
                env = self._poll_fn(st.shape, generation)
            except Exception as e:  # noqa: BLE001 — one bad shape must not stall the rest
                self._rlog.warning(f"shape:{st.shape.key!r}",
                                   "stream shape evaluation failed: %s", e)
                continue
            rows = _env_rows(st.shape.route, env)
            new_map: dict[tuple, dict] = {}
            for row in rows:
                if isinstance(row, dict):
                    new_map[row_key(row)] = row
            with self._lock:
                old_map = st.rows_by_key or {}
                seq = st.seq
            changed = [r for k, r in new_map.items() if old_map.get(k) != r]
            removed = [key_doc(k) for k in old_map if k not in new_map]
            full_due = (self.full_sync_s > 0
                        and now_wall - st.last_full_wall >= self.full_sync_s)
            if not changed and not removed and not full_due:
                # Nothing to ship: the heartbeat timer covers liveness.
                with self._lock:
                    st.generation = generation
                continue
            seq += 1
            # Stamped at BUILD time, per frame: ts is the push-latency
            # witness (client recv minus ts), and an entry-time stamp
            # would bill every shape for the evaluation time of the
            # shapes computed before it in this pass.
            frame_wall = self._wallclock()
            if full_due:
                ftype = "full_sync"
                frame: dict[str, Any] = {
                    "type": ftype, "seq": seq, "gen": generation,
                    "ts": frame_wall, "rows": list(new_map.values()),
                    "meta": _frame_meta(env, full=True),
                }
            else:
                ftype = "delta"
                frame = {
                    "type": ftype, "seq": seq, "gen": generation,
                    "ts": frame_wall, "changed": changed,
                    "removed": removed,
                    "meta": _frame_meta(env, full=False),
                }
            frame_json = _dumps(frame)
            payload = sse_bytes(frame_json, ftype)
            with self._lock:
                st.seq = seq
                st.generation = generation
                st.rows_by_key = new_map
                st.meta = _frame_meta(env, full=True)
                st.ring.append((seq, ftype, frame_json, payload))
                st.last_push_wall = now_wall
                if full_due:
                    st.last_full_wall = now_wall
                    # bytes_est refreshed on every full sync; deltas
                    # leave the retained-rows estimate alone (drift is
                    # bounded by one full_sync period).
                    st.bytes_est = len(frame_json)
                subs = [s for s in st.subscribers if s.started]
                waiters = [w for w in st.waiters if not w.done]
                st.waiters = []
            self._push(subs, payload, ftype)
            for w in waiters:
                self._answer_waiter(w, [(seq, frame_json)])
            self._hist.observe(self._clock() - t0)

    def _push(self, subs: list[_Subscriber], payload: bytes,
              ftype: str) -> None:
        n = 0
        for sub in subs:
            if sub.closed:
                continue
            try:
                sub.writer(payload)
                n += 1
            except Exception:  # noqa: BLE001 — one dead writer must not stop the fan-out
                self.detach(sub)
        if n:
            self._counters.inc(schema.TPU_STREAM_FRAMES_TOTAL.name,
                               (ftype,), float(n))
            self._counters.inc(schema.TPU_STREAM_FRAME_BYTES_TOTAL.name, (),
                               float(n * len(payload)))

    # ----------------------------------------------------------- long-poll

    def poll_frames(
        self,
        shape: QueryShape,
        cursor: int | None,
        callback: Callable[[dict], None],
        wait_s: float | None = None,
    ) -> dict | None:
        """Long-poll transport: answer immediately when frames newer than
        ``cursor`` exist (or no cursor → snapshot), else park the request;
        ``callback`` fires with the answer document from a later
        ``on_round``/``tick``. Returns the immediate answer or None when
        parked."""
        st = self._shape_state(shape)
        self._counters.inc(schema.TPU_STREAM_SUBSCRIBES_TOTAL.name,
                           ("longpoll",))
        with self._lock:
            seq = st.seq
            generation = st.generation
            if cursor is None or cursor > seq:
                rows = list((st.rows_by_key or {}).values())
                meta = dict(st.meta)
                snap = True
                ring: list[tuple[int, str]] = []
            elif cursor < seq:
                ring = [(q, j) for q, _t, j, _s in st.ring if q > cursor]
                snap = not ring or ring[0][0] != cursor + 1
                if snap:
                    # The ring no longer reaches the cursor: resync.
                    rows = list((st.rows_by_key or {}).values())
                    meta = dict(st.meta)
                    ring = []
            else:
                # Waiter deadline: heartbeat cadence, or a sane hold when
                # heartbeats are disabled — a parked long-poll must ALWAYS
                # get answered (tick() expires waiters unconditionally).
                hold = (wait_s if wait_s is not None
                        else (self.heartbeat_s if self.heartbeat_s > 0
                              else 25.0))
                w = _Waiter(
                    shape_key=shape.key, cursor=cursor, callback=callback,
                    deadline=self._clock() + hold,
                )
                st.waiters.append(w)
                return None
        if snap:
            frame = {
                "type": "snapshot", "seq": seq, "gen": generation,
                "ts": self._wallclock(), "shape": shape.params_doc(),
                "rows": rows, "meta": meta,
            }
            self._counters.inc(schema.TPU_STREAM_FRAMES_TOTAL.name,
                               ("snapshot",))
            return {"status": "ok", "cursor": seq, "frames": [frame]}
        frames = [json.loads(j) for _q, j in ring]
        return {"status": "ok", "cursor": ring[-1][0], "frames": frames}

    def _answer_waiter(self, w: _Waiter,
                       frames: list[tuple[int, str]]) -> None:
        if w.done:
            return
        w.done = True
        doc = {"status": "ok", "cursor": frames[-1][0],
               "frames": [json.loads(j) for _q, j in frames]}
        try:
            w.callback(doc)
        except Exception:  # noqa: BLE001 — a dead waiter must not stop the round
            log.exception("long-poll waiter callback failed")

    # ----------------------------------------------------------------- tick

    def tick(self, now: float | None = None) -> None:
        """Periodic maintenance (the server arms a 1 s loop timer):
        heartbeats to quiet subscribers, heartbeat answers to expired
        long-poll waiters, and GC of shapes nobody watches."""
        mono = self._clock() if now is None else now
        now_wall = self._wallclock()
        hb_due: list[tuple[_ShapeState, list[_Subscriber],
                           list[_Waiter]]] = []
        with self._lock:
            for key in [k for k, st in self._shapes.items()
                        if not st.subscribers and not st.waiters
                        and mono - st.last_used_mono > 60.0]:
                del self._shapes[key]
            for st in self._shapes.values():
                # Waiter expiry is UNCONDITIONAL: a parked long-poll must
                # be answered even with heartbeat frames disabled
                # (heartbeat_s gates only the subscriber-side keep-alives).
                expired = [w for w in st.waiters
                           if not w.done and w.deadline <= mono]
                subs: list[_Subscriber] = []
                if (self.heartbeat_s > 0 and st.subscribers
                        and now_wall - st.last_push_wall
                        >= self.heartbeat_s):
                    subs = [s for s in st.subscribers if s.started]
                    st.last_push_wall = now_wall
                if expired:
                    st.waiters = [w for w in st.waiters
                                  if not w.done and w.deadline > mono]
                if subs or expired:
                    hb_due.append((st, subs, expired))
        for st, subs, expired in hb_due:
            frame = {"type": "heartbeat", "seq": st.seq,
                     "gen": st.generation, "ts": now_wall}
            frame_json = _dumps(frame)
            if subs:
                self._push(subs, sse_bytes(frame_json, "heartbeat"),
                           "heartbeat")
            for w in expired:
                if w.done:
                    continue
                w.done = True
                doc = {"status": "ok", "cursor": st.seq,
                       "frames": [json.loads(frame_json)]}
                try:
                    w.callback(doc)
                except Exception:  # noqa: BLE001 — a dead waiter must not stop the tick
                    log.exception("long-poll heartbeat callback failed")

    # ------------------------------------------------------------- pressure

    def shed_oldest(self, fraction: float = 0.5,
                    reason: str = "pressure") -> int:
        """Close the oldest ``fraction`` of live subscriptions (each gets
        a final ``shed`` frame naming the reason, then its connection is
        closed — the client should reconnect against a replica). The
        memory ladder's ``stream_shed`` rung. Returns the count shed."""
        with self._lock:
            subs = [s for st in self._shapes.values()
                    for s in st.subscribers if not s.closed]
        if not subs:
            return 0
        subs.sort(key=lambda s: s.created)
        n = max(1, int(len(subs) * fraction))
        victims = subs[:n]
        frame = _dumps({"type": "shed", "reason": reason,
                        "ts": self._wallclock()})
        payload = sse_bytes(frame, "shed")
        for sub in victims:
            try:
                sub.writer(payload)
                sub.closer()
            except Exception:  # noqa: BLE001 — shedding must not raise
                pass
            self.detach(sub)
            self._counters.inc(schema.TPU_STREAM_SHEDS_TOTAL.name, (reason,))
        return len(victims)

    def apply_pressure(self) -> None:
        """``stream_shed`` rung apply: shed the oldest half and halve the
        effective cap so a storm cannot instantly refill what was shed."""
        self.shed_oldest(0.5, reason="pressure")
        self._max_subscribers = max(1, self._configured_max // 2)

    def release_pressure(self) -> None:
        self._max_subscribers = self._configured_max

    def shape_seqs(self) -> dict[tuple, int]:
        """Current data-frame seq per shape key — the drills' catch-up
        oracle: a subscriber is caught up when its replay seq reaches its
        shape's seq (a shape whose rows did not change ships nothing, so
        'saw every generation' would be the wrong invariant)."""
        with self._lock:
            return {key: st.seq for key, st in self._shapes.items()}

    def memory_bytes(self) -> int:
        """Estimated retained bytes (last answers + catch-up rings) for
        the memory budget's component accounting — the same number
        /debug/vars reports."""
        total = 0
        with self._lock:
            for st in self._shapes.values():
                total += st.bytes_est
                total += sum(len(j) for _q, _t, j, _s in st.ring)
        return total

    # ------------------------------------------------------------ exposition

    def emit(self, b: SnapshotBuilder) -> None:
        """Publish the plane's self-metrics into one snapshot (called from
        the owning tier's publish via its emit hook — conditional surface,
        present only while a hub is attached)."""
        for spec in schema.STREAM_SPECS:
            b.declare(spec)
        with self._lock:
            n_subs = self._n_subscribers
            n_shapes = len(self._shapes)
        b.add(schema.TPU_STREAM_SUBSCRIBERS, float(n_subs))
        b.add(schema.TPU_STREAM_QUERY_SHAPES, float(n_shapes))
        for spec in (schema.TPU_STREAM_SUBSCRIBES_TOTAL,
                     schema.TPU_STREAM_REJECTS_TOTAL,
                     schema.TPU_STREAM_FRAMES_TOTAL,
                     schema.TPU_STREAM_FRAME_BYTES_TOTAL,
                     schema.TPU_STREAM_SHEDS_TOTAL):
            for lv, v in self._counters.items_for(spec.name):
                b.add(spec, v, lv)
        self._hist.emit(b)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "subscribers": self._n_subscribers,
                "shapes": len(self._shapes),
                "max_subscribers": self._max_subscribers,
                "configured_max_subscribers": self._configured_max,
                "heartbeat_s": self.heartbeat_s,
                "full_sync_s": self.full_sync_s,
                "waiters": sum(len(st.waiters)
                               for st in self._shapes.values()),
                "memory_bytes_est": sum(
                    st.bytes_est + sum(len(j) for _q, _t, j, _s in st.ring)
                    for st in self._shapes.values()
                ),
            }

    def close(self) -> None:
        with self._lock:
            subs = [s for st in self._shapes.values()
                    for s in st.subscribers]
            self._shapes.clear()
            self._n_subscribers = 0
        for sub in subs:
            sub.closed = True
            try:
                sub.closer()
            except Exception:  # noqa: BLE001 — draining must finish
                pass


def attach_stream(
    agg: Any,
    plane: Any,
    heartbeat_s: float = 10.0,
    full_sync_s: float = 60.0,
    max_subscribers: int = 10000,
    alerts_fn: Callable[[], list] | None = None,
) -> tuple[StreamHub, "StreamPump"]:
    """Standard tier wiring: a hub answering through ``plane`` (the same
    query plane the polled /api/v1 serves), generation = the tier's round
    counter, a started pump hooked to the tier's round hook, and the
    hub's self-metrics riding the tier's publish. Used by the aggregator,
    root and replica CLIs — one wiring path, not three twins.
    ``alerts_fn`` (root only) feeds the ``route=alerts`` shape."""
    hub = StreamHub(
        plane_poll_fn(plane, alerts_fn=alerts_fn),
        generation_fn=lambda: agg.rounds,
        heartbeat_s=heartbeat_s,
        full_sync_s=full_sync_s,
        max_subscribers=max_subscribers,
    )
    pump = StreamPump(hub)
    pump.start()
    agg.round_hooks.append(pump.notify)
    agg.emit_hooks.append(hub.emit)
    return hub, pump


class StreamPump:
    """Decouples the round thread from delta evaluation.

    ``poll_once`` must stay a merge + publish — evaluating K query shapes
    (each potentially a cached-or-real fan-out) on the round thread would
    read as round time and page the round-budget alerts. The tier's round
    hook costs one ``Event.set``; this pump's own (named, daemon) thread
    runs ``hub.on_round`` — the same poll-side-cheap discipline as the
    persistence and egress writer threads. Deterministic harnesses (the
    scenario engine, the drills) skip the pump and call ``on_round``
    directly.
    """

    def __init__(self, hub: StreamHub) -> None:
        self._hub = hub
        self._event = threading.Event()
        self._stopping = False
        self._generation = 0
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="tpu-stream-pump", daemon=True,
        )
        self._thread.start()

    def notify(self, generation: int) -> None:
        """Round hook (any thread): schedule one on_round pass. Back-to-
        back rounds coalesce — the pump always evaluates against the
        NEWEST generation, and a skipped intermediate round simply means
        one delta carries two rounds' changes (seq stays contiguous)."""
        self._generation = int(generation)
        self._event.set()

    def _run(self) -> None:
        while True:
            self._event.wait()
            self._event.clear()
            if self._stopping:
                return
            try:
                self._hub.on_round(self._generation)
            except Exception:  # noqa: BLE001 — one bad round must not kill the pump
                log.exception("stream pump round failed")

    def close(self) -> None:
        self._stopping = True
        self._event.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _dumps(obj: Any) -> str:
    """Frame serialization: compact separators (these bytes repeat per
    subscriber) and NaN-safe (same contract as the JSON routes)."""
    try:
        return json.dumps(obj, separators=(",", ":"), allow_nan=False)
    except ValueError:
        from tpu_pod_exporter.server import _json_sanitize

        return json.dumps(_json_sanitize(obj), separators=(",", ":"))


def _env_rows(route: str, env: Mapping[str, Any]) -> list:
    from tpu_pod_exporter.fleet import rows_of

    return rows_of(route, env)


# ---------------------------------------------------------------- poll_fn


def plane_poll_fn(plane: Any,
                  wallclock: Callable[[], float] = time.time,
                  alerts_fn: Callable[[], list] | None = None,
                  ) -> Callable[[QueryShape, int], dict]:
    """Adapter: a fleet-like query plane (``series``/``query_range``/
    ``window_stats``) → the hub's ``poll_fn``. The trailing window is
    re-anchored at now each round; the plane's own grid alignment and
    generation-keyed cache make repeated evaluations cheap. ``alerts_fn``
    feeds the ``route=alerts`` shape (the AlertEvaluator's active rows);
    a tier with no evaluator streams an empty, never-erroring row set."""

    def poll(shape: QueryShape, generation: int) -> dict:  # noqa: ARG001 — the plane caches by its own generation
        match = dict(shape.match)
        if shape.route == "alerts":
            rows = alerts_fn() if alerts_fn is not None else []
            return {"status": "ok", "source": "live",
                    "data": {"result": rows}}
        if shape.route == "series":
            return plane.series()
        if shape.route == "window_stats":
            return plane.window_stats(shape.metric, match,
                                      window_s=shape.window_s)
        end = wallclock()
        return plane.query_range(shape.metric, match,
                                 start=end - shape.window_s, end=end,
                                 step=shape.step, agg=shape.agg)

    return poll


def history_poll_fn(history: Any,
                    wallclock: Callable[[], float] = time.time,
                    ) -> Callable[[QueryShape, int], dict]:
    """Adapter for the node tier's HistoryStore: wraps its raw answers in
    the same envelope shape the fleet planes serve, so one replay client
    reads every tier."""

    def poll(shape: QueryShape, generation: int) -> dict:  # noqa: ARG001
        match = dict(shape.match)
        if shape.route == "alerts":
            # Node tier owns no evaluator — an alerts stream is legal but
            # empty (the root is where alerting lives).
            return {"status": "ok", "source": "live",
                    "data": {"result": []}}
        if shape.route == "series":
            return {"status": "ok", "source": "live",
                    "data": history.series_list()}
        if shape.route == "window_stats":
            result = history.window_stats(shape.metric, match,
                                          window_s=shape.window_s)
            return {"status": "ok", "source": "live",
                    "data": {"result": result or []}}
        end = wallclock()
        result = history.query_range(shape.metric, match,
                                     end - shape.window_s, end, shape.step,
                                     agg=shape.agg)
        return {"status": "ok", "source": "live",
                "data": {"resultType": "matrix", "result": result or []}}

    return poll


# ------------------------------------------------------------------ replay


class StreamReplay:
    """Client-side frame application + continuity accounting.

    Applying a snapshot then every subsequent delta/full_sync reproduces
    the polled answer's row set exactly (the server diffs whole rows by
    series key); ``gaps``/``dups`` count seq discontinuities — the
    dashboard-storm drill asserts both stay zero, and ``desynced`` flags
    a replay that saw a gap and has not yet been healed by a full_sync."""

    def __init__(self) -> None:
        self.rows: dict[tuple, dict] = {}
        self.meta: dict[str, Any] = {}
        self.shape_doc: dict[str, Any] | None = None
        self.seq: int | None = None
        self.generation: int | None = None
        self.frames = 0
        self.data_frames = 0
        self.gaps = 0
        self.dups = 0
        self.desynced = False
        self.shed_reason: str | None = None
        # Wall latency of the last frame (receiver clock minus the
        # frame's emission ts — meaningful when both sides share a host,
        # as in the drills).
        self.last_latency_s: float | None = None

    def apply(self, frame: Mapping[str, Any],
              recv_wall: float | None = None) -> None:
        self.frames += 1
        ftype = frame.get("type")
        ts = frame.get("ts")
        if recv_wall is not None and isinstance(ts, (int, float)):
            self.last_latency_s = max(recv_wall - float(ts), 0.0)
        if ftype == "shed":
            self.shed_reason = str(frame.get("reason", ""))
            return
        if ftype == "heartbeat":
            return
        if ftype not in DATA_FRAME_TYPES:
            return
        seq = int(frame.get("seq", 0))
        if ftype == "snapshot":
            self.shape_doc = dict(frame.get("shape") or {})
            self._load_full(frame, seq)
            self.desynced = False
        elif ftype == "full_sync":
            if self.seq is not None and seq > self.seq + 1:
                self.gaps += seq - self.seq - 1
            elif self.seq is not None and seq <= self.seq:
                self.dups += 1
                return
            self._load_full(frame, seq)
            self.desynced = False  # a full sync heals any earlier gap
        else:  # delta
            if self.seq is None:
                # Delta before any snapshot: unusable base.
                self.desynced = True
                return
            if seq <= self.seq:
                self.dups += 1
                return
            if seq > self.seq + 1:
                self.gaps += seq - self.seq - 1
                self.desynced = True
            for row in frame.get("changed") or []:
                if isinstance(row, dict):
                    self.rows[row_key(row)] = row
            for kd in frame.get("removed") or []:
                try:
                    self.rows.pop(doc_key(kd), None)
                except (TypeError, ValueError, IndexError):
                    continue
            self._meta(frame)
            self.seq = seq
            self.generation = int(frame.get("gen", 0))
        self.data_frames += 1

    def _load_full(self, frame: Mapping[str, Any], seq: int) -> None:
        self.rows = {}
        for row in frame.get("rows") or []:
            if isinstance(row, dict):
                self.rows[row_key(row)] = row
        self._meta(frame)
        self.seq = seq
        self.generation = int(frame.get("gen", 0))

    def _meta(self, frame: Mapping[str, Any]) -> None:
        meta = frame.get("meta")
        if isinstance(meta, Mapping):
            self.meta.update(meta)

    def rows_by_key(self) -> dict[tuple, dict]:
        return dict(self.rows)


def rows_map(route: str, env: Mapping[str, Any]) -> dict[tuple, dict]:
    """Polled envelope → the same keyed row map a replay reconstructs
    (the drills' equality oracle)."""
    return {row_key(r): r for r in _env_rows(route, env)
            if isinstance(r, dict)}


# ------------------------------------------------------------------ client


class SseParser:
    """Incremental SSE frame parser: feed raw bytes, get frame dicts.
    Shared by the blocking client below and the storm harness's
    selector-driven clients (loadgen)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> list[dict]:
        self._buf += data
        frames: list[dict] = []
        while True:
            idx = self._buf.find(b"\n\n")
            if idx < 0:
                break
            block = bytes(self._buf[:idx])
            del self._buf[:idx + 2]
            data_lines = [line[5:].strip() for line in block.split(b"\n")
                          if line.startswith(b"data:")]
            if not data_lines:
                continue
            try:
                frames.append(json.loads(b"\n".join(data_lines)))
            except ValueError:
                continue
        return frames


def stream_path(shape: QueryShape, transport: str = "",
                cursor: int | None = None) -> str:
    """``/api/v1/stream`` request path for one shape."""
    import urllib.parse

    params: dict[str, str] = {"route": shape.route}
    if shape.route != "series":
        params["metric"] = shape.metric
        params["window"] = f"{shape.window_s:g}"
        for k, v in shape.match:
            params[f"match[{k}]"] = v
    if shape.route == "query_range":
        params["step"] = f"{shape.step:g}"
        params["agg"] = shape.agg
    if transport:
        params["transport"] = transport
    if cursor is not None:
        params["cursor"] = str(cursor)
    return "/api/v1/stream?" + urllib.parse.urlencode(params)


class StreamClient:
    """Minimal blocking SSE subscriber (status --watch, tests, small
    drills; the 5-10k-connection storm harness uses its own selector loop
    over :class:`SseParser` instead)."""

    def __init__(self, host: str, port: int, shape: QueryShape,
                 timeout_s: float = 10.0) -> None:
        self.shape = shape
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        path = stream_path(shape)
        self._sock.sendall(
            f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
            f"Accept: text/event-stream\r\n\r\n".encode()
        )
        self._parser = SseParser()
        self._closed = False
        # Set when the server closed the stream (shed, restart, death) —
        # distinct from a frames() timeout; watchers read it to decide
        # between waiting more and falling back to polling.
        self.eof = False
        # Read the response head; non-200 means no stream here (the
        # caller falls back to polling).
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("stream endpoint closed during head")
            head += chunk
        head, _, rest = head.partition(b"\r\n\r\n")
        status_line = head.split(b"\r\n", 1)[0]
        parts = status_line.split()
        self.status = int(parts[1]) if len(parts) > 1 else 0
        if self.status != 200:
            body = rest
            try:
                while True:
                    chunk = self._sock.recv(4096)
                    if not chunk:
                        break
                    body += chunk
            except OSError:
                pass
            self.close()
            raise StreamDisabled(
                f"stream endpoint answered HTTP {self.status}: "
                f"{body[:200].decode('utf-8', 'replace')}"
            )
        self._pending: deque[dict] = deque(self._parser.feed(rest))

    def frames(self, max_frames: int | None = None,
               timeout_s: float | None = None) -> Iterator[dict]:
        """Yield frames as they arrive; stops on connection close, after
        ``max_frames``, or when one read waits past ``timeout_s``."""
        n = 0
        if timeout_s is not None:
            self._sock.settimeout(timeout_s)
        while max_frames is None or n < max_frames:
            while self._pending:
                yield self._pending.popleft()
                n += 1
                if max_frames is not None and n >= max_frames:
                    return
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                return
            except OSError:
                self.eof = True
                return
            if not chunk:
                self.eof = True
                return
            self._pending.extend(self._parser.feed(chunk))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass
