"""Source supervision — per-phase deadlines and breaker-gated reconnects.

The collector's per-phase error containment (``collector.py``) only covers
calls that *return*. A wedged libtpu stream, a stuck gRPC channel, or a hung
``/proc`` read parks the single poll thread forever: ``/metrics`` serves an
ever-staler snapshot until ``health_max_age_s`` finally flips ``/healthz``,
and nothing ever tries to recover. This module closes that gap with two
cooperating pieces:

- :class:`SourceSupervisor` runs each phase call on a dedicated worker
  thread with a hard deadline. On deadline the call is **abandoned** — the
  worker is fenced off (its eventual result is discarded; it exits when the
  blocked call finally returns) and is never joined-on-blocking, so the poll
  loop keeps its cadence. The phase degrades exactly as an error does.
- :class:`CircuitBreaker` tracks consecutive failures per source:
  closed → open (exponential backoff + jitter) → half-open single probe →
  closed. While open, calls are *skipped* (SourceSkipped) instead of burning
  a deadline each poll; each half-open probe first runs the source's
  ``reconnect`` hook (``close()``; the gRPC clients lazily re-``open`` on
  the next call), so a wedged channel is actually **replaced**, not retried
  into.

Breaker state, transitions, abandoned calls, skips, and reconnects surface
as first-class metrics (``metrics/schema.py``) and feed ``/readyz``'s
degraded-source detail. The aggregator reuses :class:`CircuitBreaker`
per scrape target (``aggregate.py``).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from typing import Any, Callable

from tpu_pod_exporter import trace as trace_mod
from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.supervisor")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding of breaker state (tpu_exporter_source_breaker_state).
STATE_VALUES = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

# A source is reported degraded in /readyz once it has (re-)opened this many
# consecutive times without reaching closed — "open for one backoff window"
# is an incident in progress, "open across N probes" is a wedged source.
DEGRADED_AFTER_REOPENS = 3

# Consecutive successes AFTER a half-open probe success before the breaker
# forgets its backoff history. One probe succeeding proves only that one
# request got through — under a flapping network partition that is the
# NORMAL failure shape (the scenario drills' flapping-partition case): a
# full reset on the probe would restart every incident at the base backoff
# and probe-storm the unreachable endpoint forever. Until this many
# follow-up successes land, a re-open resumes from the retained (halved)
# backoff and the cumulative reopen count, so a flapping cut settles at
# the backoff ceiling instead of oscillating at the base.
PROBATION_SUCCESSES = 2


class SourceTimeout(RuntimeError):
    """A supervised call exceeded its phase deadline and was abandoned."""


class SourceSkipped(RuntimeError):
    """The breaker is open and its backoff has not elapsed; no call made."""


class CircuitBreaker:
    """Consecutive-failure breaker with exponential backoff + jitter.

    Not thread-safe by design: each instance belongs to exactly one caller
    thread (the collector's poll thread, or one aggregator round's scrape
    of one target — the pool maps each target to a single call per round).

    ``decide()`` returns what the caller may do *now*:
    - ``"call"``  — closed; call normally.
    - ``"probe"`` — open and the backoff elapsed; the breaker has moved to
      half-open and admits exactly this one probe call.
    - ``"skip"``  — open (backoff pending) or a probe already in flight.
    """

    __slots__ = (
        "failure_threshold", "backoff_base_s", "backoff_max_s", "jitter",
        "state", "consecutive_failures", "reopens", "transitions",
        "_backoff_s", "_next_probe_at", "_clock", "_rng", "_probation",
    )

    def __init__(
        self,
        failure_threshold: int = 3,
        backoff_base_s: float = 1.0,
        backoff_max_s: float = 30.0,
        jitter: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
        rng: random.Random | None = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError("need 0 < backoff_base_s <= backoff_max_s")
        self.failure_threshold = failure_threshold
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.state = CLOSED
        self.consecutive_failures = 0
        # Times the breaker (re-)entered OPEN without an intervening close —
        # the /readyz degraded-source signal (DEGRADED_AFTER_REOPENS).
        self.reopens = 0
        # Cumulative entries into each state since construction; closed
        # counts only recoveries (not the initial state), so a never-failed
        # source shows all-zero transitions.
        self.transitions = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        self._backoff_s = 0.0
        self._next_probe_at = 0.0
        # Successes still owed before backoff history is forgotten (set by
        # a half-open probe success; see PROBATION_SUCCESSES).
        self._probation = 0
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()

    def decide(self) -> str:
        if self.state == CLOSED:
            return "call"
        if self.state == HALF_OPEN:
            # Single-probe rule: a probe is already in flight (only possible
            # if the caller re-enters before recording the probe's outcome).
            return "skip"
        if self._clock() >= self._next_probe_at:
            self._enter(HALF_OPEN)
            return "probe"
        return "skip"

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != CLOSED:
            # A half-open probe success closes the breaker but keeps the
            # backoff and the reopen count on probation: one request
            # surviving a flapping partition must not reset the incident —
            # the next re-open DOUBLES from here toward the ceiling
            # instead of restarting the dance at the base backoff.
            self._probation = PROBATION_SUCCESSES
            self._enter(CLOSED)
        elif self._probation > 0:
            self._probation -= 1
            if self._probation == 0:
                self.reopens = 0
                self._backoff_s = 0.0
        else:
            self.reopens = 0
            self._backoff_s = 0.0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == HALF_OPEN or (
            self.state == CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._open()

    @property
    def seconds_until_probe(self) -> float:
        """How long until the next half-open probe (0 when callable now)."""
        if self.state != OPEN:
            return 0.0
        return max(self._next_probe_at - self._clock(), 0.0)

    # ------------------------------------------------- persistence (persist.py)

    def export_state(self, wallclock: Callable[[], float] = time.time) -> dict:
        """Serializable breaker state for crash-safe persistence. The open
        window is exported as an absolute WALL deadline (``open_until_wall``)
        because the monotonic clock does not survive a restart."""
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "reopens": self.reopens,
            "backoff_s": self._backoff_s,
            "open_until_wall": (
                wallclock() + self.seconds_until_probe
                if self.state == OPEN else 0.0
            ),
            "transitions": dict(self.transitions),
        }

    def restore_state(self, doc: dict, wallclock: Callable[[], float] = time.time) -> None:
        """Rehydrate from :meth:`export_state` output (defensively: the
        payload crossed a process death and a disk). A restored OPEN
        breaker keeps its remaining backoff window — the restarted process
        must not re-learn a still-wedged source from closed — and a
        breaker persisted mid-probe (HALF_OPEN) restores as OPEN with the
        probe due immediately: the in-flight probe died with the process,
        so the honest state is 'quarantined, probe now'."""
        state = doc.get("state")
        if state not in (CLOSED, OPEN, HALF_OPEN):
            return
        self.consecutive_failures = max(int(doc.get("consecutive_failures", 0)), 0)
        self.reopens = max(int(doc.get("reopens", 0)), 0)
        self._backoff_s = min(
            max(float(doc.get("backoff_s", 0.0)), 0.0), self.backoff_max_s
        )
        transitions = doc.get("transitions")
        if isinstance(transitions, dict):
            for key in self.transitions:
                try:
                    self.transitions[key] = max(int(transitions.get(key, 0)), 0)
                except (TypeError, ValueError):
                    pass
        if state == CLOSED:
            self.state = CLOSED
            return
        self.state = OPEN
        remaining = 0.0
        if state == OPEN:
            try:
                remaining = float(doc.get("open_until_wall", 0.0)) - wallclock()
            except (TypeError, ValueError):
                remaining = 0.0
        # Clamp into [0, ceiling]: a wall clock that stepped during the
        # restart must not quarantine a source for hours, nor probe in
        # the past.
        self._next_probe_at = self._clock() + min(
            max(remaining, 0.0), self.backoff_max_s
        )

    def _open(self) -> None:
        if self._backoff_s <= 0:
            self._backoff_s = self.backoff_base_s
        else:
            self._backoff_s = min(self._backoff_s * 2.0, self.backoff_max_s)
        # Symmetric jitter (±jitter fraction): de-synchronizes a fleet of
        # exporters that all lost the same dependency at the same instant.
        factor = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        self._next_probe_at = self._clock() + self._backoff_s * factor
        self.reopens += 1
        self._enter(OPEN)

    def _enter(self, state: str) -> None:
        self.state = state
        self.transitions[state] += 1


class _Call:
    __slots__ = ("fn", "done", "result", "exc")

    def __init__(self, fn: Callable[[], Any]) -> None:
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.exc: BaseException | None = None


class _Worker:
    """One reusable phase-worker thread. ``fenced`` is set when a call it is
    running was abandoned; the loop exits as soon as the blocked call
    returns (never joined while blocking)."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, source: str) -> None:
        with _Worker._seq_lock:
            _Worker._seq += 1
            n = _Worker._seq
        self.fenced = False
        self.inbox: queue.Queue[_Call | None] = queue.Queue()
        self.thread = threading.Thread(
            target=self._run, name=f"tpu-sup-{source}-{n}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        while True:
            call = self.inbox.get()
            if call is None:
                return
            try:
                call.result = call.fn()
            except BaseException as e:  # noqa: BLE001  # lint: disable=bare-except(relayed to the supervised caller via call.exc and re-raised there; swallowing here would hang the deadline wait)
                call.exc = e
            call.done.set()
            if self.fenced:
                # The supervisor gave up on this call; a replacement worker
                # owns the inbox of future calls. Exit quietly.
                return


class SourceSupervisor:
    """Deadline + breaker + reconnect supervision for one source's calls.

    ``fn`` is the phase call (e.g. ``lambda: backend.sample()`` — late-bound
    so tests that monkeypatch ``backend.sample`` keep working);
    ``reconnect`` (optional) is invoked on the worker thread before each
    half-open probe, normally ``source.close`` — the gRPC clients lazily
    rebuild their channel on the next call, so close-then-call IS the
    reconnect.

    Single-caller contract (the poll thread); the abandoned-worker cap is
    the only cross-thread state and is monotonic/advisory.
    """

    def __init__(
        self,
        source: str,
        fn: Callable[[], Any],
        reconnect: Callable[[], None] | None = None,
        deadline_s: float = 4.0,
        breaker: CircuitBreaker | None = None,
        max_abandoned: int = 8,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        self.source = source
        self.deadline_s = deadline_s
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._fn = fn
        self._reconnect = reconnect
        self._clock = clock
        self._worker: _Worker | None = None
        # Workers fenced off mid-call; pruned when their blocked call
        # finally returns and the thread exits. Capped: a permanently-wedged
        # syscall must not accrete a thread per probe forever.
        self._fenced: list[_Worker] = []
        self._max_abandoned = max_abandoned
        self.abandoned = 0
        self.skipped = 0
        self.reconnects = 0
        # Monotonic bookkeeping for recovery log lines; sub-threshold flap
        # recoveries are rate-limited through _rlog (see _note_success).
        self._rlog = RateLimitedLogger(log)
        self._failed_since: float | None = None
        self._failures_this_incident = 0

    # ------------------------------------------------------------------ call

    def call(self) -> Any:
        """Run one supervised phase call; returns its result.

        Raises SourceSkipped (breaker open, backoff pending), SourceTimeout
        (deadline hit; call abandoned), or whatever the call itself raised.
        """
        decision = self.breaker.decide()
        if decision == "skip":
            self.skipped += 1
            # Span annotation (no-op outside a traced poll): the quarantine
            # decision is part of the poll's causal story.
            trace_mod.annotate(
                f"breaker open: call skipped, next probe in "
                f"{self.breaker.seconds_until_probe:.1f}s"
            )
            raise SourceSkipped(
                f"{self.source}: breaker open, next probe in "
                f"{self.breaker.seconds_until_probe:.1f}s"
            )
        fn = self._fn
        if decision == "probe":
            trace_mod.annotate(
                "half-open probe"
                + (": reconnect + single call" if self._reconnect is not None
                   else "")
            )
        if decision == "probe" and self._reconnect is not None:
            # Reconnect ON the worker thread: close() of a wedged channel
            # may itself block, and that must be abandonable too. The
            # counter increments there too, so a probe refused by the
            # abandoned-worker cap is not counted as a reconnect.
            inner, reconnect = self._fn, self._reconnect

            def fn() -> Any:
                self.reconnects += 1
                reconnect()
                return inner()

        try:
            result = self._submit(fn)
        except BaseException:
            self._note_failure()
            self.breaker.record_failure()
            raise
        self._note_success()
        self.breaker.record_success()
        return result

    def _submit(self, fn: Callable[[], Any]) -> Any:
        self._prune_fenced()
        if len(self._fenced) >= self._max_abandoned:
            # Every abandoned worker is still blocked. Spawning another
            # thread into the same wedge buys nothing and leaks a thread;
            # fail the phase immediately instead (counts as a failure, so
            # the breaker keeps backing off).
            trace_mod.annotate(
                f"{len(self._fenced)} abandoned workers still blocked; "
                f"call refused without spawning another"
            )
            raise SourceTimeout(
                f"{self.source}: {len(self._fenced)} abandoned calls still "
                f"blocked; refusing to spawn more workers"
            )
        # Carry the poll thread's trace context onto the worker: the call
        # body (and anything it triggers — chaos injections, provider logs)
        # annotates the PHASE span, not limbo. Restored in a finally so a
        # reused worker never leaks one poll's span into the next.
        span = trace_mod.current_span()
        if span is not None:
            inner = fn

            def fn() -> Any:
                prev = trace_mod.swap_current(span)
                try:
                    return inner()
                finally:
                    trace_mod.swap_current(prev)

        w = self._worker
        if w is None or not w.thread.is_alive():
            w = self._worker = _Worker(self.source)
        call = _Call(fn)
        w.inbox.put(call)
        if not call.done.wait(self.deadline_s):
            # Fence, don't join: the worker exits on its own when (if) the
            # blocked call returns; its late result is discarded.
            w.fenced = True
            # Wake-up pill for the completion race: if the call finished
            # right at the deadline, the worker may have checked ``fenced``
            # (still False) and looped back to inbox.get() before the flag
            # landed — without this it would park there forever, eating an
            # abandoned-worker slot. A worker still blocked in the call
            # never consumes it (it sees ``fenced`` after the call returns
            # and exits first); the stray item dies with the queue.
            w.inbox.put(None)
            self._worker = None
            self._fenced.append(w)
            self.abandoned += 1
            trace_mod.annotate(
                f"deadline {self.deadline_s:g}s exceeded; worker "
                f"{w.thread.name} fenced ({len(self._fenced)} abandoned alive)"
            )
            raise SourceTimeout(
                f"{self.source}: call exceeded {self.deadline_s:g}s phase "
                f"deadline; worker abandoned"
            )
        if call.exc is not None:
            raise call.exc
        return call.result

    def _prune_fenced(self) -> None:
        if self._fenced:
            self._fenced = [w for w in self._fenced if w.thread.is_alive()]

    def _note_failure(self) -> None:
        if self._failed_since is None:
            self._failed_since = self._clock()
        self._failures_this_incident += 1

    def _note_success(self) -> None:
        if self._failed_since is not None:
            duration = self._clock() - self._failed_since
            n = self._failures_this_incident
            # An isolated incident's end always logs (recovery rides its
            # own rate-limit window, not the fault lines'); per-poll
            # flapping collapses to one recovery line per window.
            self._rlog.recovery(
                self.source,
                "source %s healthy again after %d failure(s) over %.1fs "
                "(%d call(s) abandoned, %d reconnect(s))",
                self.source, n, duration, self.abandoned, self.reconnects,
            )
            self._failed_since = None
            self._failures_this_incident = 0

    # ----------------------------------------------------------------- state

    @property
    def degraded(self) -> bool:
        """True once the source has re-opened >= DEGRADED_AFTER_REOPENS
        consecutive times — the /readyz degraded-source predicate."""
        return (
            self.breaker.state != CLOSED
            and self.breaker.reopens >= DEGRADED_AFTER_REOPENS
        )

    def stats(self) -> dict:
        b = self.breaker
        return {
            "state": b.state,
            "state_value": STATE_VALUES[b.state],
            "transitions": dict(b.transitions),
            "consecutive_failures": b.consecutive_failures,
            "reopens": b.reopens,
            "seconds_until_probe": b.seconds_until_probe,
            "abandoned": self.abandoned,
            "skipped": self.skipped,
            "reconnects": self.reconnects,
            "abandoned_alive": len(self._fenced),
            "deadline_s": self.deadline_s,
            "degraded": self.degraded,
        }

    def shutdown(self) -> None:
        """Release the idle worker (fenced/blocked ones exit on their own)."""
        w = self._worker
        self._worker = None
        if w is not None and w.thread.is_alive():
            w.fenced = True
            w.inbox.put(None)
