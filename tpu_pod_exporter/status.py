"""One-shot human-readable status — the ``tpu-info`` analog.

``python -m tpu_pod_exporter.status`` samples the same backends the
exporter daemon uses (same flags/env) and prints a chip table plus per-pod
rollups. Exits non-zero if the device read fails. ``--process-metrics``
adds a holder column (host pid/comm per chip, from the procfs scanner);
``--watch N`` re-renders every N seconds until interrupted, feeding each
sample into a local :class:`~tpu_pod_exporter.history.HistoryStore` so the
table shows per-chip HBM/duty deltas and trend arrows over the trailing
window instead of discarding prior samples.
"""

from __future__ import annotations

import argparse
import sys
import time

from tpu_pod_exporter.app import build_attribution, build_backend
from tpu_pod_exporter.attribution import AttributionError
from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.config import ExporterConfig
from tpu_pod_exporter.topology import detect_host_topology


def fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def render_table(rows: list[list[str]], header: list[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    def line(r):
        return "  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def main(argv=None) -> int:
    # --watch is status-only; everything else is the shared exporter flag set.
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--watch", type=float, default=0.0,
                     help="re-render every N seconds until interrupted")
    pre.add_argument("--json", action="store_true",
                     help="machine-readable output (one JSON object; with "
                          "--watch, one compact JSON line per interval)")
    pre.add_argument("--fleet", default="",
                     help="aggregator host:port — render a fleet-wide "
                          "window_stats answer (per-host table + per-target "
                          "partial/quarantine footer) instead of sampling "
                          "local backends")
    pre.add_argument("--fleet-window", type=float, default=60.0,
                     help="trailing window for the fleet view, seconds")
    pre.add_argument("--tree", default="",
                     help="root aggregator host:port — render the sharded "
                          "aggregation tree topology (leaves, HA pairs, "
                          "per-shard target counts, quarantines, freshness "
                          "winner) from the root's /metrics")
    pre.add_argument("--store-dir", default="",
                     help="with --tree on the root host: read the fleet "
                          "store's store-status.json sidecar from this "
                          "dir and append a store: footer (retention "
                          "span, disk vs budget, rules, last-append age)")
    pre.add_argument("--alert-dir", default="",
                     help="with --tree on the root host: read the "
                          "alerting plane's alert-status.json sidecar "
                          "from this dir and append an alerts: footer "
                          "(firing/pending counts, newest transition "
                          "age, notifier backlog + breaker)")
    ns, rest = pre.parse_known_args(argv)
    if ns.tree:
        try:
            if ns.watch <= 0:
                return _run_tree(ns.tree, as_json=ns.json,
                                 store_dir=ns.store_dir,
                                 alert_dir=ns.alert_dir)
            return _watch_tree(ns.tree, ns.watch, as_json=ns.json,
                               store_dir=ns.store_dir,
                               alert_dir=ns.alert_dir)
        except KeyboardInterrupt:
            return 0
    if ns.fleet:
        try:
            if ns.watch <= 0:
                return _run_fleet(ns.fleet, ns.fleet_window, as_json=ns.json)
            # Ride a stream subscription when the aggregator offers one:
            # the server pushes per-round deltas, so the watch stops
            # paying a full fleet fan-out per frame. None = no stream on
            # this tier (or it went away) — fall back to polling.
            rc = _watch_fleet_stream(
                ns.fleet, ns.fleet_window, ns.watch,
                as_json="line" if ns.json else False)
            if rc is not None:
                return rc
            while True:
                if not ns.json:
                    print("\x1b[H\x1b[2J", end="")
                rc = _run_fleet(ns.fleet, ns.fleet_window,
                                as_json="line" if ns.json else False)
                if rc != 0:
                    return rc
                time.sleep(ns.watch)
        except KeyboardInterrupt:
            return 0
    cfg = ExporterConfig.from_args(rest)
    topo = detect_host_topology(
        accelerator=cfg.accelerator, slice_name=cfg.slice_name,
        host=cfg.node_name, worker_id=cfg.worker_id,
    )
    backend = build_backend(cfg)
    # Same family→resource dispatch as ExporterApp: the doctor must join
    # attribution the way the exporter it diagnoses would (nvidia.com/gpu
    # device UUIDs for GPU-family backends).
    resource_name = (
        cfg.gpu_resource_name
        if getattr(backend, "family", "tpu") == "gpu"
        else cfg.resource_name
    )
    attribution = build_attribution(cfg, resource_name)
    scanner = None
    if cfg.process_metrics:
        from tpu_pod_exporter.procscan import ProcScanner

        scanner = ProcScanner(
            proc_root=cfg.proc_root,
            full_scan_every=cfg.process_full_scan_every,
        )
    try:
        if ns.watch <= 0:
            return _run(cfg, topo, backend, attribution, scanner, as_json=ns.json)
        # Watch mode keeps a local flight recorder so each render can show
        # where a value is HEADING, not just where it is. Bounded exactly
        # like the daemon's store, scaled to one screenful of history.
        from tpu_pod_exporter.history import HistoryStore

        history = HistoryStore(capacity=256, max_series=2048, retention_s=0.0)
        trend_window_s = max(10.0 * ns.watch, 5.0)
        while True:
            if ns.json:
                # JSONL stream: no ANSI escapes, one object per line, so
                # `... --json --watch 5 | jq` works.
                rc = _run(cfg, topo, backend, attribution, scanner,
                          as_json="line")
            else:
                # ANSI home+clear keeps the table in place like `watch`.
                print("\x1b[H\x1b[2J", end="")
                rc = _run(cfg, topo, backend, attribution, scanner,
                          history=history, trend_window_s=trend_window_s)
            if rc != 0:
                return rc
            time.sleep(ns.watch)
    except KeyboardInterrupt:
        return 0
    finally:
        backend.close()
        attribution.close()


# Metric set the fleet view folds per host: the guaranteed presence series
# (chip counts), the HBM sum, and the duty mean — the "what is the slice
# doing" triple.
_FLEET_METRICS = (
    "tpu_chip_info",
    "tpu_hbm_used_bytes",
    "tpu_tensorcore_duty_cycle_percent",
)


def fetch_fleet_window(addr: str, metric: str, window_s: float,
                       timeout_s: float = 5.0) -> dict:
    """One fleet window_stats envelope from the aggregator (always a 200
    envelope — a metric with no samples anywhere is just an empty merge
    inside it; connection-level failures raise)."""
    import json as _json
    import urllib.request

    base = addr if addr.startswith(("http://", "https://")) else f"http://{addr}"
    url = f"{base}/api/v1/window_stats?metric={metric}&window={window_s:g}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied address
        doc = _json.loads(resp.read())
    return doc


def render_fleet(envelopes: dict[str, dict], window_s: float) -> str:
    """Per-host table + per-target status footer from fleet envelopes."""
    hosts: dict[str, dict] = {}
    now = time.time()
    for metric, env in envelopes.items():
        for row in env.get("data", {}).get("result", []):
            host = (row.get("labels") or {}).get("host", "?")
            agg = hosts.setdefault(
                host, {"chips": 0, "hbm": 0.0, "hbm_n": 0,
                       "duty_sum": 0.0, "duty_n": 0, "newest": None})
            s = row.get("stats") or {}
            if metric == "tpu_chip_info":
                agg["chips"] += 1
            elif metric == "tpu_hbm_used_bytes":
                if s.get("last") is not None:
                    agg["hbm"] += s["last"]
                    agg["hbm_n"] += 1
            elif s.get("last") is not None:
                agg["duty_sum"] += s["last"]
                agg["duty_n"] += 1
            ts = row.get("last_sample_wall_ts")
            if isinstance(ts, (int, float)) and (
                    agg["newest"] is None or ts > agg["newest"]):
                agg["newest"] = ts
    rows = []
    for host in sorted(hosts):
        a = hosts[host]
        rows.append([
            host,
            a["chips"] or "-",
            fmt_bytes(a["hbm"]) if a["hbm_n"] else "-",
            f"{a['duty_sum'] / a['duty_n']:.1f}%" if a["duty_n"] else "-",
            f"{now - a['newest']:.1f}s" if a["newest"] is not None else "-",
        ])
    out = []
    if rows:
        out.append(render_table(
            rows, ["host", "chips", "hbm used", "duty avg", "stale"]))
    else:
        out.append("no fleet data in window")
    # Footer folds target status across the envelopes (identical target
    # sets; the worst state per target wins so a mid-render kill shows).
    order = {"ok": 0, "no_data": 1, "quarantined": 2, "timeout": 3, "error": 4}
    targets: dict[str, dict] = {}
    partial = False
    for env in envelopes.values():
        partial = partial or bool(env.get("partial"))
        for t, st in (env.get("targets") or {}).items():
            prev = targets.get(t)
            if prev is None or (
                    order.get(st.get("state"), 9)
                    > order.get(prev.get("state"), 9)):
                targets[t] = st
    n = len(targets)
    ok = sum(1 for st in targets.values()
             if st.get("state") in ("ok", "no_data"))
    bad = [
        f"{t} ({st.get('state')}"
        + (f": {st['error']}" if st.get("error") else "")
        + ")"
        for t, st in sorted(targets.items())
        if st.get("state") not in ("ok", "no_data")
    ]
    footer = f"targets: {ok}/{n} ok · window {window_s:g}s"
    if partial:
        footer += " · PARTIAL result"
    if bad:
        footer += "\n  degraded: " + ", ".join(bad)
    out.append("")
    out.append(footer)
    return "\n".join(out)


def fetch_tree(addr: str, timeout_s: float = 5.0) -> dict:
    """Scrape the root aggregator's /metrics and fold the shard-topology
    view out of it: per-shard target counts/quarantines, per-leaf up +
    staleness (the freshest leaf of each HA pair is the dedup winner),
    fleet rollup headlines, and the dedup/reshard counters. One HTTP GET —
    the tree view is exactly what the root already publishes."""
    import urllib.request

    from tpu_pod_exporter.metrics import schema
    from tpu_pod_exporter.metrics.parse import parse_families

    base = addr if addr.startswith(("http://", "https://")) else f"http://{addr}"
    with urllib.request.urlopen(f"{base}/metrics", timeout=timeout_s) as resp:  # noqa: S310 — operator-supplied address
        text = resp.read().decode("utf-8", errors="replace")
    fams = parse_families(text)

    def first_value(name: str, default=None):
        rows = fams.get(name)
        return rows[0].value if rows else default

    shards: dict[str, dict] = {}
    for s in fams.get(schema.TPU_ROOT_LEAF_UP.name, ()):
        shard = s.labels.get("shard", "?")
        leaf = s.labels.get("leaf", "?")
        entry = shards.setdefault(
            shard, {"targets": None, "quarantined": None, "leaves": {},
                    "families": {}})
        entry["leaves"][leaf] = {"up": s.value, "staleness_s": None}
    for s in fams.get(schema.TPU_ROOT_LEAF_STALENESS_SECONDS.name, ()):
        shard = s.labels.get("shard", "?")
        leaf = s.labels.get("leaf", "?")
        entry = shards.get(shard)
        if entry and leaf in entry["leaves"]:
            entry["leaves"][leaf]["staleness_s"] = s.value
    for s in fams.get(schema.TPU_ROOT_SHARD_TARGETS.name, ()):
        entry = shards.get(s.labels.get("shard", "?"))
        if entry is not None:
            entry["targets"] = s.value
    for s in fams.get(schema.TPU_ROOT_SHARD_QUARANTINED_TARGETS.name, ()):
        entry = shards.get(s.labels.get("shard", "?"))
        if entry is not None:
            entry["quarantined"] = s.value
    for s in fams.get(schema.TPU_ROOT_SHARD_FAMILY_CHIPS.name, ()):
        entry = shards.get(s.labels.get("shard", "?"))
        if entry is not None:
            entry.setdefault("families", {})[
                s.labels.get("family", "?")] = s.value
    for entry in shards.values():
        fresh = None
        for leaf, doc in entry["leaves"].items():
            st = doc["staleness_s"]
            if doc["up"] and st is not None and (
                    fresh is None or st < entry["leaves"][fresh]["staleness_s"]):
                fresh = leaf
        entry["freshest"] = fresh
    up_targets = sum(
        1 for s in fams.get(schema.TPU_AGG_TARGET_UP.name, ())
        if s.value == 1.0
    )
    # Per-family chip/memory split for the fleet footer — read from the
    # published tpu_fleet_family_* rollups, never re-derived by summing
    # (the whole point of publishing the split).
    family_chips = {
        s.labels.get("family", "?"): s.value
        for s in fams.get(schema.TPU_FLEET_FAMILY_CHIP_COUNT.name, ())
    }
    family_hbm = {
        s.labels.get("family", "?"): s.value
        for s in fams.get(schema.TPU_FLEET_FAMILY_HBM_USED_BYTES.name, ())
    }
    return {
        "root": addr,
        "shards": shards,
        "fleet": {
            "targets": len(fams.get(schema.TPU_AGG_TARGET_UP.name, ())),
            "targets_up": up_targets,
            "chips": sum(
                s.value for s in fams.get(schema.TPU_SLICE_CHIP_COUNT.name,
                                          ())),
            "family_chips": family_chips,
            "family_hbm_used_bytes": family_hbm,
            "dedup_stale_wins_total": first_value(
                schema.TPU_ROOT_DEDUP_STALE_WINS_TOTAL.name),
            "reshard_moves_total": first_value(
                schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name),
            "last_round_ts": first_value(
                schema.TPU_ROOT_LAST_ROUND_TIMESTAMP_SECONDS.name),
            "round_duration_s": first_value(
                schema.TPU_ROOT_ROUND_DURATION_SECONDS.name),
        },
    }


def render_tree(doc: dict) -> str:
    """Shard-topology table + fleet footer, mirroring the --fleet view."""
    rows = []
    for shard in sorted(doc["shards"]):
        entry = doc["shards"][shard]
        leaf_cells = []
        for leaf in sorted(entry["leaves"]):
            ldoc = entry["leaves"][leaf]
            mark = "*" if leaf == entry.get("freshest") else ""
            if ldoc["up"]:
                st = ldoc["staleness_s"]
                age = f" {st:.1f}s" if st is not None else ""
                leaf_cells.append(f"{leaf}{mark} up{age}")
            else:
                leaf_cells.append(f"{leaf} DOWN")
        t = entry.get("targets")
        q = entry.get("quarantined")
        fams_cell = "-"
        families = entry.get("families") or {}
        if families:
            # e.g. "tpu:48+gpu:16" — which device families this shard's
            # consistent-hash cut happens to carry, and how many chips.
            fams_cell = "+".join(
                f"{fam}:{chips:g}"
                for fam, chips in sorted(families.items())
            )
        rows.append([
            shard,
            int(t) if t is not None else "-",
            int(q) if q is not None else "-",
            fams_cell,
            ", ".join(leaf_cells) or "-",
        ])
    out = []
    if rows:
        out.append(render_table(
            rows,
            ["shard", "targets", "quar", "family", "leaves (* = freshest)"]))
    else:
        out.append("no shard topology published (is this a root aggregator?)")
    f = doc["fleet"]
    footer = (f"fleet: {f['targets_up']}/{f['targets']} targets up · "
              f"{f['chips']:g} chips")
    family_chips = f.get("family_chips") or {}
    if family_chips:
        # Per-family split of the chip/memory totals (mixed fleets): e.g.
        # "tpu 96 chips 1.2TiB · gpu 16 chips 320GiB".
        family_hbm = f.get("family_hbm_used_bytes") or {}
        cells = []
        for fam in sorted(family_chips):
            cell = f"{fam} {family_chips[fam]:g} chips"
            if fam in family_hbm:
                cell += f" {fmt_bytes(family_hbm[fam])}"
            cells.append(cell)
        footer += " (" + " · ".join(cells) + ")"
    if f.get("dedup_stale_wins_total") is not None:
        footer += f" · stale wins {f['dedup_stale_wins_total']:g}"
    if f.get("reshard_moves_total") is not None:
        footer += f" · reshard moves {f['reshard_moves_total']:g}"
    if f.get("last_round_ts"):
        footer += f" · round {time.time() - f['last_round_ts']:.1f}s ago"
    down = [
        f"{leaf} ({shard})"
        for shard, entry in sorted(doc["shards"].items())
        for leaf, ldoc in sorted(entry["leaves"].items())
        if not ldoc["up"]
    ]
    if down:
        footer += "\n  leaves down: " + ", ".join(down)
    out.append("")
    out.append(footer)
    store = doc.get("store")
    if store is not None:
        out.append(store_line(store))
    elif doc.get("store_error"):
        # A typo'd --store-dir must look different from "no store
        # configured" — the forensics playbook starts here.
        out.append(f"store: {doc['store_error']}")
    alerts = doc.get("alerts")
    if alerts is not None:
        out.append(alert_line(alerts))
    elif doc.get("alerts_error"):
        # Same discipline as store_error: a typo'd --alert-dir must look
        # different from "no alerting configured".
        out.append(f"alerts: {doc['alerts_error']}")
    return "\n".join(out)


def store_line(doc: dict) -> str:
    """``store:`` footer from the fleet store's on-disk sidecar
    (tpu_pod_exporter.store.store_status_summary): retention span, disk
    bytes vs budget, rules evaluated, last-append age — the four numbers
    the RUNBOOK's forensics playbook reads first."""
    span = doc.get("span_s") or 0.0
    span_txt = (f"{span / 86400.0:.1f}d" if span >= 86400.0
                else f"{span / 3600.0:.1f}h" if span >= 3600.0
                else f"{span:.0f}s")
    parts = [f"store: span {span_txt}"]
    disk = doc.get("disk_bytes")
    budget = doc.get("disk_budget_bytes") or 0
    if disk is not None:
        d = fmt_bytes(float(disk))
        if budget:
            over = " OVER" if disk > budget else ""
            parts.append(f"disk {d}/{fmt_bytes(float(budget))}{over}")
        else:
            parts.append(f"disk {d} (no budget)")
    if doc.get("thinned"):
        parts.append("THINNED (finest tier shed)")
    rules = doc.get("rules") or 0
    parts.append(f"rules {rules} "
                 f"(evaluated {doc.get('rules_evaluated_total', 0):g})")
    last = doc.get("last_append_wall")
    if last:
        parts.append(f"last append {max(time.time() - last, 0.0):.1f}s ago")
    failures = doc.get("append_failures") or 0
    if failures:
        parts.append(f"APPEND FAILURES {failures:g}")
    series = doc.get("series")
    if series is not None:
        parts.append(f"{series:g} series")
    return " · ".join(parts)


def alert_line(doc: dict) -> str:
    """``alerts:`` footer from the alerting plane's on-disk sidecar
    (tpu_pod_exporter.alerting.alert_status_summary): firing/pending
    counts, newest transition age, suppression/evaluation health and the
    notifier's backlog + breaker — what the alerting triage playbook
    reads first."""
    firing = doc.get("firing") or 0
    pending = doc.get("pending") or 0
    parts = [f"alerts: {firing:g} firing · {pending:g} pending "
             f"· rules {doc.get('rules', 0):g}"]
    last = doc.get("last_transition_wall")
    if last:
        parts.append(
            f"last transition {max(time.time() - last, 0.0):.1f}s ago")
    if not doc.get("suppression", True):
        parts.append("SUPPRESSION OFF")
    suppressed = doc.get("suppressed_total") or 0
    if suppressed:
        parts.append(f"suppressed {suppressed:g}")
    failures = doc.get("eval_failures") or 0
    if failures:
        parts.append(f"EVAL FAILURES {failures:g}")
    notif = doc.get("notifier")
    if notif:
        backlog = notif.get("backlog_records") or 0
        cell = f"notify sent {notif.get('sent', 0):g}"
        if backlog:
            cell += (f" backlog {backlog:g} "
                     f"({notif.get('backlog_age_s', 0.0):.0f}s old)")
        breaker = notif.get("breaker")
        if breaker and breaker != "closed":
            cell += f" breaker {str(breaker).upper()}"
        parts.append(cell)
    return " · ".join(parts)


def render_tree_screen(addr: str, doc: dict | None, error=None,
                       unreachable_s: float = 0.0) -> str:
    """One watch-mode frame: the freshest tree we have, plus an explicit
    ``unreachable`` footer when the root is not answering right now.
    A briefly-unreachable root (it restarts, a partition blips) must not
    throw the operator out of watch mode mid-incident — the last-known
    state labeled stale beats a dead terminal."""
    out = [f"shard tree via {addr}", ""]
    if doc is not None:
        out.append(render_tree(doc))
    if error is not None:
        if doc is not None:
            out.append("")
            out.append(
                f"root unreachable ({unreachable_s:.0f}s): {error} — "
                f"showing last-known state"
            )
        else:
            out.append(f"root unreachable: {error} (no tree fetched yet)")
    return "\n".join(out)


def _attach_store(doc: dict, store_dir: str) -> dict:
    """Attach the fleet store's sidecar summary under ``doc["store"]``
    (rendered by render_tree and carried in the JSON stream). Absent or
    unreadable sidecars attach nothing — the tree view stays usable on
    roots without a store."""
    if store_dir:
        from tpu_pod_exporter.store import store_status_summary

        summary = store_status_summary(store_dir)
        if summary is not None:
            doc["store"] = summary
        else:
            doc["store_error"] = (
                f"no store-status.json under {store_dir}")
    return doc


def _attach_alerts(doc: dict, alert_dir: str) -> dict:
    """Attach the alerting sidecar summary under ``doc["alerts"]`` (the
    store-footer discipline: absent dir attaches nothing, a configured
    but unreadable sidecar attaches an explicit error)."""
    if alert_dir:
        from tpu_pod_exporter.alerting import alert_status_summary

        summary = alert_status_summary(alert_dir)
        if summary is not None:
            doc["alerts"] = summary
        else:
            doc["alerts_error"] = (
                f"no alert-status.json under {alert_dir}")
    return doc


def _watch_tree(addr: str, interval_s: float, as_json=False,
                store_dir: str = "", alert_dir: str = "") -> int:
    """``--tree --watch``: re-render until interrupted, surviving root
    outages with a last-known-state footer instead of exiting. The store
    sidecar is re-read every interval — a thinning or append-failing
    store shows up mid-watch."""
    import json as _json

    # Stream-ticked refresh when the root offers /api/v1/stream: renders
    # then track ROUNDS (a delta frame = the root published) instead of a
    # blind interval — no wasted refreshes between rounds, sub-interval
    # reaction when rounds are fast. A missing/never/dead stream falls
    # back to the plain interval sleep below.
    ticker = None
    hp = _split_addr(addr)
    if hp is not None:
        try:
            from tpu_pod_exporter.stream import QueryShape, StreamClient

            ticker = StreamClient(
                hp[0], hp[1],
                QueryShape(route="window_stats",
                           metric="tpu_hbm_used_bytes",
                           window_s=max(interval_s * 4, 30.0)),
                timeout_s=5.0)
        except Exception:  # noqa: BLE001 — no stream = plain polling
            ticker = None
    last_doc: dict | None = None
    last_ok = time.monotonic()
    while True:
        error = None
        try:
            doc = _attach_alerts(
                _attach_store(fetch_tree(addr), store_dir), alert_dir)
            last_doc = doc
            last_ok = time.monotonic()
        except Exception as e:  # noqa: BLE001 — watch mode outlives outages
            error = e
        if as_json:
            # JSONL stream: one object per interval; outages are explicit
            # records (with the last-known doc attached), never an exit.
            if error is None:
                print(_json.dumps(doc, indent=None), flush=True)
            else:
                print(_json.dumps({
                    "root": addr,
                    "unreachable": True,
                    "unreachable_s": round(time.monotonic() - last_ok, 1),
                    "error": str(error),
                    "last_known": last_doc,
                }, indent=None), flush=True)
        else:
            print("\x1b[H\x1b[2J", end="")
            print(render_tree_screen(
                addr,
                last_doc,
                error=error,
                unreachable_s=time.monotonic() - last_ok,
            ))
        if ticker is not None and not ticker.eof:
            # Block until the next round's frame (or heartbeat/timeout —
            # either way, re-render no later than a slow poll would).
            for _frame in ticker.frames(max_frames=1,
                                        timeout_s=max(interval_s * 3, 5.0)):
                break
        else:
            if ticker is not None:
                ticker.close()
                ticker = None
            time.sleep(interval_s)


def _run_tree(addr: str, as_json=False, store_dir: str = "",
              alert_dir: str = "") -> int:
    import json as _json

    try:
        doc = _attach_alerts(
            _attach_store(fetch_tree(addr), store_dir), alert_dir)
    except Exception as e:  # noqa: BLE001 — a down root is the answer
        print(f"tree query against {addr} failed: {e}", file=sys.stderr)
        return 1
    if as_json:
        print(_json.dumps(doc, indent=None if as_json == "line" else 1),
              flush=True)
        return 0
    print(f"shard tree via {addr}")
    print()
    print(render_tree(doc))
    return 0


def _split_addr(addr: str) -> tuple[str, int] | None:
    a = addr
    if a.startswith(("http://", "https://")):
        a = a.split("//", 1)[1]
    a = a.split("/", 1)[0]
    host, _, port_s = a.partition(":")
    try:
        return host or "127.0.0.1", int(port_s or "80")
    except ValueError:
        return None


def _watch_fleet_stream(addr: str, window_s: float, interval_s: float,
                        as_json=False) -> int | None:
    """``--fleet --watch`` over /api/v1/stream: one subscription per
    fleet metric, each frame a per-round delta applied to a local replay
    — the aggregator evaluates each shape once per round however many
    watchers ride it, and this tool stops paying a fan-out per frame.
    Returns None when the server offers no stream endpoint (or the
    stream dies mid-watch): the caller falls back to polling."""
    import json as _json

    from tpu_pod_exporter.stream import (
        DATA_FRAME_TYPES,
        QueryShape,
        StreamClient,
        StreamDisabled,
        StreamReplay,
    )

    hp = _split_addr(addr)
    if hp is None:
        return None
    host, port = hp
    subs: list[tuple[str, StreamClient, StreamReplay]] = []
    try:
        for metric in _FLEET_METRICS:
            shape = QueryShape(route="window_stats", metric=metric,
                               window_s=window_s)
            subs.append((metric, StreamClient(host, port, shape,
                                              timeout_s=5.0),
                         StreamReplay()))
    except StreamDisabled:
        for _m, c, _r in subs:
            c.close()
        return None
    except OSError as e:
        for _m, c, _r in subs:
            c.close()
        print(f"fleet stream against {addr} failed: {e}", file=sys.stderr)
        return 1
    try:
        while True:
            for _metric, client, replay in subs:
                for frame in client.frames(timeout_s=0.2):
                    if frame.get("type") in DATA_FRAME_TYPES \
                            or frame.get("type") == "shed":
                        replay.apply(frame)
            if any(client.eof for _m, client, _r in subs):
                # Shed / server restart: the polling fallback takes over
                # (and will retry the subscription on the next watch).
                return None
            envelopes = {}
            for metric, _client, replay in subs:
                envelopes[metric] = {
                    "data": {"result": [replay.rows[k]
                                        for k in sorted(replay.rows)]},
                    "partial": bool(replay.meta.get("partial")),
                    "fleet": replay.meta.get("fleet") or {},
                    # Per-target states ride snapshot/full_sync meta (at
                    # most --stream-full-sync-s stale) — the degraded-
                    # target footer must not vanish in stream mode.
                    "targets": replay.meta.get("targets") or {},
                    "source": "stream",
                }
            if as_json:
                print(_json.dumps(
                    {"aggregator": addr, "window_s": window_s,
                     "transport": "stream", "envelopes": envelopes},
                    indent=None), flush=True)
            else:
                print("\x1b[H\x1b[2J", end="")
                print(f"fleet view via {addr} (stream)")
                print()
                print(render_fleet(envelopes, window_s))
            time.sleep(interval_s)
    finally:
        for _m, c, _r in subs:
            c.close()


def _run_fleet(addr: str, window_s: float, as_json=False) -> int:
    import json as _json

    envelopes: dict[str, dict] = {}
    try:
        for metric in _FLEET_METRICS:
            envelopes[metric] = fetch_fleet_window(addr, metric, window_s)
    except Exception as e:  # noqa: BLE001 — a down aggregator is the answer
        print(f"fleet query against {addr} failed: {e}", file=sys.stderr)
        return 1
    if as_json:
        print(_json.dumps(
            {"aggregator": addr, "window_s": window_s,
             "envelopes": envelopes},
            indent=None if as_json == "line" else 1,
        ), flush=True)
        return 0
    print(f"fleet view via {addr}")
    print()
    print(render_fleet(envelopes, window_s))
    return 0


def trend_cell(history, metric: str, chip_id, window_s: float,
               fmt, eps: float) -> str:
    """Delta + direction arrow for one chip's series over the trailing
    window, from the watch-mode history store. "-" until two samples exist."""
    rows = history.window_stats(
        metric, {"chip_id": str(chip_id)}, window_s=window_s
    )
    if not rows or rows[0]["stats"]["samples"] < 2:
        return "-"
    s = rows[0]["stats"]
    delta = s["last"] - s["first"]
    arrow = "↑" if delta > eps else ("↓" if delta < -eps else "→")
    return f"{arrow}{fmt(delta)}"


def _fmt_delta_bytes(d: float) -> str:
    return ("+" if d >= 0 else "-") + fmt_bytes(abs(d))


def persist_line(state_dir: str) -> str | None:
    """``state-dir: …`` footer: on-disk size, checkpoint age, and whether a
    restart right now would warm-start (checkpoint present) or cold-start.
    The checkpoint age IS the worst-case staleness a crash-restore would
    serve — the operator-facing read of tpu_exporter_snapshot_stale_seconds
    before it happens."""
    from tpu_pod_exporter.persist import state_dir_summary

    s = state_dir_summary(state_dir)
    if not s["exists"]:
        return f"state-dir: {state_dir} (missing — restart would cold-start)"
    if s["snapshot_bytes"]:
        age = s["snapshot_age_s"]
        warm = (f"warm restart ready, checkpoint {age:g}s stale"
                if age is not None else "warm restart ready")
    else:
        warm = "no checkpoint yet — restart would cold-start"
    return (f"state-dir: {state_dir} {fmt_bytes(s['total_bytes'])} "
            f"(checkpoint {fmt_bytes(s['snapshot_bytes'])}, "
            f"wal {fmt_bytes(s['wal_bytes'])}) · {warm}")


def pressure_line(state_dir: str) -> str | None:
    """``pressure: …`` footer: per-resource ladder rung, bytes vs budget,
    and last shed/recover instants — the operator-facing read of the
    ``tpu_exporter_pressure_*`` surface, from the governor's on-disk
    sidecar (mirrors the ``state-dir:``/``egress:`` footers). None when no
    governor has run against this state dir."""
    from tpu_pod_exporter.pressure import pressure_status_summary

    doc = pressure_status_summary(state_dir)
    if doc is None:
        return None
    parts = ["pressure:"]
    now = time.time()
    for resource in ("disk", "memory"):
        ladder = doc.get(resource)
        if not isinstance(ladder, dict):
            continue
        level = ladder.get("level", 0)
        rung = ladder.get("rung") or "none"
        usage = ladder.get("usage_bytes", 0)
        budget = ladder.get("budget_bytes", 0)
        cell = (f"{resource} rung {level}"
                + (f" ({rung})" if level else "")
                + f" · {fmt_bytes(usage)}"
                + (f"/{fmt_bytes(budget)}" if budget else " (no budget)"))
        shed = ladder.get("last_shed_wall") or 0
        rec = ladder.get("last_recover_wall") or 0
        if shed:
            cell += f" · shed {max(now - shed, 0.0):.0f}s ago"
        if rec:
            cell += f" · recovered {max(now - rec, 0.0):.0f}s ago"
        parts.append(cell)
    if len(parts) == 1:
        return None
    return " ".join(parts[:1]) + " " + " | ".join(parts[1:])


def egress_line(egress_url: str, egress_dir: str) -> str | None:
    """``egress: …`` footer: receiver/breaker state, backlog bytes/age,
    last-send latency — the operator-facing read of the
    ``tpu_exporter_egress_*`` surface, mirroring the ``state-dir:`` footer.
    Reads the shipper's on-disk status sidecar plus segment sizes; a
    missing dir means egress has never run here."""
    from tpu_pod_exporter.egress import egress_dir_summary

    s = egress_dir_summary(egress_dir)
    if not s["exists"]:
        return (f"egress: {egress_url} (dir {egress_dir} missing — "
                f"no batches shipped yet)")
    st = s["status"] or {}
    backlog = st.get("backlog_batches")
    parts = [f"egress: {egress_url}"]
    parts.append(f"breaker {st.get('breaker', '?')}")
    if backlog is not None:
        parts.append(
            f"backlog {backlog} batch(es) / {fmt_bytes(st.get('backlog_bytes', 0))}"
        )
    else:
        parts.append(f"buffer {fmt_bytes(s['segment_bytes'])} on disk")
    ok_wall = st.get("last_send_ok_wall") or 0
    if ok_wall:
        parts.append(
            f"last send ok {max(time.time() - ok_wall, 0.0):.1f}s ago "
            f"({1e3 * st.get('last_send_latency_s', 0.0):.1f}ms)"
        )
    else:
        parts.append("no send acknowledged yet")
    err = st.get("last_error")
    if err:
        parts.append(f"last error: {err}")
    return " · ".join(parts)


# Series name the watch-mode phase breakdown stores its timings under — the
# same family the exporter's per-phase histogram publishes, so the footer
# reads as a local preview of the daemon's phase heatmap.
PHASE_METRIC = "tpu_exporter_poll_phase_duration_seconds"


def phase_breakdown_line(history, phases, window_s: float) -> str | None:
    """``phases: device_read 1.2ms (p≈mean 1.1ms) · …`` footer for watch
    mode, computed from the locally-recorded phase series over the trailing
    window. None until at least one phase has a sample."""
    parts = []
    for phase in phases:
        rows = history.window_stats(PHASE_METRIC, {"phase": phase},
                                    window_s=window_s)
        if not rows:
            continue
        s = rows[0]["stats"]
        parts.append(
            f"{phase} {1e3 * s['last']:.1f}ms"
            f" (mean {1e3 * s['mean']:.1f}ms, max {1e3 * s['max']:.1f}ms)"
        )
    return "phases: " + " · ".join(parts) if parts else None


def _run(cfg, topo, backend, attribution, scanner=None, as_json=False,
         history=None, trend_window_s=0.0) -> int:
    t_phase0 = time.perf_counter()
    try:
        sample = backend.sample()
    except BackendError as e:
        print(f"device read failed: {e}", file=sys.stderr)
        return 1
    t_phase1 = time.perf_counter()
    # Per-chip read problems must be visible even when they leave 0 chips —
    # "no chips found" and "all chip reads failed" are different diagnoses.
    for err in sample.partial_errors:
        print(f"warning: {err}", file=sys.stderr)
    try:
        owner_map = attribution.snapshot().by_device_id(cfg.resource_name)
    except AttributionError as e:
        print(f"(attribution unavailable: {e})", file=sys.stderr)
        owner_map = {}
    t_phase2 = time.perf_counter()
    if history is not None:
        # Watch mode keeps a local per-phase latency record (the same
        # series name as the exporter's phase histogram) so the footer
        # shows where each refresh's time goes — a hung attribution source
        # is visible as a growing phase cell before it is visible anywhere
        # else on a box with no daemon running.
        history.append(PHASE_METRIC, {"phase": "device_read"},
                       t_phase1 - t_phase0)
        history.append(PHASE_METRIC, {"phase": "attribution"},
                       t_phase2 - t_phase1)

    if not as_json and topo.accelerator:
        st = topo.slice_topology
        extra = (
            f"  ({st.total_chips} chips / {st.num_hosts} hosts slice-wide)"
            if st.total_chips else ""
        )
        print(f"accelerator: {topo.accelerator}{extra}")
        if topo.worker_id or topo.slice_name:
            print(f"slice: {topo.slice_name or '-'}  worker: {topo.worker_id or '-'}  host: {topo.host}")
        print()

    if not sample.chips and not as_json:
        print("no TPU chips found on this host")
        return 0

    holders_by_path: dict[str, list] = {}
    if scanner is not None:
        t_scan0 = time.perf_counter()
        try:
            for h in scanner.scan():
                holders_by_path.setdefault(h.device_path, []).append(h)
        except Exception as e:  # noqa: BLE001 — status stays useful without it
            print(f"(process scan unavailable: {e})", file=sys.stderr)
        if history is not None:
            history.append(PHASE_METRIC, {"phase": "process_scan"},
                           time.perf_counter() - t_scan0)

    rows = []
    doc_chips = []
    pods: dict[tuple[str, str], list[float]] = {}
    for chip in sample.chips:
        owner = None
        for did in chip.info.device_ids:
            owner = owner_map.get(did)
            if owner:
                break
        if owner:
            agg = pods.setdefault((owner.namespace, owner.pod), [0, 0.0])
            agg[0] += 1
            agg[1] += chip.hbm_used_bytes or 0.0
        if as_json:
            chip_holders = holders_by_path.get(chip.info.device_path, [])
            doc_chips.append({
                "chip_id": chip.info.chip_id,
                "device_path": chip.info.device_path,
                "device_kind": chip.info.device_kind,
                "coords": chip.info.coords,
                "hbm_used_bytes": chip.hbm_used_bytes,
                "hbm_total_bytes": chip.hbm_total_bytes,
                "hbm_peak_bytes": chip.hbm_peak_bytes,
                "duty_cycle_percent": chip.tensorcore_duty_cycle_percent,
                # Per-link cumulative ICI counters (link="all" on backends
                # serving only a per-chip aggregate — see backend/libtpu.py).
                "ici": {
                    l.link: l.transferred_bytes_total for l in chip.ici_links
                },
                "dcn": {
                    l.link: l.transferred_bytes_total for l in chip.dcn_links
                },
                "pod": owner.pod if owner else None,
                "namespace": owner.namespace if owner else None,
                "container": owner.container if owner else None,
                "holders": [
                    {"pid": h.pid, "comm": h.comm, "pod_uid": h.pod_uid}
                    for h in chip_holders
                ],
            })
            continue
        duty = (
            f"{chip.tensorcore_duty_cycle_percent:.1f}%"
            if chip.tensorcore_duty_cycle_percent is not None
            else "-"
        )
        pct = (
            f"{100 * chip.hbm_used_bytes / chip.hbm_total_bytes:.1f}%"
            if chip.hbm_total_bytes and chip.hbm_used_bytes is not None
            else "-"
        )
        hbm_cell = (
            f"{fmt_bytes(chip.hbm_used_bytes)}/{fmt_bytes(chip.hbm_total_bytes)}"
            if chip.hbm_used_bytes is not None and chip.hbm_total_bytes is not None
            else "-"  # backend couldn't read HBM (e.g. tunnel, HARDWARE.md)
        )
        row = [
            chip.info.chip_id,
            chip.info.device_path or "-",
            hbm_cell,
            pct,
            duty,
        ]
        if history is not None:
            cid = chip.info.chip_id
            if chip.hbm_used_bytes is not None:
                history.append("tpu_hbm_used_bytes", {"chip_id": str(cid)},
                               chip.hbm_used_bytes)
            if chip.tensorcore_duty_cycle_percent is not None:
                history.append("tpu_tensorcore_duty_cycle_percent",
                               {"chip_id": str(cid)},
                               chip.tensorcore_duty_cycle_percent)
            # Direction over the trailing window: ±0.5% of capacity (or
            # 1 MiB) counts as movement for HBM, ±1 duty point for the core.
            hbm_eps = max((chip.hbm_total_bytes or 0) * 0.005, 1024.0**2)
            row.append(trend_cell(history, "tpu_hbm_used_bytes", cid,
                                  trend_window_s, _fmt_delta_bytes, hbm_eps))
            row.append(trend_cell(history, "tpu_tensorcore_duty_cycle_percent",
                                  cid, trend_window_s,
                                  lambda d: f"{d:+.1f}%", 1.0))
        row.append(f"{owner.namespace}/{owner.pod}" if owner else "-")
        if scanner is not None:
            chip_holders = holders_by_path.get(chip.info.device_path, [])
            row.append(
                ",".join(f"{h.pid}/{h.comm}" for h in chip_holders) or "-"
            )
        rows.append(row)
    if as_json:
        import json

        persist = None
        pressure = None
        if cfg.state_dir:
            from tpu_pod_exporter.persist import state_dir_summary
            from tpu_pod_exporter.pressure import pressure_status_summary

            persist = state_dir_summary(cfg.state_dir)
            pressure = pressure_status_summary(cfg.state_dir)
        egress = None
        if cfg.egress_url:
            from tpu_pod_exporter.egress import egress_dir_summary

            egress = egress_dir_summary(cfg.egress_dir)
        print(json.dumps({
            "accelerator": topo.accelerator,
            "persist": persist,
            "pressure": pressure,
            "egress": egress,
            "slice_name": topo.slice_name,
            "host": topo.host,
            "worker_id": topo.worker_id,
            "multislice_group": topo.multislice_group,
            "num_slices": topo.num_slices,
            "chips": doc_chips,
            # Machine-readable too, not just the stderr warnings: an
            # hbm_used_bytes of null is only diagnosable with these.
            "partial_errors": list(sample.partial_errors),
            "pods": [
                {"namespace": ns_, "pod": pod, "chips": int(n),
                 "hbm_used_bytes": hbm}
                for (ns_, pod), (n, hbm) in sorted(pods.items())
            ],
        }, indent=None if as_json == "line" else 1), flush=True)
        return 0

    header = ["chip", "device", "hbm", "hbm%", "duty"]
    if history is not None:
        header += ["Δhbm", "Δduty"]
    header.append("pod")
    if scanner is not None:
        header.append("holder")
    print(render_table(rows, header))

    if pods:
        print()
        pod_rows = [
            [f"{ns}/{pod}", int(n), fmt_bytes(hbm)]
            for (ns, pod), (n, hbm) in sorted(pods.items())
        ]
        print(render_table(pod_rows, ["pod", "chips", "hbm used"]))

    if history is not None:
        phases = ["device_read", "attribution"]
        if scanner is not None:
            phases.append("process_scan")
        line = phase_breakdown_line(history, phases, trend_window_s)
        if line:
            print()
            print(line)
    if cfg.state_dir:
        line = persist_line(cfg.state_dir)
        if line:
            print()
            print(line)
        line = pressure_line(cfg.state_dir)
        if line:
            print()
            print(line)
    if cfg.egress_url:
        line = egress_line(cfg.egress_url, cfg.egress_dir)
        if line:
            print()
            print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
