"""Process ↔ device attribution via procfs — the per-process dimension.

The reference's headline capability is *per-process* device accounting: NVML
``GetComputeRunningProcesses`` host PIDs joined against ``kubectl exec … ps``
output (``main.go:101-109,135-154``). That join is broken by construction —
container-namespace PIDs compared against host PIDs, and an index-vs-value
bug besides (SURVEY.md §2.6 items 1-2). On a TPU node the same question —
**which process holds which chip?** — has a correct, purely local answer:
the process that opened ``/dev/accel*`` (or its vfio group) shows the device
in its own ``/proc/<pid>/fd``, host-side, with no exec, no apiserver, and no
PID-namespace translation. The process's cgroup path names the pod UID and
container runtime ID, which cross-checks the kubelet podresources
allocation (the primary attribution source).

Cost model: a full walk of ``/proc`` is O(processes × fds) readlinks, too
much to pay every second on a busy node. The scanner therefore verifies the
cached holder set each call (O(holders) — a handful of processes) and does
a full rescan only every ``full_scan_every`` calls or as soon as a cached
holder changes, so a freed chip disappears within one poll while a *new*
holder appears within ``full_scan_every`` polls.
"""

from __future__ import annotations

import logging
import os
import re
from dataclasses import dataclass

log = logging.getLogger("tpu_pod_exporter.procscan")

# Kubernetes pod UID inside a cgroup path. cgroupfs (v1) spells it with
# dashes (".../kubepods/burstable/pod<uid>/<cid>"); the systemd driver (v2)
# with underscores ("kubepods-burstable-pod<uid>.slice").
_POD_UID_RE = re.compile(
    r"pod([0-9a-f]{8}[-_][0-9a-f]{4}[-_][0-9a-f]{4}[-_][0-9a-f]{4}[-_][0-9a-f]{12})"
)
# Container runtime ID: the path component after the pod scope — hex id,
# optionally wrapped runtime-prefix…"-"…id…".scope" by the systemd driver.
_CONTAINER_ID_RE = re.compile(
    r"^(?:cri-containerd-|docker-|crio-|containerd-)?([0-9a-f]{12,64})(?:\.scope)?$"
)

DEFAULT_DEVICE_PREFIXES = ("/dev/accel", "/dev/vfio/")

# The shared vfio *container* node — every vfio-using process holds it open
# (including non-TPU passthrough users), so treating it as a device would
# inflate the holder/verify set on mixed nodes. Only /dev/vfio/<group>
# numeric entries identify an actual passthrough device.
EXCLUDED_DEVICE_PATHS = frozenset({"/dev/vfio/vfio"})


class ProcScanError(RuntimeError):
    """The proc root itself was unreadable — the *whole scan* failed (vs. a
    single process racing away, which is normal and silently skipped). Raised
    so the collector's error budget + bounded-staleness holder fallback
    engage instead of publishing a falsely-empty holder set."""


@dataclass(frozen=True)
class DeviceHolder:
    """One (process, device-file) pair: ``pid`` holds ``device_path`` open.

    ``pod_uid``/``container_id`` come from the process's cgroup path and are
    empty for non-pod processes (a bare-metal workload, or the exporter's own
    jax backend when colocated).
    """

    pid: int
    comm: str
    device_path: str
    pod_uid: str = ""
    container_id: str = ""


def parse_cgroup_identity(cgroup_text: str) -> tuple[str, str]:
    """``/proc/<pid>/cgroup`` contents → (pod_uid, container_id), "" when
    the process is not in a Kubernetes pod cgroup. Pure function (the unit
    seam); accepts both cgroupfs-v1 multi-line and v2 single-line formats."""
    for line in cgroup_text.splitlines():
        # line: "<hierarchy>:<controllers>:<path>"
        path = line.rpartition(":")[2]
        m = _POD_UID_RE.search(path)
        if m is None:
            continue
        pod_uid = m.group(1).replace("_", "-")
        container_id = ""
        # The component *after* the pod component names the container.
        tail = path[m.end():].lstrip("-.")  # ".slice/cri-containerd-…" or "/<cid>"
        for comp in tail.split("/"):
            cm = _CONTAINER_ID_RE.match(comp)
            if cm is not None:
                container_id = cm.group(1)
                break
        return pod_uid, container_id
    return "", ""


class ProcScanner:
    """Finds holders of TPU device files by walking procfs.

    ``proc_root`` is injectable so tests drive the scanner over a synthetic
    proc tree (symlinks to nonexistent ``/dev/accel*`` work — only the link
    *target string* is read, never the device).
    """

    name = "procfs"

    def __init__(
        self,
        proc_root: str = "/proc",
        device_prefixes: tuple[str, ...] = DEFAULT_DEVICE_PREFIXES,
        full_scan_every: int = 10,
    ) -> None:
        if full_scan_every < 1:
            raise ValueError("full_scan_every must be >= 1")
        self._proc_root = proc_root
        self._prefixes = device_prefixes
        self._full_scan_every = full_scan_every
        self._cached: dict[int, tuple[DeviceHolder, ...]] = {}
        self._scans_since_full = 0
        # "Empty" is a valid verified result: an idle node must not pay the
        # full /proc walk every poll just because nothing holds a chip.
        self._has_scanned = False
        # Observability for /debug/vars and tests.
        self.full_scans = 0
        self.verify_scans = 0

    # ------------------------------------------------------------------ scan

    def scan(self) -> tuple[DeviceHolder, ...]:
        """Current holder set. Never raises for per-process races (processes
        exiting mid-scan are the norm, not an error)."""
        if self._has_scanned and self._scans_since_full < self._full_scan_every:
            self._scans_since_full += 1
            self.verify_scans += 1
            fresh: dict[int, tuple[DeviceHolder, ...]] = {}
            for pid, prev in self._cached.items():
                now = self._scan_pid(pid)
                if now != prev:
                    # A holder exited or dropped/added a device: the cheap
                    # verify can no longer vouch for the set; rescan now so
                    # a freed chip never reports a stale holder.
                    break
                fresh[pid] = now
            else:
                return self._flatten(fresh)
        return self._full_scan()

    def _full_scan(self) -> tuple[DeviceHolder, ...]:
        found = self._native_full_scan()
        if found is None:
            found = self._python_full_scan()
        self.full_scans += 1
        self._scans_since_full = 0
        self._has_scanned = True
        self._cached = found
        return self._flatten(found)

    def _python_full_scan(self) -> dict[int, tuple[DeviceHolder, ...]]:
        try:
            entries = os.listdir(self._proc_root)
        except OSError as e:
            # Scanner state is left untouched: the failure must not wipe the
            # cache or reset the verify window, or recovery would trust a
            # bogus empty set for another full_scan_every polls.
            raise ProcScanError(f"proc root {self._proc_root!r} unreadable: {e}") from e
        found: dict[int, tuple[DeviceHolder, ...]] = {}
        for entry in entries:
            if not entry.isdigit():
                continue
            pid = int(entry)
            holders = self._scan_pid(pid)
            if holders:
                found[pid] = holders
        return found

    def _native_full_scan(self) -> dict[int, tuple[DeviceHolder, ...]] | None:
        """Walk /proc via libtpumon (the O(processes × fds) readlink loop is
        the scan's entire cost on a busy node). Returns None when the native
        library is unavailable or disagrees structurally — the Python walk is
        always a correct fallback. Per-holder cgroup identity is read here in
        Python: holders are few, the walk is what's hot."""
        from tpu_pod_exporter import nativelib

        lib = nativelib.load()
        if lib is None:
            return None
        if len(self._prefixes) > 16:
            # tpumon_scan_proc matches at most 16 prefixes; beyond that the
            # native scan would silently miss holders — refuse it instead.
            return None
        prefixes = "\n".join(self._prefixes).encode()
        root = self._proc_root.encode()
        cap = 64 * 1024
        import ctypes

        while True:
            buf = ctypes.create_string_buffer(cap)
            n = lib.tpumon_scan_proc(root, prefixes, buf, cap)
            if n < 0:
                if not os.path.isdir(self._proc_root):
                    raise ProcScanError(
                        f"proc root {self._proc_root!r} unreadable"
                    )
                # Readable root but native scan refused: fall back.
                return None
            # Split on '\n' ONLY: splitlines() also breaks on \r/\v/\f/U+0085,
            # which can legally appear inside a comm and would desync the
            # record-count handshake below.
            records = [
                r for r in buf.value.decode("utf-8", errors="replace").split("\n") if r
            ]
            if len(records) == n:
                break
            if cap >= 16 * 1024 * 1024:
                # Still truncated at the ceiling: a partial holder set must
                # not masquerade as the full one (dropped holders would
                # vanish from metrics AND from the verify cache) — let the
                # unbounded Python walk take over.
                return None
            cap *= 4  # truncated: grow and rescan
        by_pid: dict[int, list[str]] = {}
        comms: dict[int, str] = {}
        for rec in records:
            parts = rec.split("\t")
            if len(parts) != 3 or not parts[0].isdigit():
                continue
            if parts[1] in EXCLUDED_DEVICE_PATHS:
                # The native walk is a pure prefix matcher; the exclusion
                # rule lives here so Python and native scans agree.
                continue
            pid = int(parts[0])
            by_pid.setdefault(pid, []).append(parts[1])
            comms[pid] = parts[2]
        found: dict[int, tuple[DeviceHolder, ...]] = {}
        for pid, paths in by_pid.items():
            base = os.path.join(self._proc_root, str(pid))
            pod_uid, container_id = parse_cgroup_identity(
                self._read_text(os.path.join(base, "cgroup"))
            )
            found[pid] = tuple(
                DeviceHolder(
                    pid=pid,
                    comm=comms[pid],
                    device_path=dp,
                    pod_uid=pod_uid,
                    container_id=container_id,
                )
                for dp in sorted(set(paths))
            )
        return found

    def _scan_pid(self, pid: int) -> tuple[DeviceHolder, ...]:
        """One process's device-file holds; () on any per-process failure
        (exited, fd table unreadable)."""
        base = os.path.join(self._proc_root, str(pid))
        fd_dir = os.path.join(base, "fd")
        device_paths: list[str] = []
        try:
            for fd in os.listdir(fd_dir):
                try:
                    target = os.readlink(os.path.join(fd_dir, fd))
                except OSError:
                    continue  # fd closed between listdir and readlink
                # A runtime restart can recreate /dev/accel* while a wedged
                # process still holds the old inode; readlink then reports
                # "/dev/accel0 (deleted)". Strip the suffix so the holder
                # still joins to the chip — that wedged holder is exactly
                # what this metric exists to expose.
                if target.endswith(" (deleted)"):
                    target = target[: -len(" (deleted)")]
                if (
                    target.startswith(self._prefixes)
                    and target not in EXCLUDED_DEVICE_PATHS
                    and target not in device_paths
                ):
                    device_paths.append(target)
        except OSError:
            return ()
        if not device_paths:
            return ()
        # Sanitized identically to the native scanner's record format (which
        # uses tab/newline separators): parity matters because the verify
        # path compares Python-scanned holders against native-scanned cache
        # entries — any formatting drift would force a full rescan per poll.
        # Trim the explicit ASCII whitespace set (NOT .strip(), which also
        # eats unicode whitespace the C side keeps), then '?'-replace the
        # separators.
        comm = (
            self._read_text(os.path.join(base, "comm"))[:63]
            .strip(" \t\n\r\v\f")
            .replace("\t", "?")
            .replace("\n", "?")
        )
        pod_uid, container_id = parse_cgroup_identity(
            self._read_text(os.path.join(base, "cgroup"))
        )
        return tuple(
            DeviceHolder(
                pid=pid,
                comm=comm,
                device_path=dp,
                pod_uid=pod_uid,
                container_id=container_id,
            )
            for dp in sorted(device_paths)
        )

    @staticmethod
    def _read_text(path: str) -> str:
        try:
            # newline="" disables universal-newline translation: a literal
            # \r inside a comm must stay \r, byte-for-byte with the native
            # scanner's raw read (verify-path parity).
            with open(path, encoding="utf-8", errors="replace", newline="") as f:
                return f.read()
        except OSError:
            return ""

    @staticmethod
    def _flatten(by_pid: dict[int, tuple[DeviceHolder, ...]]) -> tuple[DeviceHolder, ...]:
        out: list[DeviceHolder] = []
        for pid in sorted(by_pid):
            out.extend(by_pid[pid])
        return tuple(out)
