"""Slice/host topology labels for multi-host aggregation.

The reference has no topology dimension at all — it is single-node and its
labels are ``{pid, pod}`` (``main.go:22-35``). On TPU the interesting scale
is chips-per-host × hosts-per-slice (SURVEY.md §2.8): every host of a
multi-host slice runs its own exporter, and *cross-host aggregation happens
in Prometheus via labels*, never via exporter-to-exporter traffic. This
module derives those labels.

Sources, in precedence order:
1. explicit config overrides,
2. GKE/TPU-VM environment (``TPU_ACCELERATOR_TYPE``, ``TPU_WORKER_ID``,
   ``TPU_WORKER_HOSTNAMES``, GKE's ``NODE_NAME`` downward-API convention),
3. hostname fallback.

Accelerator-type parsing ("v5p-64" → generation v5p, 64 cores, 32 chips,
8 hosts) uses the public TPU topology tables. Marked **[design]** — none of
this exists in the reference.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass, field

# generation -> (tensorcores per chip, chips per host) for full hosts.
# v4/v5p expose one "megacore" device per chip but the product name counts
# 2 cores/chip; v5e ("v5litepod") and v6e count 1 core per chip.  [design]
_GEN_TABLE: dict[str, tuple[int, int]] = {
    "v2": (2, 4),
    "v3": (2, 4),
    "v4": (2, 4),
    "v5p": (2, 4),
    "v5e": (1, 8),
    "v5litepod": (1, 8),
    "v6e": (1, 8),
}


@dataclass(frozen=True)
class SliceTopology:
    accelerator: str = ""   # e.g. "v5p-64"
    generation: str = ""    # e.g. "v5p"
    total_cores: int = 0
    total_chips: int = 0
    chips_per_host: int = 0
    num_hosts: int = 0

    @property
    def multi_host(self) -> bool:
        return self.num_hosts > 1


def parse_accelerator_type(accel: str) -> SliceTopology:
    """Parse "v4-8" / "v5p-64" / "v5litepod-16" into a SliceTopology.

    Unknown shapes degrade to a zero-filled topology rather than raising —
    topology labels are best-effort context, not load-bearing joins.
    """
    accel = accel.strip()
    if not accel or "-" not in accel:
        return SliceTopology(accelerator=accel)
    gen, _, tail = accel.rpartition("-")
    gen = gen.lower()
    try:
        total_cores = int(tail)
    except ValueError:
        return SliceTopology(accelerator=accel)
    cores_per_chip, chips_per_host = _GEN_TABLE.get(gen, (0, 0))
    if cores_per_chip == 0 or total_cores <= 0:
        return SliceTopology(accelerator=accel, generation=gen, total_cores=total_cores)
    total_chips = max(total_cores // cores_per_chip, 1)
    # Single-host slices can be smaller than a full host (e.g. v5e-4).
    num_hosts = max((total_chips + chips_per_host - 1) // chips_per_host, 1)
    return SliceTopology(
        accelerator=accel,
        generation=gen,
        total_cores=total_cores,
        total_chips=total_chips,
        chips_per_host=min(chips_per_host, total_chips),
        num_hosts=num_hosts,
    )


@dataclass(frozen=True)
class HostTopology:
    """The label values this exporter instance attaches to every series.

    ``multislice_group``/``num_slices`` are NOT per-series labels (that
    would bloat every chip series for a dimension most deployments lack);
    they ride the once-per-host ``tpu_host_info`` series, which aggregators
    and recording rules join on (the Prometheus info-series pattern).
    """

    accelerator: str = ""
    slice_name: str = ""
    host: str = ""
    worker_id: str = ""
    # Multi-slice membership (BASELINE config 5, GKE multi-slice): the
    # group identity shared by all slices of one multi-slice workload, and
    # the expected slice count. Empty / "0" outside multi-slice.
    multislice_group: str = ""
    num_slices: str = ""
    slice_topology: SliceTopology = field(default_factory=SliceTopology)

    def labels(self) -> dict[str, str]:
        return {
            "accelerator": self.accelerator,
            "slice_name": self.slice_name,
            "host": self.host,
            "worker_id": self.worker_id,
        }

    def host_info_labels(self) -> dict[str, str]:
        return {
            **self.labels(),
            "multislice_group": self.multislice_group,
            "num_slices": self.num_slices,
        }


def detect_host_topology(
    env: dict[str, str] | None = None,
    accelerator: str = "",
    slice_name: str = "",
    host: str = "",
    worker_id: str = "",
    multislice_group: str = "",
) -> HostTopology:
    """Build HostTopology from overrides > environment > hostname."""
    e = os.environ if env is None else env
    accel = accelerator or e.get("TPU_ACCELERATOR_TYPE", "") or e.get("ACCELERATOR_TYPE", "")
    wid = worker_id or e.get("TPU_WORKER_ID", "") or e.get("WORKER_ID", "")
    hostname = host or e.get("NODE_NAME", "") or e.get("HOSTNAME", "") or socket.gethostname()
    sname = (
        slice_name
        or e.get("TPU_SLICE_NAME", "")
        or e.get("TPU_NAME", "")
        # GKE multi-slice: jobset/replicated-job identity downward-API convention
        or e.get("MEGASCALE_SLICE_ID", "")
    )
    # Multi-slice group identity: explicit override first (taken VERBATIM —
    # an operator's group name may legitimately contain colons), else the
    # MEGASCALE coordinator address every slice of one group shares (GKE
    # multi-slice injects it into all workers). A trailing :port is
    # stripped from the env value only when the tail is numeric, so a bare
    # IPv6 address is not mangled.
    group = multislice_group
    if not group:
        group = e.get("MEGASCALE_COORDINATOR_ADDRESS", "")
        if ":" in group:
            head, _, tail = group.rpartition(":")
            if tail.isdigit():
                group = head
    nslices = e.get("MEGASCALE_NUM_SLICES", "") if group else ""
    return HostTopology(
        accelerator=accel,
        slice_name=sname,
        host=hostname,
        worker_id=wid,
        multislice_group=group,
        num_slices=nslices,
        slice_topology=parse_accelerator_type(accel),
    )
