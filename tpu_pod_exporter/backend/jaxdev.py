"""JAX device backend — live HBM telemetry via ``Device.memory_stats()``.

For dev/bench setups where the exporter is colocated *inside* the workload
process's trust domain (it initializes the TPU runtime itself, which would
starve a separate training job — hence never auto-selected; see
``app.build_backend``). On real TPU hardware ``memory_stats()`` reports
``bytes_in_use`` / ``bytes_limit`` straight from the allocator, making this
the ground-truth cross-check for the libtpu metrics path, and the backend
the benchmark harness uses on the one real chip available to CI.
"""

from __future__ import annotations

import logging

from tpu_pod_exporter.backend import (
    BackendError,
    ChipInfo,
    ChipSample,
    DeviceBackend,
    HostSample,
)

log = logging.getLogger("tpu_pod_exporter.backend.jaxdev")


class JaxDeviceBackend(DeviceBackend):
    name = "jax"

    def __init__(self, platform: str | None = "tpu") -> None:
        """``platform=None`` samples whatever JAX's default backend exposes
        (CPU devices report no memory_stats and appear with zeroed HBM)."""
        try:
            import jax
        except Exception as e:  # noqa: BLE001
            raise BackendError(f"jax unavailable: {e}") from e
        self._jax = jax
        self._platform = platform
        self._devices = None  # resolved lazily; first call may compile-init

    def _local_devices(self):
        if self._devices is None:
            try:
                if self._platform:
                    self._devices = self._jax.local_devices(backend=self._platform)
                else:
                    self._devices = self._jax.local_devices()
            except RuntimeError as e:
                raise BackendError(f"jax device init failed: {e}") from e
        return self._devices

    def sample(self) -> HostSample:
        devices = self._local_devices()
        chips: list[ChipSample] = []
        partial: list[str] = []
        for d in devices:
            used = None
            total = None
            peak = None
            try:
                stats = d.memory_stats()
                if not stats:
                    # None (CPU) and {} (the experimental TPU tunnel — seen
                    # live, tests/fixtures/real-trace.jsonl) both mean "not
                    # readable here". Leave used/total None so the collector
                    # publishes nothing rather than a fake idle-zero.
                    partial.append(
                        f"device {d.id}: memory_stats "
                        + ("returned None" if stats is None else "empty")
                    )
                else:
                    if "bytes_in_use" in stats:
                        used = float(stats["bytes_in_use"])
                    if "bytes_limit" in stats or "bytes_reservable_limit" in stats:
                        total = float(
                            stats.get("bytes_limit", stats.get("bytes_reservable_limit"))
                        )
                    if "peak_bytes_in_use" in stats:
                        peak = float(stats["peak_bytes_in_use"])
            except Exception as e:  # noqa: BLE001 — CPU devices raise; report once
                partial.append(f"device {d.id}: memory_stats unavailable: {e}")
            coords = getattr(d, "coords", None)
            chips.append(
                ChipSample(
                    info=ChipInfo(
                        chip_id=int(d.id),
                        device_path="",
                        device_ids=(str(d.id),),
                        device_kind=getattr(d, "device_kind", "") or "",
                        coords=",".join(str(c) for c in coords) if coords else "",
                    ),
                    hbm_used_bytes=used,
                    hbm_total_bytes=total,
                    tensorcore_duty_cycle_percent=None,  # not exposed via JAX
                    hbm_peak_bytes=peak,
                )
            )
        return HostSample(chips=tuple(chips), partial_errors=tuple(partial))
