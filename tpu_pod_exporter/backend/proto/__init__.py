"""Vendored libtpu runtime metrics protobufs (see tpu_metric_service.proto)."""

from tpu_pod_exporter.backend.proto import tpu_metric_service_pb2

__all__ = ["tpu_metric_service_pb2"]
