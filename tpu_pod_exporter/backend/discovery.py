"""Local TPU device discovery — the analog of ``nvml.DeviceGetCount``
(``main.go:116-120``), without opening any device.

TPU VMs expose chips as ``/dev/accel{N}`` (v2-v5) or via vfio
(``/dev/vfio/*``, v6e+); sysfs mirrors them under ``/sys/class/accel``.
Discovery is a directory scan — no driver init, no runtime lock, safe to run
next to a training job.

A native C++ scanner (``native/tpumon.cc``) provides the same interface for
the hot path; this module is the pure-Python implementation and the ctypes
loader, falling back transparently when the shared library is absent.
"""

from __future__ import annotations

import ctypes
import glob
import os
import re
from pathlib import Path

from tpu_pod_exporter.backend import ChipInfo

_ACCEL_GLOBS = ("/dev/accel*", "/dev/vfio/[0-9]*")
_SYS_ACCEL = "/sys/class/accel"

_native = None
_native_tried = False


def _load_native() -> ctypes.CDLL | None:
    global _native, _native_tried
    if _native_tried:
        return _native
    _native_tried = True
    here = Path(__file__).resolve().parent.parent.parent
    for cand in (
        here / "native" / "libtpumon.so",
        Path("/usr/local/lib/libtpumon.so"),
    ):
        if cand.exists():
            try:
                lib = ctypes.CDLL(str(cand))
                lib.tpumon_count_devices.restype = ctypes.c_int
                lib.tpumon_count_devices.argtypes = [ctypes.c_char_p]
                _native = lib
                break
            except (OSError, AttributeError):
                # unloadable, or loadable but missing the symbol (stale .so):
                # fall back to the pure-Python scan either way
                continue
    return _native


def list_device_paths(root: str = "/") -> list[str]:
    """Paths of local TPU device nodes, sorted by chip index."""
    out: list[str] = []
    for pattern in _ACCEL_GLOBS:
        out.extend(glob.glob(os.path.join(root, pattern.lstrip("/"))))
    sys_accel = os.path.join(root, _SYS_ACCEL.lstrip("/"))
    if not out and os.path.isdir(sys_accel):
        out = [
            os.path.join("/dev", name)
            for name in sorted(os.listdir(sys_accel))
            if name.startswith("accel")
        ]

    def key(p: str) -> tuple[int, str]:
        m = re.search(r"(\d+)$", p)
        return (int(m.group(1)) if m else 1 << 30, p)

    return sorted(set(out), key=key)


def local_chip_count(root: str = "/") -> int:
    lib = _load_native()
    if lib is not None and root == "/":
        n = lib.tpumon_count_devices(b"/")
        if n >= 0:
            return n
    return len(list_device_paths(root))


def discover_chips(root: str = "/") -> list[ChipInfo]:
    """ChipInfo for each local device node. Device-plugin IDs default to the
    chip index as a string — the GKE TPU device plugin enumerates devices
    ``0..N-1`` per node, which is also what podresources reports.  [design]
    """
    paths = list_device_paths(root)
    chips: list[ChipInfo] = []
    for i, path in enumerate(paths):
        m = re.search(r"(\d+)$", path)
        idx = int(m.group(1)) if m else i
        chips.append(ChipInfo(chip_id=idx, device_path=path, device_ids=(str(idx),)))
    return chips
