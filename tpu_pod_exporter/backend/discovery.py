"""Local TPU device discovery — the analog of ``nvml.DeviceGetCount``
(``main.go:116-120``), without opening any device.

TPU VMs expose chips as ``/dev/accel{N}`` (v2-v5); newer platforms use vfio
(``/dev/vfio/{N}``). Discovery is a directory scan — no driver init, no
runtime lock, safe to run next to a training job.

Scan semantics (identical in the native scanner, ``native/tpumon.cc``, and
this pure-Python fallback — test-enforced):
- ``/dev/accel<digits>`` nodes only (non-numeric suffixes are not chips);
- vfio numeric nodes are consulted **only when zero accel nodes exist** —
  on accel platforms, unrelated vfio groups (e.g. NIC passthrough) must not
  inflate the chip count.
"""

from __future__ import annotations

import os
import re
from tpu_pod_exporter import nativelib
from tpu_pod_exporter.backend import ChipInfo

_NUM = re.compile(r"^\d+$")


def _scan(root: str) -> list[str]:
    dev = os.path.join(root, "dev")
    accel: list[tuple[int, str]] = []
    try:
        for name in os.listdir(dev):
            if name.startswith("accel") and _NUM.match(name[5:]):
                accel.append((int(name[5:]), f"/dev/{name}"))
    except OSError:
        pass
    if accel:
        return [p for _, p in sorted(accel)]
    vfio: list[tuple[int, str]] = []
    try:
        for name in os.listdir(os.path.join(dev, "vfio")):
            if _NUM.match(name):
                vfio.append((int(name), f"/dev/vfio/{name}"))
    except OSError:
        pass
    if vfio:
        return [p for _, p in sorted(vfio)]
    # Last resort: sysfs. Pods sometimes get /sys mounted but not raw /dev
    # nodes; the accel class still names the chips (SURVEY.md §2.7 commits
    # to sysfs discovery).
    sysfs: list[tuple[int, str]] = []
    try:
        for name in os.listdir(os.path.join(root, "sys", "class", "accel")):
            if name.startswith("accel") and _NUM.match(name[5:]):
                sysfs.append((int(name[5:]), f"/dev/{name}"))
    except OSError:
        pass
    return [p for _, p in sorted(sysfs)]


def list_device_paths(root: str = "/") -> list[str]:
    """Paths of local TPU device nodes, sorted by chip index."""
    return _scan(root)


def local_chip_count(root: str = "/") -> int:
    lib = nativelib.load()
    if lib is not None:
        n = lib.tpumon_count_devices(root.encode())
        if n >= 0:
            return n
    return len(_scan(root))


def discover_chips(root: str = "/") -> list[ChipInfo]:
    """ChipInfo for each local device node. Device-plugin IDs default to the
    chip index as a string — the GKE TPU device plugin enumerates devices
    ``0..N-1`` per node, which is also what podresources reports.  [design]
    """
    paths = list_device_paths(root)
    chips: list[ChipInfo] = []
    for i, path in enumerate(paths):
        m = re.search(r"(\d+)$", path)
        idx = int(m.group(1)) if m else i
        chips.append(ChipInfo(chip_id=idx, device_path=path, device_ids=(str(idx),)))
    return chips
