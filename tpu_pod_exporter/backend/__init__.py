"""Device telemetry backends — the TPU-native analog of the NVML layer.

The reference binds its telemetry source directly into ``main()`` via cgo
(``nvml.Init``/``DeviceGetCount``/``GetMemoryInfo``/
``GetComputeRunningProcesses``, ``main.go:44-54,116-138``) with no seam, so
nothing is testable without an NVIDIA driver. Here the backend is an
interface with several implementations:

- :class:`~tpu_pod_exporter.backend.fake.FakeBackend` — scripted chip
  metrics for tests, the 0-device smoke config, and benchmarks.
- :class:`~tpu_pod_exporter.backend.jaxdev.JaxDeviceBackend` — live HBM
  telemetry via JAX device ``memory_stats()``. Holds the TPU runtime, so it
  is for dev/bench colocated-with-workload setups, not the DaemonSet.
- :class:`~tpu_pod_exporter.backend.libtpu.LibtpuMetricsBackend` — the
  production path: reads the libtpu runtime metrics gRPC service (the same
  endpoint ``tpu-info`` uses) without ever opening the TPU devices.

A backend returns one :class:`HostSample` per call: every local chip's HBM
used/total, TensorCore duty cycle, and per-ICI-link cumulative traffic
counters. Errors raise :class:`BackendError`; the collector contains them
per-iteration instead of dying (inverts the reference's ``log.Fatalf`` in
the hot loop, ``main.go:119-137``).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import NamedTuple


class BackendError(RuntimeError):
    """A device-telemetry read failed; the poll should degrade, not die."""


@dataclass(frozen=True, slots=True)
class ChipInfo:
    """Static identity of one local accelerator chip.

    ``chip_id`` is the stable per-host index (the analog of the NVML device
    index, ``main.go:123-124``). ``device_ids`` are the kubelet device-plugin
    IDs this chip appears as in podresources (``google.com/tpu`` resource,
    or GPU UUIDs for ``nvidia.com/gpu``) — the join key for attribution.
    ``family`` selects the metric namespace the chip publishes under:
    ``"tpu"`` (the default — every pre-GPU backend) or ``"gpu"`` (the
    NVML-shaped backend), and rides the rollup tree as the per-family
    aggregation key so mixed GPU/TPU fleets never sum across families.
    """

    chip_id: int
    device_path: str = ""
    device_ids: tuple[str, ...] = ()
    # Optional hardware identity, filled by backends that know it (jaxdev:
    # Device.device_kind / .coords; nvml: the marketing name from
    # DeviceGetName). Empty strings when unknown.
    device_kind: str = ""
    coords: str = ""  # torus position, e.g. "0,1,2"
    family: str = "tpu"  # accelerator family: "tpu" | "gpu"

    def __post_init__(self) -> None:
        if not self.device_ids:
            object.__setattr__(self, "device_ids", (str(self.chip_id),))


class IciLinkSample(NamedTuple):
    """One inter-chip-interconnect link's cumulative traffic counter.

    NamedTuple, not dataclass: backends construct one of these per link per
    poll (256 chips × 6 links at 1 s), and tuple construction keeps that off
    the CPU budget — frozen-dataclass ``__init__`` goes through
    ``object.__setattr__`` per field.
    """

    link: str                      # stable link id, e.g. "0".."5" (3D torus: ±x,±y,±z)
    transferred_bytes_total: float # monotonic since runtime start


class DeviceProcessSample(NamedTuple):
    """One process's device-memory footprint on one chip, as reported by the
    device runtime itself (NVML ``GetComputeRunningProcesses``,
    ``main.go:134-138``). TPU runtimes pin whole chips and serve no
    per-process table, so TPU backends leave ``ChipSample.processes`` empty;
    the procfs scanner remains the TPU-side process dimension."""

    pid: int
    used_bytes: float
    comm: str = ""


class ChipSample(NamedTuple):
    """One chip's telemetry at one instant. (NamedTuple — see IciLinkSample.)"""

    info: ChipInfo
    # None means "this backend could not read HBM for this chip" (e.g. the
    # experimental TPU tunnel serves empty memory_stats — see HARDWARE.md).
    # The collector then publishes NO hbm series for the chip, matching the
    # reference's never-publish-what-you-didn't-read rule (main.go:129-132);
    # a literal 0.0 is reserved for a real idle-zero reading.
    hbm_used_bytes: float | None
    hbm_total_bytes: float | None
    tensorcore_duty_cycle_percent: float | None = None
    ici_links: tuple[IciLinkSample, ...] = ()
    # Allocator high-water mark since runtime start (jaxdev:
    # memory_stats peak_bytes_in_use); None when the backend can't report it.
    hbm_peak_bytes: float | None = None
    # DCN (data-center network, the cross-slice fabric in multi-slice
    # deployments) cumulative traffic counters — same shape as ici_links,
    # empty on runtimes/surfaces that don't serve them.
    dcn_links: tuple[IciLinkSample, ...] = ()
    # Per-process device memory, from runtimes that report it (NVML
    # GetComputeRunningProcesses). Empty on TPU backends — see
    # DeviceProcessSample. For GPU chips, tensorcore_duty_cycle_percent
    # carries the NVML utilization rate (GetUtilizationRates.gpu) and the
    # collector publishes it as gpu_utilization_percent.
    processes: tuple[DeviceProcessSample, ...] = ()


class HostSample(NamedTuple):
    """All local chips' telemetry from one backend read."""

    chips: tuple[ChipSample, ...] = ()
    # Non-fatal per-chip read problems the collector should count but not die on.
    partial_errors: tuple[str, ...] = ()


class DeviceBackend(abc.ABC):
    """The seam the reference lacks (SURVEY.md §4): all attribution and
    publishing logic must be provable against fakes, with the real backend a
    drop-in."""

    name: str = "abstract"
    # Accelerator family this backend serves ("tpu" | "gpu"): selects the
    # metric namespace for backend-level series (gpu_backend_up) and the
    # default ChipInfo.family its chips carry. Advisory — per-chip family
    # is authoritative for per-chip series.
    family: str = "tpu"

    @abc.abstractmethod
    def sample(self) -> HostSample:
        """Read all local chips. Raises BackendError on total failure."""

    def close(self) -> None:  # analog of nvml.Shutdown (main.go:49-54)
        return None


from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript  # noqa: E402

__all__ = [
    "BackendError",
    "ChipInfo",
    "ChipSample",
    "DeviceBackend",
    "DeviceProcessSample",
    "FakeBackend",
    "FakeChipScript",
    "HostSample",
    "IciLinkSample",
]
