"""Record/replay device backend — the third seam (SURVEY.md §7: "real
(libtpu), fake (tests), and a recorded mode for benchmarks").

``RecordingBackend`` wraps any backend and appends every HostSample to a
JSONL file; ``RecordedBackend`` replays such a file deterministically (loop
or hold-last). This turns one session against real hardware into a
repeatable benchmark/regression input with genuine value distributions —
something the reference has no equivalent for.

JSONL schema (one poll per line; optional keys are omitted when absent so
old recordings replay unchanged):
    {"chips": [{"chip_id": 0, "device_path": "...", "device_ids": ["0"],
                "hbm_used": N, "hbm_total": N, "duty": N|null,
                "ici": {"0": N, ...}, "dcn": {"0": N, ...}?,
                "peak": N?, "device_kind": "..."?, "coords": "..."?,
                "family": "gpu"?, "procs": [[pid, used_bytes, "comm"], ...]?},
               ...],
     "partial_errors": ["..."]}

GPU samples (the NVML-shaped backend) ride the same schema: ``family``
marks the chip's namespace (omitted = "tpu", so every pre-GPU recording
replays unchanged), ``duty`` carries the NVML utilization rate, and
``procs`` carries the per-process device-memory table — the committed
``tests/fixtures/gpu-recorded.jsonl`` runs the whole GPU path
deterministically without a driver.
"""

from __future__ import annotations

import json
import threading
from typing import IO

from tpu_pod_exporter.backend import (
    BackendError,
    ChipInfo,
    ChipSample,
    DeviceBackend,
    DeviceProcessSample,
    HostSample,
    IciLinkSample,
)
# Same numeric-first link ordering the live libtpu backend emits: replay
# must be ORDER-faithful too, or numeric ids >= 10 come back
# lexicographically shuffled and the collector's layout fast path sees a
# different link sequence than the backend being reproduced. (Safe import:
# libtpu defers its grpc import to construction.)
from tpu_pod_exporter.backend.libtpu import _link_sort_key


def sample_to_dict(sample: HostSample) -> dict:
    chips = []
    for c in sample.chips:
        doc = {
            "chip_id": c.info.chip_id,
            "device_path": c.info.device_path,
            "device_ids": list(c.info.device_ids),
            "hbm_used": c.hbm_used_bytes,
            "hbm_total": c.hbm_total_bytes,
            "duty": c.tensorcore_duty_cycle_percent,
            "ici": {l.link: l.transferred_bytes_total for l in c.ici_links},
        }
        if c.dcn_links:  # omitted when absent: old recordings replay unchanged
            doc["dcn"] = {
                l.link: l.transferred_bytes_total for l in c.dcn_links
            }
        if c.hbm_peak_bytes is not None:
            doc["peak"] = c.hbm_peak_bytes
        if c.info.device_kind:
            doc["device_kind"] = c.info.device_kind
        if c.info.coords:
            doc["coords"] = c.info.coords
        if c.info.family != "tpu":  # omitted = tpu: old recordings replay unchanged
            doc["family"] = c.info.family
        if c.processes:
            doc["procs"] = [
                [p.pid, p.used_bytes, p.comm] for p in c.processes
            ]
        chips.append(doc)
    return {
        "chips": chips,
        "partial_errors": list(sample.partial_errors),
    }


def sample_from_dict(doc: dict) -> HostSample:
    chips = []
    for c in doc.get("chips", []):
        chips.append(
            ChipSample(
                info=ChipInfo(
                    chip_id=int(c["chip_id"]),
                    device_path=c.get("device_path", ""),
                    device_ids=tuple(c.get("device_ids") or [str(c["chip_id"])]),
                    device_kind=c.get("device_kind", ""),
                    coords=c.get("coords", ""),
                    family=str(c.get("family", "tpu")),
                ),
                hbm_used_bytes=(
                    None if c["hbm_used"] is None else float(c["hbm_used"])
                ),
                hbm_total_bytes=(
                    None if c["hbm_total"] is None else float(c["hbm_total"])
                ),
                tensorcore_duty_cycle_percent=(
                    None if c.get("duty") is None else float(c["duty"])
                ),
                ici_links=tuple(
                    IciLinkSample(link=str(k), transferred_bytes_total=float(v))
                    for k, v in sorted(
                        (c.get("ici") or {}).items(), key=_link_sort_key
                    )
                ),
                hbm_peak_bytes=(
                    None if c.get("peak") is None else float(c["peak"])
                ),
                dcn_links=tuple(
                    IciLinkSample(link=str(k), transferred_bytes_total=float(v))
                    for k, v in sorted(
                        (c.get("dcn") or {}).items(), key=_link_sort_key
                    )
                ),
                processes=tuple(
                    DeviceProcessSample(
                        pid=int(p[0]), used_bytes=float(p[1]),
                        comm=str(p[2]) if len(p) > 2 else "",
                    )
                    for p in (c.get("procs") or ())
                ),
            )
        )
    return HostSample(
        chips=tuple(chips),
        partial_errors=tuple(doc.get("partial_errors", [])),
    )


class RecordingBackend(DeviceBackend):
    """Pass-through wrapper that records every sample to a JSONL stream."""

    name = "recording"

    def __init__(self, inner: DeviceBackend, sink: str | IO[str]) -> None:
        self._inner = inner
        self._own_file = isinstance(sink, str)
        self._sink: IO[str] = open(sink, "a") if isinstance(sink, str) else sink
        self._lock = threading.Lock()
        self.name = f"recording({inner.name})"
        self.family = getattr(inner, "family", "tpu")

    def sample(self) -> HostSample:
        sample = self._inner.sample()  # BackendError propagates untouched
        line = json.dumps(sample_to_dict(sample))
        with self._lock:
            self._sink.write(line + "\n")
            self._sink.flush()
        return sample

    def close(self) -> None:
        self._inner.close()
        if self._own_file:
            self._sink.close()


class RecordedBackend(DeviceBackend):
    """Deterministic replay of a recorded JSONL trace."""

    name = "recorded"

    def __init__(self, path: str, loop: bool = True) -> None:
        self._samples: list[HostSample] = []
        try:
            with open(path) as f:
                for ln, line in enumerate(f, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self._samples.append(sample_from_dict(json.loads(line)))
                    except (
                        json.JSONDecodeError,
                        KeyError,
                        ValueError,
                        # float()/.items() on a structurally wrong value
                        # (e.g. "dcn": {"0": [1,2]} or "ici": 5) raise
                        # TypeError/AttributeError — a corrupt record must
                        # report path:line, not a raw traceback.
                        TypeError,
                        AttributeError,
                    ) as e:
                        raise BackendError(f"{path}:{ln}: bad record: {e}") from e
        except OSError as e:
            raise BackendError(f"cannot read recording {path}: {e}") from e
        if not self._samples:
            raise BackendError(f"recording {path} is empty")
        # A replayed GPU recording keeps its family: gpu_backend_up and the
        # gpu_* surface come up exactly as they would against the live
        # NVML backend the trace was captured from.
        first_chips = self._samples[0].chips
        if first_chips and all(c.info.family == "gpu" for c in first_chips):
            self.family = "gpu"
        self._loop = loop
        self._i = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._samples)

    def sample(self) -> HostSample:
        with self._lock:
            if self._i >= len(self._samples):
                if self._loop:
                    self._i = 0
                else:
                    return self._samples[-1]  # hold last frame
            s = self._samples[self._i]
            self._i += 1
        return s
