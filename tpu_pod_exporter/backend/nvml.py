"""NVML-shaped GPU device backend — the second real device family.

The reference binds NVML straight into ``main()`` via cgo (``nvml.Init`` /
``DeviceGetCount`` / ``DeviceGetHandleByIndex`` / ``GetMemoryInfo`` /
``GetComputeRunningProcesses`` / ``Shutdown``, ``main.go:44-54,116-138``),
which is exactly the seam this repo abstracted into
:class:`~tpu_pod_exporter.backend.DeviceBackend`. This module closes the
loop: the same call surface, behind a swappable **driver binding**, proving
the backend seam with a second device family (ROADMAP "Prove the backend
seam").

Two bindings:

- :class:`PynvmlDriver` — thin adapter over the real ``pynvml`` wheel when
  it is installed (it is NOT in the CI image; construction degrades with a
  :class:`BackendError` naming the fix, never an ImportError at import
  time).
- :class:`SimulatedNvmlDriver` — the CI-testable driver, the way
  ``fake.py``/``recorded.py`` set the pattern: scripted per-GPU memory /
  utilization / process tables (scalars or callables of the poll step) and
  injectable NVML error codes, so every failure shape the reference dies on
  (``main.go:119-137``) is exercisable without an NVIDIA driver.

Mapping to :class:`~tpu_pod_exporter.backend.ChipSample`: device memory
rides ``hbm_used/total_bytes`` (the collector publishes it under the
``gpu_*`` twins keyed by ``ChipInfo.family == "gpu"``), the NVML
utilization rate rides ``tensorcore_duty_cycle_percent`` (published as
``gpu_utilization_percent``), and the per-process table —
the reference's headline dimension (``main.go:134-155``) — rides
``ChipSample.processes``, feeding the same podresources join the TPU path
uses for per-pod memory.

NVML error codes map to :class:`NvmlError` (a ``BackendError``): a failed
``Init``/``DeviceGetCount`` fails the whole sample (the collector degrades
the poll, inverting the reference's ``log.Fatalf``); a failed per-device
query degrades that chip only (absent fields + a ``partial_errors`` entry).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Sequence

from tpu_pod_exporter.backend import (
    BackendError,
    ChipInfo,
    ChipSample,
    DeviceBackend,
    DeviceProcessSample,
    HostSample,
)

# The NVML return codes the simulated driver can speak and the backend maps
# (numeric values per nvml.h; names accepted with or without the prefix).
NVML_ERROR_CODES: dict[str, int] = {
    "NVML_ERROR_UNINITIALIZED": 1,
    "NVML_ERROR_INVALID_ARGUMENT": 2,
    "NVML_ERROR_NOT_SUPPORTED": 3,
    "NVML_ERROR_NO_PERMISSION": 4,
    "NVML_ERROR_NOT_FOUND": 6,
    "NVML_ERROR_INSUFFICIENT_SIZE": 7,
    "NVML_ERROR_DRIVER_NOT_LOADED": 9,
    "NVML_ERROR_TIMEOUT": 10,
    "NVML_ERROR_IRQ_ISSUE": 13,
    "NVML_ERROR_LIBRARY_NOT_FOUND": 12,
    "NVML_ERROR_GPU_IS_LOST": 15,
    "NVML_ERROR_RESET_REQUIRED": 16,
    "NVML_ERROR_MEMORY": 20,
    "NVML_ERROR_UNKNOWN": 999,
}

_CODE_NAMES = {v: k for k, v in NVML_ERROR_CODES.items()}

DEFAULT_GPU_MEM_TOTAL = 80 * 1024**3  # A100/H100-class: 80 GiB  [design]


def normalize_nvml_code(code: str | int) -> tuple[str, int]:
    """``"gpu_is_lost"`` / ``"NVML_ERROR_GPU_IS_LOST"`` / ``15`` →
    ``("NVML_ERROR_GPU_IS_LOST", 15)``. Raises ValueError on an unknown
    code — a typo'd chaos/sim spec must fail loudly at parse time."""
    if isinstance(code, int):
        name = _CODE_NAMES.get(code)
        if name is None:
            raise ValueError(f"unknown NVML error code {code}")
        return name, code
    name = code.strip().upper()
    if not name.startswith("NVML_ERROR_"):
        name = "NVML_ERROR_" + name
    value = NVML_ERROR_CODES.get(name)
    if value is None:
        raise ValueError(
            f"unknown NVML error code {code!r} "
            f"(want one of {', '.join(sorted(NVML_ERROR_CODES))})"
        )
    return name, value


class NvmlError(BackendError):
    """An NVML call failed; carries the NVML return code so tests and the
    chaos layer can speak exact error shapes (``main.go:119-137`` dies on
    any of these — here they degrade)."""

    def __init__(self, call: str, code: str | int) -> None:
        self.call = call
        self.code_name, self.code = normalize_nvml_code(code)
        super().__init__(f"{call}: {self.code_name} ({self.code})")


class NvmlDriverError(RuntimeError):
    """Raised by a driver binding; the backend wraps it into NvmlError.
    Mirrors pynvml.NVMLError's ``.value`` attribute."""

    def __init__(self, code: str | int) -> None:
        name, value = normalize_nvml_code(code)
        self.value = value
        super().__init__(name)


@dataclass
class GpuScript:
    """Scripted telemetry for one simulated GPU. Values may be scalars
    (constant) or callables of the driver step — same convention as
    :class:`~tpu_pod_exporter.backend.fake.FakeChipScript`."""

    mem_total_bytes: float = DEFAULT_GPU_MEM_TOTAL
    mem_used_bytes: float | Callable[[int], float] = 0.0
    utilization_percent: float | Callable[[int], float] | None = 0.0
    # [(pid, used_bytes, comm)] or a callable of the step returning that —
    # the GetComputeRunningProcesses table (main.go:134-138).
    processes: (
        Sequence[tuple[int, float, str]]
        | Callable[[int], Sequence[tuple[int, float, str]]]
    ) = ()
    name: str = "Simulated-GPU"
    uuid: str = ""  # defaults to GPU-sim-<index> at construction

    def _resolve(self, v, step: int) -> float:
        return float(v(step)) if callable(v) else float(v)


class SimulatedNvmlDriver:
    """NVML-shaped in-process driver: the exact call surface the reference
    uses (``main.go:44-54,116-138``) plus ``GetUtilizationRates``, over
    scripted tables, with injectable per-call NVML error codes.

    The step counter advances on each ``nvmlDeviceGetCount()`` — the first
    call of every backend sample pass, matching the reference's
    re-enumeration each loop iteration (``main.go:117``)."""

    def __init__(self, gpus: int | Sequence[GpuScript] = 1) -> None:
        if isinstance(gpus, int):
            scripts = [GpuScript() for _ in range(gpus)]
        else:
            scripts = list(gpus)
        for i, s in enumerate(scripts):
            if not s.uuid:
                s.uuid = f"GPU-sim-{i}"
        self.scripts = scripts
        self.step = -1  # first DeviceGetCount() makes it 0
        self.initialized = False
        self.init_calls = 0
        self.shutdown_calls = 0
        self._lock = threading.Lock()
        # call name -> [(code, remaining)] injection queue, FIFO.
        self._faults: dict[str, list[list]] = {}

    # -- fault injection ----------------------------------------------------

    def inject(self, call: str, code: str | int, times: int = 1) -> None:
        """Make the next ``times`` invocations of ``call`` (e.g.
        ``"DeviceGetMemoryInfo"``) raise the given NVML code."""
        name, _v = normalize_nvml_code(code)
        with self._lock:
            self._faults.setdefault(call, []).append([name, times])

    def _maybe_fault(self, call: str) -> None:
        with self._lock:
            q = self._faults.get(call)
            if not q:
                return
            name, remaining = q[0]
            if remaining <= 1:
                q.pop(0)
            else:
                q[0][1] = remaining - 1
        raise NvmlDriverError(name)

    def _handle(self, handle: int) -> GpuScript:
        if not self.initialized:
            raise NvmlDriverError("NVML_ERROR_UNINITIALIZED")
        if not 0 <= handle < len(self.scripts):
            raise NvmlDriverError("NVML_ERROR_INVALID_ARGUMENT")
        return self.scripts[handle]

    # -- the NVML call surface (main.go:44-54,116-138) ----------------------

    def nvmlInit(self) -> None:  # noqa: N802 — NVML API casing
        self._maybe_fault("Init")
        self.init_calls += 1
        self.initialized = True

    def nvmlShutdown(self) -> None:  # noqa: N802
        self._maybe_fault("Shutdown")
        self.shutdown_calls += 1
        self.initialized = False

    def nvmlDeviceGetCount(self) -> int:  # noqa: N802
        if not self.initialized:
            raise NvmlDriverError("NVML_ERROR_UNINITIALIZED")
        self._maybe_fault("DeviceGetCount")
        self.step += 1
        return len(self.scripts)

    def nvmlDeviceGetHandleByIndex(self, index: int) -> int:  # noqa: N802
        self._handle(index)
        self._maybe_fault("DeviceGetHandleByIndex")
        return index

    def nvmlDeviceGetName(self, handle: int) -> str:  # noqa: N802
        return self._handle(handle).name

    def nvmlDeviceGetUUID(self, handle: int) -> str:  # noqa: N802
        return self._handle(handle).uuid

    def nvmlDeviceGetMemoryInfo(self, handle: int):  # noqa: N802
        script = self._handle(handle)
        self._maybe_fault("DeviceGetMemoryInfo")
        step = max(self.step, 0)
        used = script._resolve(script.mem_used_bytes, step)
        total = script.mem_total_bytes
        return {"used": used, "total": total, "free": max(total - used, 0.0)}

    def nvmlDeviceGetUtilizationRates(self, handle: int):  # noqa: N802
        script = self._handle(handle)
        self._maybe_fault("DeviceGetUtilizationRates")
        if script.utilization_percent is None:
            raise NvmlDriverError("NVML_ERROR_NOT_SUPPORTED")
        step = max(self.step, 0)
        return {"gpu": script._resolve(script.utilization_percent, step)}

    def nvmlDeviceGetComputeRunningProcesses(self, handle: int):  # noqa: N802
        script = self._handle(handle)
        self._maybe_fault("DeviceGetComputeRunningProcesses")
        step = max(self.step, 0)
        procs = script.processes
        if callable(procs):
            procs = procs(step)
        return [
            {"pid": int(p[0]), "usedGpuMemory": float(p[1]),
             "comm": str(p[2]) if len(p) > 2 else ""}
            for p in procs
        ]


def sim_driver_from_spec(doc: dict) -> SimulatedNvmlDriver:
    """Build a simulated driver from a JSON spec (``--nvml-sim-spec``)::

        {"gpus": [{"mem_total": N, "mem_used": N, "utilization": N,
                   "name": "...", "uuid": "...",
                   "processes": [[pid, used_bytes, "comm"], ...]}, ...],
         "faults": [{"call": "DeviceGetMemoryInfo",
                     "code": "gpu_is_lost", "times": 2}, ...]}

    Scalars only (callables are for in-process tests); malformed specs
    raise ValueError at startup, same discipline as every other flag."""
    gpus = doc.get("gpus")
    if not isinstance(gpus, list) or not gpus:
        raise ValueError("nvml sim spec: want a non-empty 'gpus' list")
    scripts = []
    for i, g in enumerate(gpus):
        if not isinstance(g, dict):
            raise ValueError(f"nvml sim spec: gpus[{i}] must be an object")
        scripts.append(GpuScript(
            mem_total_bytes=float(g.get("mem_total", DEFAULT_GPU_MEM_TOTAL)),
            mem_used_bytes=float(g.get("mem_used", 0.0)),
            utilization_percent=(
                None if g.get("utilization") is None
                else float(g["utilization"])
            ),
            processes=tuple(
                (int(p[0]), float(p[1]), str(p[2]) if len(p) > 2 else "")
                for p in g.get("processes", ())
            ),
            name=str(g.get("name", "Simulated-GPU")),
            uuid=str(g.get("uuid", "")),
        ))
    driver = SimulatedNvmlDriver(scripts)
    for j, f in enumerate(doc.get("faults", ())):
        if not isinstance(f, dict) or "call" not in f or "code" not in f:
            raise ValueError(
                f"nvml sim spec: faults[{j}] wants {{call, code[, times]}}"
            )
        driver.inject(str(f["call"]), f["code"], int(f.get("times", 1)))
    return driver


class PynvmlDriver:
    """Adapter over the real ``pynvml`` wheel (same call names, NVML
    struct returns normalized to the dict shapes the simulated driver
    serves). Not importable in the CI image — construction raises
    BackendError, never ImportError."""

    def __init__(self) -> None:
        try:
            import pynvml  # noqa: PLC0415 — optional, driver-gated
        except ImportError as e:
            raise BackendError(
                "pynvml is not installed; --backend nvml needs either the "
                "NVIDIA driver + pynvml or --nvml-sim-gpus/--nvml-sim-spec "
                "for the simulated driver"
            ) from e
        self._nvml = pynvml

    def __getattr__(self, item: str):
        return getattr(self._nvml, item)


@dataclass
class _InitState:
    initialized: bool = False
    lock: threading.Lock = field(default_factory=threading.Lock)


def _nvml_str(v) -> str:
    """Real NVML bindings return ``bytes`` for name/UUID on widely-deployed
    nvidia-ml-py versions; ``str(b'GPU-…')`` would mangle the UUID and
    silently break the podresources attribution join."""
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    return str(v)


class NvmlBackend(DeviceBackend):
    """The GPU device family behind the same seam: one ``HostSample`` per
    call, every local GPU's memory/utilization/process table, errors as
    :class:`NvmlError` instead of the reference's in-loop ``log.Fatalf``."""

    name = "nvml"
    family = "gpu"

    def __init__(self, driver=None,
                 device_path_fmt: str = "/dev/nvidia{index}") -> None:
        self._driver = driver if driver is not None else PynvmlDriver()
        self._device_path_fmt = device_path_fmt
        self._init = _InitState()

    def _wrap(self, call: str, e: Exception) -> NvmlError:
        code = getattr(e, "value", None)
        if code is None or code not in _CODE_NAMES:
            code = "NVML_ERROR_UNKNOWN"
        return NvmlError(call, code)

    def _ensure_init(self) -> None:
        # Init-once, re-init after close(): the supervisor's breaker-gated
        # reconnect path is close()+re-call, and for NVML that is
        # Shutdown()+Init() — a lost GPU often needs exactly that.
        with self._init.lock:
            if self._init.initialized:
                return
            try:
                self._driver.nvmlInit()
            except NvmlDriverError as e:
                raise self._wrap("Init", e) from e
            except BackendError:
                raise
            except Exception as e:  # noqa: BLE001 — binding-level failure
                raise self._wrap("Init", e) from e
            self._init.initialized = True

    def sample(self) -> HostSample:
        self._ensure_init()
        d = self._driver
        try:
            count = d.nvmlDeviceGetCount()
        except Exception as e:  # noqa: BLE001 — total failure fails the poll
            raise self._wrap("DeviceGetCount", e) from e
        chips: list[ChipSample] = []
        partial: list[str] = []
        for i in range(int(count)):
            try:
                handle = d.nvmlDeviceGetHandleByIndex(i)
            except Exception as e:  # noqa: BLE001 — this device only
                partial.append(str(self._wrap(f"DeviceGetHandleByIndex({i})", e)))
                continue
            kind = ""
            uuid = ""
            try:
                kind = _nvml_str(d.nvmlDeviceGetName(handle))
                uuid = _nvml_str(d.nvmlDeviceGetUUID(handle))
            except Exception:  # noqa: BLE001 — identity is optional
                pass
            info = ChipInfo(
                chip_id=i,
                device_path=self._device_path_fmt.format(index=i),
                # The kubelet device plugin advertises nvidia.com/gpu
                # devices by GPU UUID — that is the attribution join key;
                # the bare index rides along for fakes/tests.
                device_ids=(uuid, str(i)) if uuid else (str(i),),
                device_kind=kind,
                family="gpu",
            )
            used = total = None
            try:
                mem = d.nvmlDeviceGetMemoryInfo(handle)
                used = float(mem["used"] if isinstance(mem, dict)
                             else mem.used)
                total = float(mem["total"] if isinstance(mem, dict)
                              else mem.total)
            except Exception as e:  # noqa: BLE001 — absent beats fake-zero
                partial.append(str(self._wrap(f"DeviceGetMemoryInfo({i})", e)))
            util = None
            try:
                rates = d.nvmlDeviceGetUtilizationRates(handle)
                util = float(rates["gpu"] if isinstance(rates, dict)
                             else rates.gpu)
            except Exception as e:  # noqa: BLE001
                code = getattr(e, "value", None)
                # NOT_SUPPORTED is a capability, not a fault: some boards
                # simply serve no utilization — absent series, no error.
                if code != NVML_ERROR_CODES["NVML_ERROR_NOT_SUPPORTED"]:
                    partial.append(
                        str(self._wrap(f"DeviceGetUtilizationRates({i})", e))
                    )
            procs: tuple[DeviceProcessSample, ...] = ()
            try:
                rows = d.nvmlDeviceGetComputeRunningProcesses(handle)
                proc_list = []
                for r in rows:
                    mem = (r["usedGpuMemory"] if isinstance(r, dict)
                           else r.usedGpuMemory)
                    if mem is None:
                        # NVML_VALUE_NOT_AVAILABLE (MIG, insufficient
                        # permissions): skip the N/A row, keep the rest of
                        # the table — absent beats fake-zero, and one
                        # unreadable row must not drop every process.
                        continue
                    proc_list.append(DeviceProcessSample(
                        pid=int(r["pid"] if isinstance(r, dict) else r.pid),
                        used_bytes=float(mem),
                        comm=str(r.get("comm", "")) if isinstance(r, dict)
                        else "",
                    ))
                procs = tuple(proc_list)
            except Exception as e:  # noqa: BLE001
                partial.append(str(self._wrap(
                    f"DeviceGetComputeRunningProcesses({i})", e)))
            chips.append(ChipSample(
                info=info,
                hbm_used_bytes=used,
                hbm_total_bytes=total,
                tensorcore_duty_cycle_percent=util,
                processes=procs,
            ))
        return HostSample(chips=tuple(chips), partial_errors=tuple(partial))

    def close(self) -> None:  # the analog of nvml.Shutdown (main.go:49-54)
        with self._init.lock:
            if not self._init.initialized:
                return
            self._init.initialized = False
            try:
                self._driver.nvmlShutdown()
            except Exception:  # noqa: BLE001 — closing a lost GPU still closes
                pass


def run_gpu_demo(recording: str, verbose: bool = True) -> int:
    """``make gpu-demo``: replay a recorded GPU trace through the REAL
    collector (no driver, no cluster) and assert the whole GPU node
    surface comes out — per-chip memory/utilization, the per-process
    table, per-pod memory via the podresources join, gpu_backend_up, and
    an injected per-device NVML fault degrading one chip only."""
    from tpu_pod_exporter.attribution import DeviceAllocation
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.recorded import RecordedBackend
    from tpu_pod_exporter.collector import Collector
    from tpu_pod_exporter.metrics import SnapshotStore
    from tpu_pod_exporter.metrics.parse import parse_families

    backend = RecordedBackend(recording, loop=False)
    first = backend.sample()  # peek the chip set for the allocation join
    device_ids = [
        did for c in first.chips for did in c.info.device_ids
    ]
    backend = RecordedBackend(recording, loop=False)  # replay from poll 0
    attribution = FakeAttribution(allocations=[
        DeviceAllocation(pod="gpu-demo-pod", namespace="demo",
                         container="main", device_ids=tuple(device_ids)),
    ])
    store = SnapshotStore()
    collector = Collector(backend, attribution, store)
    partials = 0
    for _ in range(len(backend)):
        stats = collector.poll_once()
        partials += sum(1 for e in stats.errors if e == "device_partial")
    collector.close()
    text = store.current().encode().decode()
    fams = parse_families(text)
    problems: list[str] = []
    for name in ("gpu_chip_info", "gpu_hbm_used_bytes",
                 "gpu_hbm_total_bytes", "gpu_utilization_percent",
                 "gpu_process_memory_used_bytes", "gpu_pod_chip_count",
                 "gpu_pod_memory_used_bytes"):
        if not fams.get(name):
            problems.append(f"{name} absent from the replayed exposition")
    up = [s.value for s in fams.get("gpu_backend_up", ())]
    if up != [1.0]:
        problems.append(f"gpu_backend_up {up}, want [1.0]")
    pod_mem = [
        s for s in fams.get("gpu_pod_memory_used_bytes", ())
        if s.labels.get("pod") == "gpu-demo-pod"
    ]
    if not pod_mem:
        problems.append("per-pod GPU memory did not join to gpu-demo-pod")
    chip_mem = sum(s.value for s in fams.get("gpu_hbm_used_bytes", ()))
    if pod_mem and abs(pod_mem[0].value - chip_mem) > 1e-6:
        problems.append(
            f"pod memory {pod_mem[0].value} != summed chip memory "
            f"{chip_mem} (join drift)")
    if partials < 1:
        problems.append(
            "no device_partial observed — the recorded NVML fault did "
            "not replay")
    if verbose:
        chips = len(fams.get("gpu_chip_info", ()))
        procs = len(fams.get("gpu_process_memory_used_bytes", ()))
        print(f"gpu-demo: replayed {len(backend)} polls: {chips} GPUs, "
              f"{procs} process series, pod memory "
              f"{pod_mem[0].value / 2**30:.1f} GiB, "
              f"{partials} partial-fault poll(s)"
              if not problems else
              f"gpu-demo FAILED: {problems}")
    return 1 if problems else 0


def _main(argv: "list[str] | None" = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-nvml",
        description="NVML-shaped GPU backend demo (make gpu-demo).",
    )
    p.add_argument("--demo", action="store_true", required=True)
    p.add_argument("--recording",
                   default="tests/fixtures/gpu-recorded.jsonl")
    ns = p.parse_args(argv)
    return run_gpu_demo(ns.recording)


if __name__ == "__main__":
    raise SystemExit(_main())
