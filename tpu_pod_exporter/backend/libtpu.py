"""libtpu runtime-metrics backend — the production telemetry path.

TPU-native replacement for the reference's NVML layer (``main.go:116-138``):
instead of cgo ioctls into a driver library, this reads the libtpu runtime's
local gRPC metrics service (the endpoint ``tpu-info`` uses, default
``localhost:8431``). Crucially it never opens ``/dev/accel*`` itself — the
TPU runtime lock stays with the workload pod, and the exporter stays a pure
observer.

Metric names queried (the public libtpu names):
  - ``tpu.runtime.hbm.memory.usage.bytes``    (per chip)
  - ``tpu.runtime.hbm.memory.total.bytes``    (per chip)
  - ``tpu.runtime.tensorcore.dutycycle.percent`` (per chip)

All three are fetched in one poll; each response row carries a device-id
attribute, and ICI counter rows may additionally carry a link attribute
(either attribute order) which becomes the per-link ``link`` label —
the degraded single-attribute shape exports ``link="all"``.
Any RPC failure, parse surprise, or shape mismatch raises
BackendError (total) or is reported via ``HostSample.partial_errors``
(per-metric) — the collector degrades instead of dying (contrast the
reference's ``log.Fatalf`` per query, ``main.go:119-137``).
"""

from __future__ import annotations

import logging
import threading

from tpu_pod_exporter.backend import (
    BackendError,
    ChipInfo,
    ChipSample,
    DeviceBackend,
    HostSample,
    IciLinkSample,
)

log = logging.getLogger("tpu_pod_exporter.backend.libtpu")

DEFAULT_ADDR = "localhost:8431"

HBM_USAGE = "tpu.runtime.hbm.memory.usage.bytes"
HBM_TOTAL = "tpu.runtime.hbm.memory.total.bytes"
DUTY_CYCLE = "tpu.runtime.tensorcore.dutycycle.percent"
# Optional — not all runtime versions export ICI counters, and the exact
# public name is unconfirmed until probed on real hardware (VERDICT r1 #3).
# Candidates are tried in order: first via ListSupportedMetrics when the
# runtime implements it, else by direct GetRuntimeMetric probes; the first
# hit is remembered for the life of the backend.
ICI_TRANSFERRED = "tpu.runtime.ici.transferred.bytes"
ICI_CANDIDATES = (
    ICI_TRANSFERRED,
    "tpu.runtime.ici.traffic.bytes",
    "tpu.runtime.interconnect.transferred.bytes",
    "megascale.ici.transferred.bytes",
)
# DCN (data-center network — the cross-slice fabric of a GKE multi-slice
# deployment, BASELINE config 5) rides the same discovery ladder as ICI:
# the exact public name is unconfirmed until probed on real multi-slice
# hardware, so candidates are tried via enumeration, then direct probes.
DCN_TRANSFERRED = "tpu.runtime.dcn.transferred.bytes"
DCN_CANDIDATES = (
    DCN_TRANSFERRED,
    "tpu.runtime.dcn.traffic.bytes",
    "megascale.dcn.transferred.bytes",
)

GET_METRIC_METHOD = "/tpu.monitoring.runtime.RuntimeMetricService/GetRuntimeMetric"
LIST_METRICS_METHOD = (
    "/tpu.monitoring.runtime.RuntimeMetricService/ListSupportedMetrics"
)


def gauge_value(metric) -> float:
    which = metric.gauge.WhichOneof("value")
    if which == "as_int":
        return float(metric.gauge.as_int)
    if which == "as_double":
        return float(metric.gauge.as_double)
    if which == "as_string":
        try:
            return float(metric.gauge.as_string)
        except ValueError:
            return float("nan")
    return float("nan")


def attr_str(value) -> str:
    which = value.WhichOneof("attr")
    if which == "int_attr":
        return str(value.int_attr)
    if which == "string_attr":
        return value.string_attr
    return ""


# Attribute-key substrings that identify which attribute on a metric row is
# the device id vs the ICI link id. Matched case-insensitively so both
# "device-id" and "DeviceId" shapes resolve; per-link rows may carry the two
# attributes in either order.
DEVICE_ATTR_HINTS = ("device", "chip", "core", "accel")
LINK_ATTR_HINTS = ("link", "port", "direction", "neighbor", "axis")

# One-shot guard for the positional-fallback warning below: the fallback
# engaging on a real runtime means its attribute keys matched no hint, and
# a mis-labeled device/link axis would otherwise be undiagnosable from the
# exported series alone (VERDICT r4 weak #4). Per-process, not per-row —
# the fallback runs on the hottest parse path.
_positional_fallback_logged = False


def split_attrs(metric) -> tuple[str, str | None]:
    """One metric row's attributes → (device_id, link_id-or-None).

    Historical rows carry exactly one attribute (the device id). Per-link
    ICI counters (BASELINE config 4's headline) carry a device attribute
    plus a link attribute — accepted in either order by matching attribute
    *keys*, with a positional fallback (first=device, second=link) for a
    runtime whose key names match no hint. Contrast the reference, which
    only ever walks one implicit device axis (main.go:123-138).
    """
    attrs = metric.attribute
    if len(attrs) == 1:
        return attr_str(attrs[0].value), None
    if not attrs:
        return "", None
    dev: str | None = None
    link: str | None = None
    rest = []
    for a in attrs:
        k = a.key.lower()
        if dev is None and any(h in k for h in DEVICE_ATTR_HINTS):
            dev = attr_str(a.value)
        elif link is None and any(h in k for h in LINK_ATTR_HINTS):
            link = attr_str(a.value)
        else:
            rest.append(a)
    if rest and (dev is None or link is None):
        global _positional_fallback_logged
        if not _positional_fallback_logged:
            _positional_fallback_logged = True
            log.warning(
                "metric attribute key(s) %s matched no device/link hint; "
                "assuming positional order (first=device, second=link) — "
                "verify labels against the runtime's real key names",
                [a.key for a in rest],
            )
    if dev is None and rest:
        dev = attr_str(rest.pop(0).value)
    if link is None and rest:
        link = attr_str(rest[0].value)
    return dev or "", link


def rows_by_device(resp) -> dict[str, float]:
    """MetricResponse → {device_id_attr: value} (per-device metrics)."""
    out: dict[str, float] = {}
    for m in resp.metric.metrics:
        dev, _ = split_attrs(m)
        out[dev] = gauge_value(m)
    return out


def ici_rows(resp) -> dict[str, dict[str, float]]:
    """MetricResponse → {device_id: {link_id: value}}.

    Rows without a link attribute land under link "all" — the degraded
    per-chip-aggregate shape older runtimes serve (and the only shape the
    production path could emit before round 4).
    """
    out: dict[str, dict[str, float]] = {}
    for m in resp.metric.metrics:
        dev, link = split_attrs(m)
        out.setdefault(dev, {})[link if link is not None else "all"] = gauge_value(m)
    return out


class _CounterDiscovery:
    """Discovery-ladder state for one optional per-link counter family.

    ``metric``: None = unprobed; False = affirmatively unsupported; str =
    the confirmed metric name to query every poll. ``vanished``: names that
    were confirmed and then NOT_FOUND on query (stale enumeration table /
    runtime swap) — excluded from rediscovery so an inconsistent runtime
    can't flap discover→fail every poll.
    """

    __slots__ = ("kind", "candidates", "metric", "vanished")

    def __init__(self, kind: str, candidates: tuple[str, ...]) -> None:
        self.kind = kind
        self.candidates = candidates
        self.metric: str | None | bool = None
        self.vanished: set[str] = set()


class LibtpuMetricsBackend(DeviceBackend):
    name = "libtpu"

    def __init__(
        self,
        addr: str = DEFAULT_ADDR,
        timeout_s: float = 1.0,
        device_paths: dict[int, str] | None = None,
    ) -> None:
        import grpc

        from tpu_pod_exporter.backend.proto import tpu_metric_service_pb2 as pb

        self._grpc = grpc
        self._pb = pb
        self._addr = addr
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._channel = None
        self._get = None
        self._list = None
        # One discovery-ladder state per optional per-link counter family
        # (ICI and DCN share the machinery; each confirms independently).
        self._ici_disc = _CounterDiscovery("ICI", ICI_CANDIDATES)
        self._dcn_disc = _CounterDiscovery("DCN", DCN_CANDIDATES)
        if device_paths is None:
            import re

            from tpu_pod_exporter.backend.discovery import list_device_paths

            device_paths = {}
            for i, p in enumerate(list_device_paths()):
                if "/vfio/" in p:
                    # vfio group numbers are kernel-assigned and unrelated to
                    # runtime device ids — key positionally.
                    device_paths[i] = p
                else:
                    # accelN → N: runtime device ids follow the node
                    # numbering even when it is not 0-based contiguous.
                    m = re.search(r"(\d+)$", p)
                    device_paths[int(m.group(1)) if m else i] = p
        self._device_paths = device_paths

    def _ensure_channel(self) -> None:
        with self._lock:
            if self._channel is not None:
                return
            self._channel = self._grpc.insecure_channel(
                self._addr, options=[("grpc.enable_http_proxy", 0)]
            )
            self._get = self._channel.unary_unary(
                GET_METRIC_METHOD,
                request_serializer=self._pb.MetricRequest.SerializeToString,
                response_deserializer=self._pb.MetricResponse.FromString,
            )
            self._list = self._channel.unary_unary(
                LIST_METRICS_METHOD,
                request_serializer=(
                    self._pb.ListSupportedMetricsRequest.SerializeToString
                ),
                response_deserializer=(
                    self._pb.ListSupportedMetricsResponse.FromString
                ),
            )

    def query_raw(self, metric_name: str, timeout_s: float | None = None):
        """Public raw GetRuntimeMetric — returns the MetricResponse message.
        The probe tool builds on this so the RPC plumbing has one owner."""
        self._ensure_channel()
        return self._get(
            self._pb.MetricRequest(metric_name=metric_name),
            timeout=self._timeout_s if timeout_s is None else timeout_s,
        )

    def _query(self, metric_name: str) -> dict[str, float]:
        return rows_by_device(self.query_raw(metric_name))

    def _query_ici(self, metric_name: str) -> dict[str, dict[str, float]]:
        return ici_rows(self.query_raw(metric_name))

    def list_supported_metrics(self) -> list[str] | None:
        """Names the runtime serves, or None when the runtime does not
        implement the enumeration RPC (older libtpu)."""
        self._ensure_channel()
        try:
            resp = self._list(
                self._pb.ListSupportedMetricsRequest(), timeout=self._timeout_s
            )
        except self._grpc.RpcError as e:
            if e.code() in (
                self._grpc.StatusCode.UNIMPLEMENTED,
                self._grpc.StatusCode.NOT_FOUND,
            ):
                return None
            raise
        return [m.metric_name for m in resp.supported_metric]

    def _resolve_counter(self, disc: "_CounterDiscovery", get_supported):
        """One-time discovery of one counter family's real name. Sets
        ``disc.metric`` to the confirmed name, or False when the runtime
        affirmatively serves none of the candidates. Returns the metric
        rows when discovery already fetched them (the probe path), so the
        first poll doesn't issue the same RPC twice. Raises on transient
        errors (leaves the probe un-latched for the next poll). Names in
        ``disc.vanished`` are excluded — see _CounterDiscovery.
        ``get_supported`` memoizes the enumeration RPC so ICI and DCN
        resolving in the same poll share one ListSupportedMetrics call."""
        candidates = [n for n in disc.candidates if n not in disc.vanished]
        supported = get_supported()
        if supported is not None and HBM_USAGE not in supported:
            # Sanity check before trusting enumeration: sample() queried
            # HBM_USAGE successfully moments ago, so a list omitting it
            # means the RPC exists but its wire shape differs from our
            # guessed proto (proto3 parses a mismatched response as empty,
            # not as an error). Trusting it would silently latch the
            # counter off on a runtime that serves it — fall through to
            # direct probes.
            log.warning(
                "ListSupportedMetrics omitted %s (just served); treating "
                "enumeration as unreliable and probing candidates directly",
                HBM_USAGE,
            )
            supported = None
        if supported is not None:
            for name in candidates:
                if name in supported:
                    disc.metric = name
                    log.info(
                        "%s counter confirmed via enumeration: %s",
                        disc.kind, name,
                    )
                    return None
            # Nothing named like our candidates; surface what looked close
            # so an operator can extend the candidate list from the logs.
            needle = disc.kind.lower()
            kindish = [n for n in supported if needle in n.lower()]
            log.info(
                "no known %s counter in %d supported metrics%s",
                disc.kind, len(supported),
                f"; {needle}-like names: {kindish}" if kindish else "",
            )
            disc.metric = False
            return None
        # No enumeration RPC: probe candidates directly.
        for name in candidates:
            try:
                rows = self._query_ici(name)
                disc.metric = name
                log.info("%s counter confirmed by probe: %s", disc.kind, name)
                return rows
            except self._grpc.RpcError as e:
                if e.code() in (
                    self._grpc.StatusCode.NOT_FOUND,
                    self._grpc.StatusCode.UNIMPLEMENTED,
                    self._grpc.StatusCode.INVALID_ARGUMENT,
                ):
                    continue  # affirmatively not this name; try the next
                raise  # transient — retry the whole probe next poll
        log.info(
            "%s counters unsupported by this runtime (all candidates)",
            disc.kind,
        )
        disc.metric = False
        return None

    def _sample_counter(
        self, disc: "_CounterDiscovery", partial: list[str], get_supported
    ) -> dict[str, dict[str, float]]:
        """One poll's rows for one optional counter family: resolve on
        first contact, then query the confirmed name, handling vanish
        (re-probe without the liar) and transient errors (surface, keep)."""
        rows: dict[str, dict[str, float]] = {}
        discovered_rows = None
        if disc.metric is None:
            try:
                discovered_rows = self._resolve_counter(disc, get_supported)
            except Exception as e:  # noqa: BLE001 — transient: retry next poll
                partial.append(f"{disc.kind} discovery failed: {e}")
        if isinstance(disc.metric, str):
            if discovered_rows is not None:
                rows = discovered_rows  # probe already fetched this poll's rows
            else:
                try:
                    rows = self._query_ici(disc.metric)
                except Exception as e:  # noqa: BLE001
                    code = getattr(e, "code", lambda: None)()
                    if code in (
                        self._grpc.StatusCode.NOT_FOUND,
                        self._grpc.StatusCode.UNIMPLEMENTED,
                        self._grpc.StatusCode.INVALID_ARGUMENT,
                    ):
                        # The runtime stopped serving the confirmed name
                        # (runtime swap, or a stale enumeration table):
                        # rediscover next poll, excluding this name so an
                        # inconsistent runtime can't flap forever.
                        log.info(
                            "confirmed %s metric vanished; re-probing "
                            "without it: %s", disc.kind, e,
                        )
                        disc.vanished.add(disc.metric)
                        disc.metric = None
                    else:
                        # Transient (timeout/unavailable) — keep the
                        # confirmed name, surface the failure.
                        partial.append(f"{disc.kind} query failed: {e}")
        return rows

    def sample(self) -> HostSample:
        partial: list[str] = []
        try:
            usage = self._query(HBM_USAGE)
            total = self._query(HBM_TOTAL)
        except self._grpc.RpcError as e:
            self._reset_channel()
            raise BackendError(f"libtpu metrics RPC failed: {e.code()}") from e
        except Exception as e:  # noqa: BLE001
            self._reset_channel()
            raise BackendError(f"libtpu metrics query failed: {e}") from e

        try:
            duty = self._query(DUTY_CYCLE)
        except Exception as e:  # noqa: BLE001 — HBM without duty is degraded, not down
            duty = {}
            partial.append(f"duty-cycle query failed: {e}")

        enum_memo: list = []  # one ListSupportedMetrics shared per poll

        def get_supported():
            if not enum_memo:
                enum_memo.append(self.list_supported_metrics())
            return enum_memo[0]

        ici = self._sample_counter(self._ici_disc, partial, get_supported)
        dcn = self._sample_counter(self._dcn_disc, partial, get_supported)

        chips: list[ChipSample] = []
        # Enumerate the UNION of every response's device axis, not just the
        # usage response: a device the runtime omits from one metric but
        # serves in another must still exist (chip_info presence, the
        # series that WERE read) — vanishing silently would undercount
        # chips/hosts_reporting downstream (code-review r5). But the HBM
        # axes are authoritative: a junk key from the optional responses
        # (a mis-parsed link id, an empty attribute) must not fabricate a
        # phantom chip or flip every real chip's id scheme to positional,
        # so when the HBM devices are all-numeric, non-numeric duty/ICI
        # extras are dropped with a partial error instead of enumerated.
        devices = set(usage) | set(total)
        aux = (set(duty) | set(ici) | set(dcn)) - devices
        if "" in devices or "" in aux:
            # An attribute-less row has no device identity to publish under;
            # dropping it silently would be the same unaccounted undercount
            # as the non-numeric junk below — record it.
            partial.append("dropping metric row(s) with empty device key")
            devices.discard("")
            aux.discard("")
        if devices and all(d.isdigit() for d in devices):
            junk = sorted(d for d in aux if not d.isdigit())
            if junk:
                partial.append(
                    "ignoring non-numeric device key(s) in duty/ICI "
                    "responses: " + ",".join(junk)
                )
                aux.difference_update(junk)
        devices |= aux
        ordered = sorted(devices, key=_dev_sort_key)
        # A device absent from the usage (or total) response gets None for
        # that field (series omitted), NOT 0.0 — a zero we didn't read is a
        # lie (main.go:129-132 never exports an unread value), and a fake
        # value poisons used_percent. Both directions are partial errors.
        missing_total = [d for d in ordered if d in usage and d not in total]
        missing_usage = [d for d in ordered if d not in usage and d in total]
        if missing_total:
            partial.append(
                "HBM total missing for device(s) "
                + ",".join(missing_total)
                + " (present in usage response)"
            )
        if missing_usage:
            partial.append(
                "HBM usage missing for device(s) "
                + ",".join(missing_usage)
                + " (present in total response)"
            )
        # chip_id must be unique per chip: use the runtime's numeric device
        # ids when ALL ids are numeric (the normal case — they match the GKE
        # device-plugin ids and the /dev/accel index); otherwise fall back to
        # enumeration order for every chip so ids can never collide.
        all_numeric = all(d.isdigit() for d in ordered)
        for pos, dev_id in enumerate(ordered):
            idx = int(dev_id) if all_numeric else pos
            # Per-link rows when the runtime serves a link attribute (link
            # id order stabilized for the collector's layout fast-path); a
            # single aggregate row degrades to link="all".
            links = _links_from_rows(ici.get(dev_id))
            dcn_links = _links_from_rows(dcn.get(dev_id))
            chips.append(
                ChipSample(
                    info=ChipInfo(
                        chip_id=idx,
                        device_path=self._device_paths.get(idx, ""),
                        device_ids=(dev_id,),
                    ),
                    hbm_used_bytes=usage.get(dev_id),
                    hbm_total_bytes=total.get(dev_id),
                    tensorcore_duty_cycle_percent=duty.get(dev_id),
                    ici_links=links,
                    dcn_links=dcn_links,
                )
            )
        return HostSample(chips=tuple(chips), partial_errors=tuple(partial))

    def _reset_channel(self) -> None:
        with self._lock:
            if self._channel is not None:
                try:
                    self._channel.close()
                except Exception:  # noqa: BLE001
                    pass
            self._channel = None
            self._get = None
            self._list = None

    def close(self) -> None:
        self._reset_channel()


def _links_from_rows(rows: dict[str, float] | None) -> tuple:
    """{link id: counter} rows for one device → sorted IciLinkSample tuple
    (numeric-first order — shared by the ICI and DCN paths)."""
    if not rows:
        return ()
    return tuple(
        IciLinkSample(link=lk, transferred_bytes_total=v)
        for lk, v in sorted(rows.items(), key=_link_sort_key)
    )


def _dev_sort_key(dev_id: str):
    try:
        return (0, int(dev_id))
    except ValueError:
        return (1, dev_id)


def _link_sort_key(item: tuple[str, float]):
    return _dev_sort_key(item[0])
