"""Scripted fake device backend — the test/bench seam (SURVEY.md §4.2).

Supports:
- static chip sets (N chips with fixed capacities),
- scripted time series (each call advances a script of samples),
- fault injection: raise on the next N calls, or per-chip partial errors,
- synthetic load shapes for benchmarks (deterministic pseudo-traffic).

Zero-device operation (``FakeBackend(chips=0)``) is baseline config 1: the
exporter must come up, serve ``/metrics``, and report itself healthy with no
devices present — something the reference cannot do at all (NVML init failure
is fatal, ``main.go:45-48``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Iterable, Sequence

from tpu_pod_exporter.backend import (
    BackendError,
    ChipInfo,
    ChipSample,
    DeviceBackend,
    HostSample,
    IciLinkSample,
)

DEFAULT_HBM_TOTAL = 96 * 1024**3  # v5p-class chip: 95-96 GiB HBM  [design]


@dataclass
class FakeChipScript:
    """Per-chip scripted telemetry. Values may be scalars (constant) or
    callables of the poll index."""

    hbm_total_bytes: float = DEFAULT_HBM_TOTAL
    hbm_used_bytes: float | Callable[[int], float] = 0.0
    hbm_peak_bytes: float | Callable[[int], float] | None = None
    duty_cycle_percent: float | Callable[[int], float] | None = 0.0
    ici_link_count: int = 6  # 3D torus: ±x, ±y, ±z  [design]
    # cumulative bytes per link per poll step
    ici_bytes_per_step: float | Callable[[int], float] = 0.0
    # DCN (cross-slice fabric) links — 0 outside multi-slice shapes.
    dcn_link_count: int = 0
    dcn_bytes_per_step: float | Callable[[int], float] = 0.0

    _LINK_IDS = tuple(str(i) for i in range(16))

    def _resolve(self, v, step: int) -> float:
        return float(v(step)) if callable(v) else float(v)

    def sample(
        self, info: ChipInfo, step: int, link_cache: dict | None = None
    ) -> ChipSample:
        duty = None
        if self.duty_cycle_percent is not None:
            duty = self._resolve(self.duty_cycle_percent, step)
        per_step = self._resolve(self.ici_bytes_per_step, step)
        links = None
        if link_cache is not None:
            # Link tuples are immutable and identical for every chip sharing
            # (per-step rate, link count) — share one tuple across the host
            # instead of allocating chips × links samples per poll (the
            # fake's own construction cost must stay out of the exporter's
            # CPU budget at 256-chip bench scale).
            links = link_cache.get((per_step, self.ici_link_count))
        if links is None:
            total = per_step * (step + 1)
            ids = self._LINK_IDS
            if self.ici_link_count > len(ids):
                ids = tuple(str(i) for i in range(self.ici_link_count))
            # tuple.__new__ bypasses the generated NamedTuple __new__
            # (a Python function).
            mk = tuple.__new__
            links = tuple(
                mk(IciLinkSample, (ids[li], total))
                for li in range(self.ici_link_count)
            )
            if link_cache is not None:
                link_cache[(per_step, self.ici_link_count)] = links
        dcn_links: tuple = ()
        if self.dcn_link_count:
            dcn_total = self._resolve(self.dcn_bytes_per_step, step) * (step + 1)
            mk = tuple.__new__
            dcn_links = tuple(
                mk(IciLinkSample, (f"dcn{li}", dcn_total))
                for li in range(self.dcn_link_count)
            )
        peak = None
        if self.hbm_peak_bytes is not None:
            peak = self._resolve(self.hbm_peak_bytes, step)
        return ChipSample(
            info=info,
            hbm_used_bytes=self._resolve(self.hbm_used_bytes, step),
            hbm_total_bytes=self.hbm_total_bytes,
            tensorcore_duty_cycle_percent=duty,
            ici_links=links,
            hbm_peak_bytes=peak,
            dcn_links=dcn_links,
        )


class FakeBackend(DeviceBackend):
    name = "fake"

    def __init__(
        self,
        chips: int | Sequence[ChipInfo] = 0,
        script: FakeChipScript | Sequence[FakeChipScript] | None = None,
        device_path_fmt: str = "/dev/accel{chip_id}",
        family: str = "tpu",
    ) -> None:
        # A GPU-family fake (family="gpu") models an NVML-backed node for
        # mixed-fleet tests without the nvml module: chips publish under
        # the gpu_* namespace via ChipInfo.family, exactly like NvmlBackend.
        self.family = family
        if isinstance(chips, int):
            self._infos = tuple(
                ChipInfo(chip_id=i, device_path=device_path_fmt.format(chip_id=i),
                         family=family)
                for i in range(chips)
            )
        else:
            self._infos = tuple(chips)
        if script is None:
            scripts: list[FakeChipScript] = [FakeChipScript() for _ in self._infos]
        elif isinstance(script, FakeChipScript):
            scripts = [script for _ in self._infos]
        else:
            scripts = list(script)
            if len(scripts) != len(self._infos):
                raise ValueError("one script per chip required")
        self._scripts = scripts
        self._step = 0
        self._lock = threading.Lock()
        self._fail_next = 0
        self._partial_errors: list[str] = []
        self.sample_calls = 0
        self.closed = False

    # -- fault injection (SURVEY.md §4.5) ------------------------------------

    def fail_next(self, n: int = 1) -> None:
        """Make the next n sample() calls raise BackendError."""
        with self._lock:
            self._fail_next += n

    def set_partial_errors(self, errors: Iterable[str]) -> None:
        with self._lock:
            self._partial_errors = list(errors)

    # -- DeviceBackend -------------------------------------------------------

    def sample(self) -> HostSample:
        with self._lock:
            self.sample_calls += 1
            if self._fail_next > 0:
                self._fail_next -= 1
                raise BackendError("fake backend: injected failure")
            step = self._step
            self._step += 1
            partial = tuple(self._partial_errors)
        link_cache: dict = {}  # per-poll: shared link tuples across chips
        chips = tuple(
            script.sample(info, step, link_cache)
            for info, script in zip(self._infos, self._scripts)
        )
        return HostSample(chips=chips, partial_errors=partial)

    def close(self) -> None:
        self.closed = True


def ramping_usage(base: float, step_bytes: float, cap: float) -> Callable[[int], float]:
    """Usage that climbs by step_bytes per poll up to cap — churn/stress shapes."""

    def fn(step: int) -> float:
        return min(base + step * step_bytes, cap)

    return fn


def bench_backend(chips: int, hbm_total: float = DEFAULT_HBM_TOTAL) -> FakeBackend:
    """Deterministic non-trivial load for benchmarks: distinct per-chip values
    so the encoder can't shortcut identical strings."""
    scripts = [
        FakeChipScript(
            hbm_total_bytes=hbm_total,
            hbm_used_bytes=(lambda c: (lambda step: (c * 7919 + step * 104729) % int(hbm_total)))(c),
            duty_cycle_percent=(lambda c: (lambda step: float((c * 13 + step * 29) % 101)))(c),
            ici_bytes_per_step=1_000_000.0,
        )
        for c in range(chips)
    ]
    infos = [ChipInfo(chip_id=i, device_path=f"/dev/accel{i}") for i in range(chips)]
    return FakeBackend(chips=infos, script=scripts)
