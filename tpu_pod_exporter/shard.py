"""Sharded HA aggregation tree — leaves own consistent-hash shards, a root
merges them into one fleet-wide ``/metrics``.

Every fleet-facing layer so far (aggregator, fleet query plane, egress)
funnels through a single ``SliceAggregator`` process: one SIGKILL away from
losing the whole fleet view, and one flat target list away from a round
time that grows with the fleet. This module splits the tier in two:

- **Leaf aggregators** (:class:`LeafAggregator`) are today's
  ``SliceAggregator`` owning only one **consistent-hash shard** of the node
  targets (:class:`ShardMap`): a target join/leave moves ~1/n of
  assignments (property-tested in tests/test_shard.py), so a churn wave
  reshuffles a bounded slice of the fleet, never all of it. Per-shard
  breaker/quarantine state and the shard map itself carry across restarts
  via ``persist.py`` (``BreakerStateFile`` / :class:`~tpu_pod_exporter.\
persist.ShardMapFile`). Each leaf additionally publishes its raw rollup
  **accumulator components** (``tpu_leaf_*``, schema.LEAF_SPECS) — the
  sums/counts/coverage-flags a mean or a used-vs-total guard cannot be
  rebuilt from rolled-up numbers alone.

- **A root tier** (:class:`RootAggregator`) scrapes every leaf's
  exposition, rebuilds the fleet accumulators by summing per-shard
  components, and emits slice → pod → fleet rollups through the SAME
  ``aggregate.emit_rollups`` path the flat aggregator uses — so the root's
  fleet view cannot drift from what one flat aggregator over the same
  scrape set would publish (the shard-demo asserts them equal against
  exactly that oracle).

- **HA pair mode**: two leaves scrape the same shard; the root dedups per
  series group by **freshest poll wall timestamp** (the leaf's
  ``tpu_aggregator_last_round_timestamp_seconds``). One leaf's death loses
  zero series and at most one round of freshness; taking a STALER leaf's
  value because the freshest lacked the series is counted in
  ``tpu_root_dedup_stale_wins_total``.

- **Two-level queries**: the root's ``/api/v1`` (:class:`RootQueryPlane`)
  fans out to every leaf's federated query plane (``fleet.py``) and merges
  the envelopes — per-LEAF state surfaced alongside the per-target state
  each leaf already reports, same partial-result semantics (a dead leaf
  whose HA twin answers degrades nothing).

Run::

    python -m tpu_pod_exporter.shard --role leaf --shard-index 2 \\
        --num-shards 8 --leaf-id 2a --targets-file /etc/tpe/targets
    python -m tpu_pod_exporter.shard --role root \\
        --leaves 'shard-0=leaf0a:9100|leaf0b:9100,shard-1=leaf1a:9100'
"""

from __future__ import annotations

import argparse
import bisect
import hashlib
import logging
import os
import signal
import threading
import time
import urllib.error
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Mapping, Sequence

from tpu_pod_exporter import utils
from tpu_pod_exporter.aggregate import (
    SliceAggregator,
    TargetSet,
    default_fetch,
    emit_rollups,
    read_targets_file,
)
from tpu_pod_exporter.fleet import (
    QueryCache,
    data_shape as fleet_data_shape,
    default_api_fetch,
    rows_of as fleet_rows_of,
    target_query_url,
)
from tpu_pod_exporter.metrics import (
    CounterStore,
    HistogramStore,
    PrefixCache,
    SnapshotBuilder,
    SnapshotStore,
    schema,
)
from tpu_pod_exporter.metrics.parse import (
    LayoutCache,
    ParseError,
    parse_exposition_layout,
)
from tpu_pod_exporter.supervisor import CLOSED, CircuitBreaker
from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.shard")


# --------------------------------------------------------------------- hashing


def stable_hash64(key: str) -> int:
    """Deterministic 64-bit hash. NOT ``hash()``: that is salted per
    process (PYTHONHASHSEED), and every leaf, the root, and a restarted
    process must all place the same key at the same ring position."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


def default_shards(n: int) -> tuple[str, ...]:
    """Canonical shard ids for an n-shard tree: ``shard-0`` … ``shard-n-1``.
    Every tier derives them from ``--num-shards`` alone, so leaves and root
    agree on the ring without exchanging configuration."""
    if n <= 0:
        raise ValueError("need at least one shard")
    return tuple(f"shard-{i}" for i in range(n))


class ShardMap:
    """Consistent-hash ring assigning node targets to shards.

    Each shard owns ``vnodes`` pseudo-random ring positions; a target maps
    to the first shard clockwise from its own hash. Properties (tested):

    - **stability** — same (shards, vnodes, target) → same assignment, in
      every process, on every run;
    - **target churn is local** — a target joining or leaving moves ONLY
      its own assignment (targets hash independently), so a k-target churn
      wave costs exactly k moves;
    - **shard churn is bounded** — adding/removing one shard of n moves
      about targets/n assignments (the removed shard's arcs), never a full
      reshuffle.
    """

    def __init__(self, shards: Sequence[str], vnodes: int = 64) -> None:
        uniq = tuple(dict.fromkeys(s for s in shards if s))
        if not uniq:
            raise ValueError("shard map needs at least one shard")
        if vnodes <= 0:
            raise ValueError("vnodes must be positive")
        self.shards = uniq
        self.vnodes = vnodes
        ring: list[tuple[int, str]] = []
        for shard in uniq:
            for v in range(vnodes):
                ring.append((stable_hash64(f"{shard}#{v}"), shard))
        ring.sort()
        self._ring_keys = [h for h, _ in ring]
        self._ring_shards = [s for _, s in ring]

    def assign(self, target: str) -> str:
        i = bisect.bisect_right(self._ring_keys, stable_hash64(target))
        if i == len(self._ring_keys):
            i = 0
        return self._ring_shards[i]

    def assignments(self, targets: Iterable[str]) -> dict[str, str]:
        return {t: self.assign(t) for t in targets}

    def to_doc(self) -> dict[str, object]:
        return {"shards": list(self.shards), "vnodes": self.vnodes}

    @classmethod
    def from_doc(cls, doc: Mapping[str, object]) -> "ShardMap":
        shards = doc.get("shards")
        vnodes = doc.get("vnodes", 64)
        if not isinstance(shards, list) or not isinstance(vnodes, int):
            raise ValueError("bad shard map document")
        return cls([str(s) for s in shards], vnodes=vnodes)


def count_moves(old: Mapping[str, str], new: Mapping[str, str]) -> int:
    """Assignment delta between two target→shard maps: targets added,
    removed, or moved to a different shard — the ``reshard_moves``
    quantity the churn budget bounds."""
    moves = 0
    for t, s in new.items():
        prev = old.get(t)
        if prev is None or prev != s:
            moves += 1
    for t in old:
        if t not in new:
            moves += 1
    return moves


# -------------------------------------------------------------- leaf tier


def _slice_fields(agg: Any) -> dict[str, float]:
    """One slice accumulator → the field map ``tpu_leaf_slice_component``
    carries (ordering/naming contract: schema.LEAF_SLICE_FIELDS)."""
    return {
        "hosts": float(agg.hosts_n),
        "chips": float(agg.chips),
        "hbm_used": float(agg.hbm_used),
        "hbm_total": float(agg.hbm_total),
        "used_n": float(agg.used_n),
        "total_n": float(agg.total_n),
        "coverage_eq": 1.0 if agg.coverage_eq else 0.0,
        "duty_sum": float(agg.duty_sum),
        "duty_n": float(agg.duty_n),
        "ici_bw": float(agg.ici_bw),
        "ici_n": float(agg.ici_n),
        "dcn_bw": float(agg.dcn_bw),
        "dcn_n": float(agg.dcn_n),
    }


def _workload_fields(w: Any) -> dict[str, float]:
    return {
        "chips": float(w.chips),
        "hbm_used": float(w.hbm_used),
        "hbm_used_n": float(w.hbm_used_n),
        "hosts": float(w.hosts_n),
    }


class LeafAggregator(SliceAggregator):
    """A :class:`SliceAggregator` owning one consistent-hash shard.

    Everything the flat aggregator does — scrape pool, per-target
    breakers/quarantine, history fallback, tracing, breaker persistence,
    fleet query plane — works unchanged; this subclass only (1) cuts
    membership to its shard via the TargetSet's ``target_filter`` (live:
    a targets-file reload re-applies the hash cut, so targets reshard in
    and out without a restart), (2) publishes the ``tpu_leaf_*``
    component surface the root merges, and (3) persists the shard map +
    assignment view so a restart counts real reshard moves instead of
    re-learning the world as churn.
    """

    def __init__(
        self,
        shard_id: str,
        leaf_id: str,
        shard_map: ShardMap,
        shard_map_store: Any = None,  # persist.ShardMapFile | None
        **kwargs: Any,
    ) -> None:
        if shard_id not in shard_map.shards:
            raise ValueError(
                f"shard {shard_id!r} not in shard map {shard_map.shards}"
            )
        self.shard_id = shard_id
        self.leaf_id = leaf_id
        self._shard_map = shard_map
        self._shard_map_store = shard_map_store
        kwargs["target_filter"] = self._shard_filter
        kwargs.setdefault("targets", ())
        super().__init__(**kwargs)
        if shard_map_store is not None:
            saved = shard_map_store.load()
            self._restore_shard_state(saved)
        self._saved_moves = self._tset.moves
        self._persist_shard_map()

    def _shard_filter(self, targets: Sequence[str]) -> tuple[str, ...]:
        """The consistent-hash cut: of the global target list, keep what
        hashes to this leaf's shard (order preserved)."""
        return tuple(
            t for t in targets if self._shard_map.assign(t) == self.shard_id
        )

    def _restore_shard_state(self, saved: Mapping[str, object]) -> None:
        """Boot-time carryover: restore the cumulative reshard counter and
        count the restart's real assignment delta (targets that joined or
        left the shard while we were down) as moves, not as a cold start.
        A changed ring (different shard set/vnodes) is logged loudly —
        everything is expected to move then."""
        ring = saved.get("ring")
        if isinstance(ring, dict) and ring != self._shard_map.to_doc():
            log.warning(
                "shard ring changed across restart (%s -> %s): assignment "
                "moves below reflect a topology change, not target churn",
                ring, self._shard_map.to_doc(),
            )
        # Restore the cumulative counter; the boot population itself is
        # never counted as churn (mirrors TargetSet's own boot behaviour).
        moves = saved.get("moves")
        if isinstance(moves, (int, float)):
            self._tset.moves = int(moves)
        else:
            self._tset.moves = 0
        prev = saved.get("assigned")
        if isinstance(prev, list):
            prev_set = {str(t) for t in prev}
            cur_set = set(self._tset.targets)
            delta = len(prev_set - cur_set) + len(cur_set - prev_set)
            self._tset.moves += delta
            if delta:
                log.info(
                    "shard %s membership moved %d target(s) across the "
                    "restart (now %d)", self.shard_id, delta, len(cur_set),
                )

    def _persist_shard_map(self) -> None:
        if self._shard_map_store is None:
            return
        self._shard_map_store.save({
            "ring": self._shard_map.to_doc(),
            "shard": self.shard_id,
            "leaf": self.leaf_id,
            "assigned": list(self._tset.targets),
            "moves": self._tset.moves,
        })

    def poll_once(self) -> None:
        super().poll_once()
        # Persist the assignment view only when it changed (a reshard is
        # a handful of saves per churn event, not one per round).
        if self._tset.moves != self._saved_moves:
            self._saved_moves = self._tset.moves
            self._persist_shard_map()

    def _emit_extra(self, b: SnapshotBuilder, slices: Mapping[Any, Any],
                    workloads: Mapping[Any, Any],
                    slice_groups: Mapping[Any, Any]) -> None:
        """The tier-to-tier contract: raw accumulator components + shard
        identity, appended to the same exposition the public rollups ride
        (a leaf stays directly scrapeable as an ordinary aggregator)."""
        for spec in schema.LEAF_SPECS:
            b.declare(spec)
        b.add(schema.TPU_LEAF_SHARD_INFO, 1.0,
              (self.shard_id, self.leaf_id,
               str(len(self._shard_map.shards)),
               str(self._shard_map.vnodes)))
        b.add(schema.TPU_LEAF_TARGETS, float(len(self._tset.targets)),
              (self.shard_id,))
        b.add(schema.TPU_LEAF_RESHARD_MOVES_TOTAL, float(self._tset.moves))
        for key, agg in slices.items():
            for fname, value in _slice_fields(agg).items():
                b.add(schema.TPU_LEAF_SLICE_COMPONENT, value,
                      tuple(key) + (fname,))
        for wkey, w in workloads.items():
            for fname, value in _workload_fields(w).items():
                b.add(schema.TPU_LEAF_WORKLOAD_COMPONENT, value,
                      tuple(wkey) + (fname,))
        for skey, membership in slice_groups.items():
            group, nslices = membership
            b.add(schema.TPU_LEAF_SLICE_GROUP_INFO, 1.0,
                  tuple(skey) + (group, nslices))

    def debug_vars(self) -> dict:
        out = super().debug_vars()
        out["shard"] = {
            "shard_id": self.shard_id,
            "leaf_id": self.leaf_id,
            "ring": self._shard_map.to_doc(),
            "reshard_moves": self._tset.moves,
        }
        return out


# -------------------------------------------------------------- root tier


# What the root folds out of a leaf body — everything else in the leaf's
# exposition (its public rollups included) is skipped before label parsing,
# same fast-path reasoning as aggregate.CONSUMED_NAMES.
ROOT_CONSUMED: frozenset[str] = frozenset({
    schema.TPU_LEAF_SLICE_COMPONENT.name,
    schema.TPU_LEAF_WORKLOAD_COMPONENT.name,
    schema.TPU_LEAF_SLICE_GROUP_INFO.name,
    schema.TPU_LEAF_SHARD_INFO.name,
    schema.TPU_LEAF_TARGETS.name,
    schema.TPU_AGG_TARGET_UP.name,
    schema.TPU_AGG_TARGET_BREAKER_STATE.name,
    schema.TPU_AGG_LAST_ROUND_TIMESTAMP_SECONDS.name,
})


@dataclass
class SliceStats:
    """Additive slice accumulator rebuilt from ``tpu_leaf_slice_component``
    series. Exposes the same count/flag surface ``aggregate._SliceAgg``
    does, so ``aggregate.emit_rollups`` treats both identically."""

    hosts_n: int = 0
    chips: float = 0.0
    hbm_used: float = 0.0
    hbm_total: float = 0.0
    used_n: int = 0
    total_n: int = 0
    coverage_eq: bool = True
    duty_sum: float = 0.0
    duty_n: int = 0
    ici_bw: float = 0.0
    ici_n: int = 0
    dcn_bw: float = 0.0
    dcn_n: int = 0

    def orphan_hosts(self) -> set[str]:
        """Always empty at the root: the leaf that saw the orphan warned."""
        return set()

    @classmethod
    def from_fields(cls, fields: Mapping[str, float]) -> "SliceStats":
        return cls(
            hosts_n=int(fields.get("hosts", 0.0)),
            chips=fields.get("chips", 0.0),
            hbm_used=fields.get("hbm_used", 0.0),
            hbm_total=fields.get("hbm_total", 0.0),
            used_n=int(fields.get("used_n", 0.0)),
            total_n=int(fields.get("total_n", 0.0)),
            coverage_eq=fields.get("coverage_eq", 1.0) != 0.0,
            duty_sum=fields.get("duty_sum", 0.0),
            duty_n=int(fields.get("duty_n", 0.0)),
            ici_bw=fields.get("ici_bw", 0.0),
            ici_n=int(fields.get("ici_n", 0.0)),
            dcn_bw=fields.get("dcn_bw", 0.0),
            dcn_n=int(fields.get("dcn_n", 0.0)),
        )

    def merge(self, other: "SliceStats") -> None:
        """Fold another shard's partial accumulator in. Sums everywhere;
        coverage is the AND over shards — hosts partition by shard, so
        per-shard used==total (as sets) implies the union equality the
        flat aggregator's percent guard checks."""
        self.hosts_n += other.hosts_n
        self.chips += other.chips
        self.hbm_used += other.hbm_used
        self.hbm_total += other.hbm_total
        self.used_n += other.used_n
        self.total_n += other.total_n
        self.coverage_eq = self.coverage_eq and other.coverage_eq
        self.duty_sum += other.duty_sum
        self.duty_n += other.duty_n
        self.ici_bw += other.ici_bw
        self.ici_n += other.ici_n
        self.dcn_bw += other.dcn_bw
        self.dcn_n += other.dcn_n


@dataclass
class WorkloadStats:
    """Additive workload accumulator (root-side twin of ``_WorkloadAgg``)."""

    chips: float = 0.0
    hbm_used: float = 0.0
    hbm_used_n: int = 0
    hosts_n: int = 0

    @classmethod
    def from_fields(cls, fields: Mapping[str, float]) -> "WorkloadStats":
        return cls(
            chips=fields.get("chips", 0.0),
            hbm_used=fields.get("hbm_used", 0.0),
            hbm_used_n=int(fields.get("hbm_used_n", 0.0)),
            hosts_n=int(fields.get("hosts", 0.0)),
        )

    def merge(self, other: "WorkloadStats") -> None:
        self.chips += other.chips
        self.hbm_used += other.hbm_used
        self.hbm_used_n += other.hbm_used_n
        self.hosts_n += other.hosts_n


@dataclass
class LeafView:
    """One leaf body, folded: everything the root merges, plus the round
    wall timestamp the freshest-wins dedup keys on."""

    leaf: str
    round_ts: float = 0.0
    # (slice_name, accelerator, family) -> field map
    slice_fields: dict[tuple[str, str, str], dict[str, float]] = field(
        default_factory=dict)
    workload_fields: dict[tuple[str, str, str], dict[str, float]] = field(
        default_factory=dict)
    group_info: dict[tuple[str, str], tuple[str, str]] = field(
        default_factory=dict)
    target_up: dict[str, float] = field(default_factory=dict)
    target_breaker: dict[str, float] = field(default_factory=dict)
    targets_gauge: float | None = None
    shard_claim: tuple[str, str] | None = None  # (shard, leaf) from the body
    ring_claim: tuple[str, str] | None = None   # (num_shards, vnodes)


def fold_leaf_body(leaf: str, samples: Iterable[tuple]) -> LeafView:
    """Parsed ``(name, labels, value)`` tuples → :class:`LeafView`."""
    view = LeafView(leaf=leaf)
    for name, labels, value in samples:
        if name == schema.TPU_LEAF_SLICE_COMPONENT.name:
            fname = labels.get("field", "")
            if fname not in schema.LEAF_SLICE_FIELDS:
                continue  # newer leaf: unknown components are ignored
            # family defaults to "tpu" so a pre-GPU leaf's components
            # merge unchanged (missing label = the only family there was).
            key = (labels.get("slice_name", ""), labels.get("accelerator", ""),
                   labels.get("family", "tpu"))
            view.slice_fields.setdefault(key, {})[fname] = value
        elif name == schema.TPU_LEAF_WORKLOAD_COMPONENT.name:
            fname = labels.get("field", "")
            if fname not in schema.LEAF_WORKLOAD_FIELDS:
                continue
            wkey = (labels.get("pod", ""), labels.get("namespace", ""),
                    labels.get("slice_name", ""))
            view.workload_fields.setdefault(wkey, {})[fname] = value
        elif name == schema.TPU_AGG_TARGET_UP.name:
            target = labels.get("target", "")
            if target:
                view.target_up[target] = value
        elif name == schema.TPU_AGG_TARGET_BREAKER_STATE.name:
            target = labels.get("target", "")
            if target:
                view.target_breaker[target] = value
        elif name == schema.TPU_LEAF_SLICE_GROUP_INFO.name:
            key = (labels.get("slice_name", ""), labels.get("accelerator", ""))
            view.group_info[key] = (
                labels.get("multislice_group", ""),
                labels.get("num_slices", ""),
            )
        elif name == schema.TPU_AGG_LAST_ROUND_TIMESTAMP_SECONDS.name:
            view.round_ts = value
        elif name == schema.TPU_LEAF_TARGETS.name:
            view.targets_gauge = value
        elif name == schema.TPU_LEAF_SHARD_INFO.name:
            view.shard_claim = (labels.get("shard", ""),
                                labels.get("leaf", ""))
            if "num_shards" in labels:
                view.ring_claim = (labels.get("num_shards", ""),
                                   labels.get("vnodes", ""))
    return view


@dataclass
class ShardMerged:
    """One shard after HA dedup: per-series-group winners plus dedup
    bookkeeping."""

    slices: dict[tuple[str, str, str], SliceStats] = field(
        default_factory=dict)
    workloads: dict[tuple[str, str, str], WorkloadStats] = field(
        default_factory=dict)
    group_info: dict[tuple[str, str], tuple[str, str]] = field(
        default_factory=dict)
    # target -> (value, source round_ts): the ts rides along so a target
    # briefly visible from two shards mid-reshard resolves freshest-wins
    # at the fleet fold too.
    target_up: dict[str, tuple[float, float]] = field(default_factory=dict)
    target_breaker: dict[str, tuple[float, float]] = field(
        default_factory=dict)
    targets_gauge: float | None = None
    stale_wins: int = 0


def merge_shard_views(views: Sequence[LeafView]) -> ShardMerged:
    """HA dedup for one shard: for every series group (a slice's component
    set, a workload's, one target's up/breaker…) take the value from the
    FRESHEST answering leaf that carries it — per series, by poll wall
    timestamp, exactly the freshest-wins contract. A group served only by
    a staler leaf (the freshest is mid-warmup after a restart) still
    lands — that is the zero-series-loss half — and is counted as a stale
    win."""
    out = ShardMerged()
    if not views:
        return out
    ordered = sorted(views, key=lambda v: v.round_ts, reverse=True)

    def pick(present: Callable[[LeafView], bool]) -> LeafView | None:
        for i, v in enumerate(ordered):
            if present(v):
                if i > 0:
                    out.stale_wins += 1
                return v
        return None

    skeys = {k for v in ordered for k in v.slice_fields}
    for key in skeys:
        win = pick(lambda v, k=key: k in v.slice_fields)
        if win is not None:
            out.slices[key] = SliceStats.from_fields(win.slice_fields[key])
    wkeys = {k for v in ordered for k in v.workload_fields}
    for wkey in wkeys:
        win = pick(lambda v, k=wkey: k in v.workload_fields)
        if win is not None:
            out.workloads[wkey] = WorkloadStats.from_fields(
                win.workload_fields[wkey])
    gkeys = {k for v in ordered for k in v.group_info}
    for gkey in gkeys:
        win = pick(lambda v, k=gkey: k in v.group_info)
        if win is not None:
            out.group_info[gkey] = win.group_info[gkey]
    tkeys = {t for v in ordered for t in v.target_up}
    for t in tkeys:
        win = pick(lambda v, k=t: k in v.target_up)
        if win is not None:
            out.target_up[t] = (win.target_up[t], win.round_ts)
    bkeys = {t for v in ordered for t in v.target_breaker}
    for t in bkeys:
        win = pick(lambda v, k=t: k in v.target_breaker)
        if win is not None:
            out.target_breaker[t] = (win.target_breaker[t], win.round_ts)
    for v in ordered:
        if v.targets_gauge is not None:
            out.targets_gauge = v.targets_gauge
            break
    return out


def parse_leaf_topology(spec: str) -> dict[str, tuple[str, ...]]:
    """``--leaves`` grammar: ``shard-0=addrA|addrB,shard-1=addrC`` →
    {shard: (leaf addrs…)}. Two addrs = an HA pair. Raises ValueError
    loudly on malformed entries — a typo'd topology must fail at startup,
    not silently drop a shard from the fleet view."""
    topo: dict[str, tuple[str, ...]] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        shard, sep, addrs = raw.partition("=")
        shard = shard.strip()
        if not sep or not shard:
            raise ValueError(
                f"leaf topology entry {raw!r}: want shard=addr[|addr]"
            )
        leaf_addrs = tuple(
            dict.fromkeys(a.strip() for a in addrs.split("|") if a.strip())
        )
        if not leaf_addrs:
            raise ValueError(f"leaf topology entry {raw!r}: no leaf address")
        if shard in topo:
            raise ValueError(f"leaf topology: duplicate shard {shard!r}")
        topo[shard] = leaf_addrs
    if not topo:
        raise ValueError(f"leaf topology {spec!r} contains no shards")
    return topo


class RootAggregator:
    """Scrape every leaf, dedup HA pairs freshest-wins, publish the
    fleet-wide rollups plus the per-target series the leaves own.

    An observer of leaves exactly the way the leaves observe exporters:
    public exposition over HTTP, per-leaf circuit breakers quarantining a
    persistently-dead leaf (its HA twin keeps the shard covered), layout
    caches for value-only re-parse. Drives on the same
    ``CollectorLoop``/``poll_once`` contract as every other tier.
    """

    def __init__(
        self,
        topology: Mapping[str, Sequence[str]],
        store: SnapshotStore,
        timeout_s: float = 2.0,
        fetch: Callable[..., str] = default_fetch,
        wallclock: Callable[[], float] = time.time,
        breaker_failures: int = 3,
        breaker_backoff_s: float = 10.0,
        breaker_backoff_max_s: float = 120.0,
        loop_overruns_fn: Callable[[], int] | None = None,
        targets_file: str = "",
        shard_map: ShardMap | None = None,
        shard_map_store: Any = None,  # persist.ShardMapFile | None
        breaker_store: Any = None,  # persist.BreakerStateFile | None
        stale_serve_s: float = 0.0,
        fleet_store: Any = None,  # store.FleetStore | None
        alert_evaluator: Any = None,  # alerting.AlertEvaluator | None
        render_splice: bool = True,  # --render-splice; RUNBOOK kill switch
    ) -> None:
        if not topology:
            raise ValueError("root needs at least one shard of leaves")
        self.topology = {s: tuple(ls) for s, ls in topology.items()}
        self._leaves = tuple(
            leaf for leaves in self.topology.values() for leaf in leaves
        )
        if len(set(self._leaves)) != len(self._leaves):
            raise ValueError("a leaf address appears in two shards")
        self._shard_of = {
            leaf: shard
            for shard, leaves in self.topology.items()
            for leaf in leaves
        }
        self.rounds = 0
        self._store = store
        self._timeout_s = timeout_s
        self._fetch = fetch
        self._wallclock = wallclock
        # Splice render across rounds (see SliceAggregator): the root's
        # merged exposition re-renders only changed cells per round. Same
        # kill switch as the other tiers (--render-splice false).
        self._prefix_cache = PrefixCache(splice=render_splice)
        self._rlog = RateLimitedLogger(log)
        self._counters = CounterStore()
        # Stable conditional surface: both counters exist from round 1.
        self._counters.inc(schema.TPU_ROOT_DEDUP_STALE_WINS_TOTAL.name, (),
                           0.0)
        self._counters.inc(schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name, (), 0.0)
        self._round_hist = HistogramStore(schema.TPU_ROOT_ROUND_HIST)
        self._loop_overruns_fn = loop_overruns_fn
        # Per-LEAF state rides the same TargetSet the leaf tier uses for
        # its node targets (static membership here): one construction
        # path for breakers + layout caches, not a hand-rolled twin.
        self._leaf_set = TargetSet(
            self._leaves,
            breaker_failures=breaker_failures,
            breaker_backoff_s=breaker_backoff_s,
            breaker_backoff_max_s=breaker_backoff_max_s,
            breaker_store=breaker_store,
            wallclock=wallclock,
        )
        self._layouts: dict[str, LayoutCache] = self._leaf_set.layouts
        self._breakers: dict[str, CircuitBreaker] | None = (
            self._leaf_set.breakers
        )
        # Last seen round ts per leaf: a dead leaf's staleness keeps
        # GROWING (published from here), instead of vanishing with its body.
        self._leaf_ts: dict[str, float] = {}
        # Partition tolerance (the scenario drills' hardening): keep each
        # leaf's last successfully-folded view for up to stale_serve_s and
        # MERGE it while the leaf is unreachable — the fleet view degrades
        # to stale-but-labeled (leaf_up=0, staleness growing,
        # tpu_root_leaf_stale_served=1) instead of vanishing, and because
        # the cached view's round_ts is frozen, the HA freshest-wins
        # winner cannot flap while a flapping cut strobes reachability.
        # 0 disables (a vanished leaf's series drop out immediately, the
        # pre-hardening behavior the both-leaves-dead tests pin for the
        # disabled case).
        self._stale_serve_s = stale_serve_s
        # Fleet TSDB-lite (tpu_pod_exporter.store): after each round's
        # publish, the merged rollups + per-target series append into the
        # store's downsample tiers, and the tpu_root_store_* surface rides
        # this root's exposition. Owned here for lifecycle (close()).
        self._fleet_store = fleet_store
        # Native alerting plane (tpu_pod_exporter.alerting): evaluated
        # each round against the just-published snapshot, AFTER the store
        # append (alerts may reference recording-rule outputs the same
        # round computed). Owned here for lifecycle (close()).
        self.alert_evaluator = alert_evaluator
        self._last_views: dict[str, tuple[LeafView, float]] = {}
        # Last round's health summary, read by ready_detail() from HTTP
        # threads (swapped atomically as a tuple).
        self._health: tuple[int, int, int, tuple[str, ...]] = (
            0, len(self._leaves), 0, ())
        # Reshard accounting: the root re-derives the global assignment
        # map from the same targets file the leaves read and counts the
        # delta per reload — the fleet-level churn signal
        # (tpu_root_reshard_moves_total) alerts key off.
        self._targets_file = targets_file
        self._targets_file_mtime: float | None = None
        self._shard_map = shard_map
        self._shard_map_store = shard_map_store
        self._assignments: dict[str, str] = {}
        if shard_map_store is not None:
            saved = shard_map_store.load()
            assigned = saved.get("assignments")
            if isinstance(assigned, dict):
                self._assignments = {
                    str(k): str(v) for k, v in assigned.items()
                }
            moves = saved.get("moves")
            if isinstance(moves, (int, float)) and moves > 0:
                self._counters.inc(
                    schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name, (),
                    float(moves),
                )
        self._pool = ThreadPoolExecutor(
            max_workers=min(max(len(self._leaves), 1), 16),
            thread_name_prefix="tpu-root-scrape",
        )
        # Attachment seams (same contract as SliceAggregator's): emit
        # hooks ride _publish's SnapshotBuilder (stream-hub/replica
        # surfaces), round hooks fire at the end of poll_once with the
        # new round number (poll-side cost must stay trivial).
        self.emit_hooks: list[Callable[[SnapshotBuilder], None]] = []
        self.round_hooks: list[Callable[[int], None]] = []

    # ------------------------------------------------------------------ round

    def _refresh_assignments(self) -> None:
        """Recompute target→shard assignments when the targets file moved;
        count the delta as reshard moves and persist the view."""
        if not self._targets_file or self._shard_map is None:
            return
        try:
            mtime = os.path.getmtime(self._targets_file)
        except OSError:
            return
        if self._targets_file_mtime == mtime:
            return
        try:
            targets = read_targets_file(self._targets_file)
        except OSError as e:
            self._rlog.warning("targets_file",
                               "targets file unreadable on reload: %s", e)
            return
        self._targets_file_mtime = mtime
        if not targets and self._assignments:
            # Same torn-write guard as TargetSet.refresh: a readable-but-
            # empty file is overwhelmingly a truncate-then-write edit in
            # flight. Applying it would count the whole fleet as moves
            # (firing TpuRootReshardStorm on a non-event) and persist an
            # empty assignment view; keep the last one instead.
            self._rlog.warning(
                "targets_file",
                "targets file read EMPTY on reload; keeping the last "
                "%d assignments (truncated mid-write?)",
                len(self._assignments),
            )
            return
        new = self._shard_map.assignments(targets)
        if self._assignments:
            moves = count_moves(self._assignments, new)
        else:
            moves = 0  # first read is a boot population, not churn
        if moves:
            self._counters.inc(schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name, (),
                               float(moves))
            log.info("reshard: %d assignment move(s) across %d target(s)",
                     moves, len(new))
        changed = new != self._assignments
        self._assignments = new
        if changed and self._shard_map_store is not None:
            try:
                self._shard_map_store.save({
                    "ring": self._shard_map.to_doc(),
                    "assignments": self._assignments,
                    "moves": self._counters.inc(
                        schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name, (), 0.0),
                })
            except Exception as e:  # noqa: BLE001 — persistence must not fail rounds
                self._rlog.warning("shard_map_save",
                                   "shard map save failed: %s", e)

    def _scrape_leaf(self, leaf: str) -> tuple[str, LeafView | None, float]:
        t0 = time.monotonic()
        br = self._breakers.get(leaf) if self._breakers else None
        if br is not None and br.decide() == "skip":
            return leaf, None, 0.0
        try:
            text = self._fetch(leaf, self._timeout_s)
        except Exception as e:  # noqa: BLE001 — a down leaf is data, not death
            self._rlog.warning(f"leaf:{leaf}", "leaf scrape of %s failed: %s",
                               leaf, e)
            if br is not None:
                br.record_failure()
            return leaf, None, time.monotonic() - t0
        try:
            samples = parse_exposition_layout(
                text, ROOT_CONSUMED, self._layouts[leaf]
            )
        except ParseError as e:
            self._rlog.warning(f"parse:{leaf}",
                               "bad exposition from leaf %s: %s", leaf, e)
            if br is not None:
                br.record_failure()
            return leaf, None, time.monotonic() - t0
        if br is not None:
            if br.consecutive_failures or br.state != CLOSED:
                self._rlog.recovery(
                    f"leaf:{leaf}",
                    "leaf %s healthy again after %d failed scrape(s)",
                    leaf, br.consecutive_failures,
                )
            br.record_success()
        view = fold_leaf_body(leaf, samples)
        expect = self._shard_of[leaf]
        if view.shard_claim is not None and view.shard_claim[0] != expect:
            # Mis-wired topology: a leaf serving a different shard than
            # the root expects would silently double one shard and drop
            # another. Refuse its data, keep the round.
            self._rlog.warning(
                f"claim:{leaf}",
                "leaf %s claims shard %s but topology says %s — ignoring "
                "its body (fix --leaves or the leaf's --shard-index)",
                leaf, view.shard_claim[0], expect,
            )
            return leaf, None, time.monotonic() - t0
        if (
            self._shard_map is not None
            and view.ring_claim is not None
            and view.ring_claim != (str(len(self._shard_map.shards)),
                                    str(self._shard_map.vnodes))
        ):
            # Same shard id, DIFFERENT ring (mid-resize skew: one leaf
            # restarted with a new --num-shards): its hash cut covers a
            # different target subset, and summing it would double-count
            # targets its true owners also scrape while dropping others.
            self._rlog.warning(
                f"ring:{leaf}",
                "leaf %s hashes with ring %s but the root uses %s/%s — "
                "ignoring its body until the tier agrees on --num-shards",
                leaf, view.ring_claim, len(self._shard_map.shards),
                self._shard_map.vnodes,
            )
            return leaf, None, time.monotonic() - t0
        return leaf, view, time.monotonic() - t0

    def poll_once(self) -> None:
        t0 = time.monotonic()
        self.rounds += 1
        self._refresh_assignments()
        results = list(self._pool.map(self._scrape_leaf, self._leaves))
        views: dict[str, LeafView] = {
            leaf: view for leaf, view, _d in results if view is not None
        }
        reachable = frozenset(views)
        now_wall = self._wallclock()
        for leaf, view in views.items():
            self._leaf_ts[leaf] = view.round_ts
            self._last_views[leaf] = (view, now_wall)
        # Stale-serve: an unreachable leaf's last-known view keeps its
        # shard populated for up to stale_serve_s. The cached view joins
        # the merge with its ORIGINAL round_ts, so a reachable twin (being
        # fresher) wins every shared group and the cache only fills what
        # nothing fresher carries — zero series lost, no winner flap.
        stale_served: set[str] = set()
        if self._stale_serve_s > 0:
            for leaf in self._leaves:
                if leaf in views:
                    continue
                cached = self._last_views.get(leaf)
                if cached is not None and (
                        now_wall - cached[1] <= self._stale_serve_s):
                    views[leaf] = cached[0]
                    stale_served.add(leaf)
        # Partition suspicion: one-sided unreachability — the leaf was
        # healthy moments ago (we are stale-serving its view) while its
        # HA twin still answers. A DEAD leaf trips its own liveness probe
        # and restarts; persistent one-sided cut is a partition shape.
        suspected: set[str] = set()
        for shard, leaves in self.topology.items():
            if any(leaf in reachable for leaf in leaves):
                suspected.update(
                    leaf for leaf in leaves if leaf in stale_served
                )
        merged: dict[str, ShardMerged] = {}
        stale_wins = 0
        for shard, leaves in self.topology.items():
            sm = merge_shard_views(
                [views[leaf] for leaf in leaves if leaf in views]
            )
            stale_wins += sm.stale_wins
            merged[shard] = sm
        if stale_wins:
            self._counters.inc(schema.TPU_ROOT_DEDUP_STALE_WINS_TOTAL.name,
                               (), float(stale_wins))
        self._health = (
            len(reachable), len(self._leaves), len(stale_served),
            tuple(sorted(suspected)),
        )
        self._publish(results, views, merged, now_wall, t0,
                      stale_served=stale_served, suspected=suspected)
        # AFTER publish, same discipline as the leaf tier: disk latency
        # during a leaf incident must not read as round time.
        self._leaf_set.maybe_save_breakers()
        if self._fleet_store is not None:
            # Also after publish: the store folds the just-published
            # snapshot (tracked rollups + per-target series + recording
            # rules) into its tiers; its WAL write rides the round thread
            # but never the published round duration, and a store failure
            # can never fail a round.
            try:
                self._fleet_store.append_snapshot(
                    self._store.current(), now_wall=now_wall)
            except Exception as e:  # noqa: BLE001 — history must not break merging
                self._rlog.warning("fleet_store",
                                   "fleet store append failed: %s", e)
        if self.alert_evaluator is not None:
            # Same seat, same rule: rides the round thread (the
            # evaluator's single-caller contract) but never fails a
            # round — a broken rule degrades /readyz detail, not merging.
            try:
                self.alert_evaluator.evaluate_round(
                    self._store.current(), now_wall=now_wall)
            except Exception as e:  # noqa: BLE001 — alerting must not break merging
                self._rlog.warning("alerting",
                                   "alert evaluation failed: %s", e)
        for hook in self.round_hooks:
            try:
                hook(self.rounds)
            except Exception as e:  # noqa: BLE001 — a hook must never fail a round
                self._rlog.warning("round_hook",
                                   "round hook failed: %s", e)

    def _publish(
        self,
        results: Sequence[tuple[str, LeafView | None, float]],
        views: Mapping[str, LeafView],
        merged: Mapping[str, ShardMerged],
        now_wall: float,
        round_started: float,
        stale_served: set[str] | None = None,
        suspected: set[str] | None = None,
    ) -> None:
        stale_served = stale_served or set()
        suspected = suspected or set()
        b = SnapshotBuilder(prefix_cache=self._prefix_cache)
        # Stable surface: fleet rollups + per-target passthrough + root
        # self-metrics, declared every round whether or not sampled.
        for spec in schema.AGGREGATE_SPECS:
            b.declare(spec)
        for spec in schema.ROOT_SPECS:
            b.declare(spec)

        # Fleet fold: sum per-shard accumulators, then the ONE emit path.
        fleet_slices: dict[tuple[str, str, str], SliceStats] = {}
        fleet_workloads: dict[tuple[str, str, str], WorkloadStats] = {}
        fleet_groups: dict[tuple[str, str], tuple[str, str]] = {}
        target_up: dict[str, tuple[float, float]] = {}
        target_breaker: dict[str, tuple[float, float]] = {}
        for shard, sm in merged.items():
            for key, stats in sm.slices.items():
                cur = fleet_slices.get(key)
                if cur is None:
                    # A copy, not the shard's object: merge() mutates in
                    # place, and aliasing the fleet fold to a ShardMerged
                    # view would corrupt that view for any later reader.
                    fleet_slices[key] = replace(stats)
                else:
                    cur.merge(stats)
            for wkey, wstats in sm.workloads.items():
                wcur = fleet_workloads.get(wkey)
                if wcur is None:
                    fleet_workloads[wkey] = replace(wstats)
                else:
                    wcur.merge(wstats)
            fleet_groups.update(sm.group_info)
            # Mid-reshard a target can transiently appear under two
            # shards: freshest source wins, same contract as HA dedup.
            for t, (v, ts) in sm.target_up.items():
                if t not in target_up or ts > target_up[t][1]:
                    target_up[t] = (v, ts)
            for t, (v, ts) in sm.target_breaker.items():
                if t not in target_breaker or ts > target_breaker[t][1]:
                    target_breaker[t] = (v, ts)
        emit_rollups(b, fleet_slices, fleet_workloads, fleet_groups,
                     rlog=self._rlog)
        for t in sorted(target_up):
            b.add(schema.TPU_AGG_TARGET_UP, target_up[t][0], (t,))
        for t in sorted(target_breaker):
            b.add(schema.TPU_AGG_TARGET_BREAKER_STATE,
                  target_breaker[t][0], (t,))

        # Root self-surface: per-leaf health + per-shard occupancy.
        for leaf, view, _dur in results:
            shard = self._shard_of[leaf]
            # up reflects REACHABILITY this round — a stale-served leaf is
            # still down (stale-serve is labeled continuity, not health).
            b.add(schema.TPU_ROOT_LEAF_UP,
                  1.0 if view is not None else 0.0, (shard, leaf))
            b.add(schema.TPU_ROOT_LEAF_STALE_SERVED,
                  1.0 if leaf in stale_served else 0.0, (shard, leaf))
            b.add(schema.TPU_ROOT_LEAF_PARTITION_SUSPECTED,
                  1.0 if leaf in suspected else 0.0, (shard, leaf))
            ts = self._leaf_ts.get(leaf)
            if ts:
                b.add(schema.TPU_ROOT_LEAF_STALENESS_SECONDS,
                      max(now_wall - ts, 0.0), (shard, leaf))
        for shard, sm in merged.items():
            if sm.targets_gauge is not None:
                b.add(schema.TPU_ROOT_SHARD_TARGETS, sm.targets_gauge,
                      (shard,))
            quarantined = sum(
                1 for v, _ts in sm.target_breaker.values() if v != 0.0
            )
            b.add(schema.TPU_ROOT_SHARD_QUARANTINED_TARGETS,
                  float(quarantined), (shard,))
            # Per-shard accelerator-family split (status --tree's family
            # column): consistent hashing mixes node pools across shards,
            # so which families a shard carries is data, not topology.
            shard_fams: dict[str, float] = {}
            for key, stats in sm.slices.items():
                fam = key[2] if len(key) > 2 else "tpu"
                shard_fams[fam] = shard_fams.get(fam, 0.0) + stats.chips
            for fam, chips in sorted(shard_fams.items()):
                b.add(schema.TPU_ROOT_SHARD_FAMILY_CHIPS, chips,
                      (shard, fam))
        for spec in (schema.TPU_ROOT_DEDUP_STALE_WINS_TOTAL,
                     schema.TPU_ROOT_RESHARD_MOVES_TOTAL):
            for lv, v in self._counters.items_for(spec.name):
                b.add(spec, v, lv)
        b.add(schema.TPU_ROOT_LAST_ROUND_TIMESTAMP_SECONDS, now_wall)
        if self._loop_overruns_fn is not None:
            try:
                b.add(schema.TPU_AGG_POLL_OVERRUNS_TOTAL,
                      float(self._loop_overruns_fn()))
            except Exception:  # noqa: BLE001 — accounting must never fail a round
                pass
        if self._fleet_store is not None:
            try:
                self._fleet_store.emit(b)
            except Exception:  # noqa: BLE001 — store surface must not fail publish
                pass
        for emit_hook in self.emit_hooks:
            try:
                emit_hook(b)
            except Exception:  # noqa: BLE001 — hook surface must not fail publish
                pass
        cpu_s = utils.process_cpu_seconds()
        if cpu_s is not None:
            b.add(schema.TPU_AGG_CPU_SECONDS_TOTAL, cpu_s)
        rss = utils.process_rss_bytes()
        if rss is not None:
            b.add(schema.TPU_AGG_RSS_BYTES, rss)
        self._round_hist.emit(b)
        round_dur = time.monotonic() - round_started
        b.add(schema.TPU_ROOT_ROUND_DURATION_SECONDS, round_dur)
        snap = b.build(timestamp=now_wall, transfer=True)
        self._store.swap(snap)
        self._round_hist.observe(round_dur)

    # Rough per-entry retained cost of a stale-serve cache slot: dict
    # entries + key tuples + float cells. Same estimate the memory budget
    # sums and /debug/vars shows (the shared-numbers contract of
    # tpu_pod_exporter.pressure).
    _VIEW_ENTRY_EST_BYTES = 160

    def stale_view_bytes(self) -> int:
        """Estimated retained bytes of the stale-serve view cache
        (``_last_views``) for the memory budget's component accounting."""
        total = 0
        for view, _wall in self._last_views.values():
            total += self._VIEW_ENTRY_EST_BYTES * (
                1
                + len(view.slice_fields) + len(view.workload_fields)
                + len(view.group_info) + len(view.target_up)
                + len(view.target_breaker)
            )
        return total

    def shed_stale_views(self) -> int:
        """Memory-ladder hook: drop every cached stale-serve view (an
        unreachable leaf's shard then degrades honestly instead of being
        carried — memory pressure trumps continuity at this rung).
        Returns the number of views dropped."""
        n = len(self._last_views)
        self._last_views.clear()
        return n

    def ready_detail(self) -> dict:
        """/readyz detail hook (``server.MetricsServer ready_detail_fn``):
        the root keeps answering HTTP 200 through a partition — last-known
        data IS being served — but flips ``state`` to ``degraded`` with an
        operator-readable reason once NO leaf is reachable, and surfaces
        per-leaf stale-serve/suspicion either way."""
        reachable, total, stale_served, suspected = self._health
        out: dict = {
            "leaf_tier": {
                "reachable": reachable,
                "total": total,
                "stale_served": stale_served,
                "partition_suspected": list(suspected),
            },
        }
        if total and reachable == 0 and self.rounds > 0:
            out["degraded_sources"] = [
                f"leaf-tier: 0/{total} leaves reachable — serving "
                f"last-known shard data"
                + (f" ({stale_served} leaf view(s) stale-served)"
                   if stale_served else "")
                + "; root-side network partition suspected"
            ]
        if self.alert_evaluator is not None:
            # `alerting: ok|degraded` — detail only, NEVER the HTTP code:
            # a down webhook receiver must not pull the root from scrape
            # rotation.
            out["alerting"] = self.alert_evaluator.ready_detail()
        return out

    def debug_vars(self) -> dict:
        tmpl = self._prefix_cache.template
        return {
            "topology": {s: list(ls) for s, ls in self.topology.items()},
            "timeout_s": self._timeout_s,
            "rounds": self.rounds,
            # Splice-render counters (None = --render-splice false); the
            # RUNBOOK's render triage reads the same shape on every tier.
            "render": tmpl.stats() if tmpl is not None else None,
            "store": (self._fleet_store.stats()
                      if self._fleet_store is not None else None),
            "alerting": (self.alert_evaluator.stats()
                         if self.alert_evaluator is not None else None),
            "stale_serve_s": self._stale_serve_s,
            "stale_view_bytes": self.stale_view_bytes(),
            "stale_served_leaves": self._health[2],
            "partition_suspected": list(self._health[3]),
            "leaf_round_ts": dict(self._leaf_ts),
            "assignments": len(self._assignments),
            "leaf_breakers": (
                {
                    leaf: {
                        "state": br.state,
                        "consecutive_failures": br.consecutive_failures,
                        "reopens": br.reopens,
                        "next_probe_in_s": round(br.seconds_until_probe, 3),
                    }
                    for leaf, br in self._breakers.items()
                }
                if self._breakers is not None else None
            ),
        }

    def close(self) -> None:
        self._leaf_set.maybe_save_breakers(force=True)
        self._pool.shutdown(wait=False)
        if self.alert_evaluator is not None:
            try:
                self.alert_evaluator.close()
            except Exception:  # noqa: BLE001 — draining must finish
                pass
        if self._fleet_store is not None:
            try:
                self._fleet_store.close()
            except Exception:  # noqa: BLE001 — draining must finish
                pass


# ---------------------------------------------------------- two-level queries


# Per-target state ranking for the union merge: when two leaves of an HA
# pair disagree about one target, the better state stands (the other leaf's
# failure was leaf-local).
_STATE_RANK = {"ok": 0, "no_data": 1, "quarantined": 2, "timeout": 3,
               "error": 4}


class RootQueryPlane:
    """Two-level ``/api/v1`` fan-out: the root fans a query out to every
    leaf's federated query plane (``fleet.py``) and merges the envelopes.

    Same partial-result contract as one level down, one tier up: a dead
    leaf whose HA twin answers degrades nothing; a shard with NO answering
    leaf marks the result partial. The merged envelope carries per-LEAF
    state (``leaves``) alongside the per-target state (``targets``) the
    leaves already report — ``status --tree`` and dashboards read both.

    Serves the same three methods ``server.MetricsServer`` dispatches to,
    so the root's HTTP surface is identical to an aggregator's.
    """

    def __init__(
        self,
        topology: Mapping[str, Sequence[str]],
        timeout_s: float = 2.5,
        fetch: Callable[..., dict] = default_api_fetch,
        leaf_breakers: Mapping[str, CircuitBreaker] | None = None,
        wallclock: Callable[[], float] = time.time,
        max_workers: int = 16,
        generation_fn: Callable[[], int] | None = None,
        cache_entries: int = 128,
    ) -> None:
        if not topology:
            raise ValueError("root query plane needs at least one shard")
        self.topology = {s: tuple(ls) for s, ls in topology.items()}
        self._leaves = tuple(
            leaf for leaves in self.topology.values() for leaf in leaves
        )
        self._shard_of = {
            leaf: shard
            for shard, leaves in self.topology.items()
            for leaf in leaves
        }
        self._timeout_s = timeout_s
        self._fetch = fetch
        self._breakers = leaf_breakers
        self._wallclock = wallclock
        self._rlog = RateLimitedLogger(log)
        # Generation-keyed result cache, the fleet plane's discipline one
        # tier up: with a generation_fn (the root's round counter) every
        # panel — and every stream-hub shape evaluation — costs ONE
        # two-level fan-out per round, however many viewers ask. Without
        # one (pre-existing constructions), every query fans out, the
        # original behavior.
        self._generation_fn = generation_fn
        self._cache = QueryCache(cache_entries if generation_fn else 0)
        self._pool = ThreadPoolExecutor(
            max_workers=min(max(len(self._leaves), 1), max_workers),
            thread_name_prefix="tpu-root-query",
        )

    # ------------------------------------------------------------- public API

    def series(self) -> dict:
        return self._query("series", "/api/v1/series", {}, key=("series",))

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
    ) -> dict:
        if end is None:
            end = self._wallclock()
        if start is None:
            start = end - 300.0
        if step > 0:
            # Grid alignment (fleet.py's): sliding dashboard windows land
            # on the same cache key within a generation, and grid points
            # given up at the OLD edge keep the widened range inside the
            # node-side resolution cap.
            start = (start // step) * step
            end = -((-end) // step) * step
            if (end - start) / step > 11000:
                start = end - 11000 * step
        match = dict(match or {})
        params = {"metric": metric, "start": f"{start:.3f}",
                  "end": f"{end:.3f}", "step": f"{step:g}", "agg": agg}
        for k, v in match.items():
            params[f"match[{k}]"] = v
        key = ("query_range", metric, tuple(sorted(match.items())),
               round(start, 3), round(end, 3), step, agg)
        return self._query("query_range", "/api/v1/query_range", params,
                           key=key)

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
    ) -> dict:
        match = dict(match or {})
        params = {"metric": metric, "window": f"{window_s:g}"}
        for k, v in match.items():
            params[f"match[{k}]"] = v
        key = ("window_stats", metric, tuple(sorted(match.items())),
               window_s)
        return self._query("window_stats", "/api/v1/window_stats", params,
                           key=key)

    # --------------------------------------------------------------- internals

    def _fetch_leaf(
        self, leaf: str, path: str, params: Mapping[str, str],
    ) -> tuple[str, str, dict | None, str, float]:
        """(leaf, state, envelope, error, duration)."""
        t0 = time.monotonic()
        url = target_query_url(leaf, path, params)
        try:
            doc = self._fetch(url, self._timeout_s)
        except urllib.error.HTTPError as e:
            dur = time.monotonic() - t0
            if e.code == 404:
                # The leaf answered: no samples anywhere in its shard.
                return leaf, "no_data", None, "", dur
            self._rlog.warning(f"query:{leaf}",
                               "root query to leaf %s failed: %s", leaf, e)
            return leaf, "error", None, f"HTTP {e.code}", dur
        except Exception as e:  # noqa: BLE001 — a down leaf is data, not death
            self._rlog.warning(f"query:{leaf}",
                               "root query to leaf %s failed: %s", leaf, e)
            return leaf, "error", None, str(e), time.monotonic() - t0
        return leaf, "ok", doc, "", time.monotonic() - t0

    # The ONE shape implementation (fleet.data_shape/rows_of) — tiers
    # must not drift.
    _rows_of = staticmethod(fleet_rows_of)
    _data_shape = staticmethod(fleet_data_shape)

    def _query(self, route: str, path: str,
               params: Mapping[str, str], key: tuple = ()) -> dict:
        generation = (self._generation_fn()
                      if self._generation_fn is not None else 0)
        cache_key = key + (generation,)
        cached = self._cache.get(cache_key)
        if cached is not None:
            # Shared + read-only, same contract as fleet.py's cache;
            # only the top-level marker differs per response.
            return {**cached, "cached": True}
        env = self._query_uncached(route, path, params, generation)
        self._cache.put(cache_key, env)
        return env

    def _query_uncached(self, route: str, path: str,
                        params: Mapping[str, str], generation: int) -> dict:
        t0 = time.monotonic()
        leaf_states: dict[str, dict] = {}
        futures = {}
        for leaf in self._leaves:
            br = self._breakers.get(leaf) if self._breakers else None
            if br is not None and br.state != CLOSED:
                # Scrape-plane quarantine trusted, probes not consumed —
                # same rule the leaf applies to its node targets.
                leaf_states[leaf] = {
                    "shard": self._shard_of[leaf],
                    "state": "quarantined",
                    "next_probe_in_s": round(br.seconds_until_probe, 3),
                }
                continue
            fut = self._pool.submit(self._fetch_leaf, leaf, path, params)
            futures[fut] = leaf
        envelopes: dict[str, dict] = {}
        # ONE overall deadline across the whole fan-out, fleet.py's
        # _fan_out discipline: a leaf drip-feeding bytes keeps each
        # socket op under timeout_s and would otherwise hold this query
        # for n_leaves x timeout — behind the server's 2-permit /api/v1
        # fence, two such queries would wedge the root's entire API.
        # Stragglers are marked `timeout` and left to finish on the pool.
        deadline = time.monotonic() + self._timeout_s + 0.5
        pending = set(futures)
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            done, pending = futures_wait(pending, timeout=remaining,
                                         return_when=FIRST_COMPLETED)
            for fut in done:
                fut_leaf = futures[fut]
                try:
                    leaf, state, env, err, dur = fut.result()
                except Exception as e:  # noqa: BLE001 — a broken leg degrades, never fails
                    leaf_states[fut_leaf] = {
                        "shard": self._shard_of[fut_leaf],
                        "state": "error",
                        "error": str(e),
                    }
                    continue
                st: dict[str, Any] = {
                    "shard": self._shard_of[leaf],
                    "state": state,
                    "duration_s": round(dur, 6),
                }
                if err:
                    st["error"] = err
                if env is not None:
                    st["partial"] = bool(env.get("partial"))
                    envelopes[leaf] = env
                leaf_states[leaf] = st
        for fut in pending:
            leaf_states[futures[fut]] = {
                "shard": self._shard_of[futures[fut]],
                "state": "timeout",
                "error": "missed fan-out deadline",
            }

        # Per-series merge, freshest-wins on colliding keys: HA twins
        # answer with the SAME series for their shared shard, and the one
        # carrying the newer last_sample_wall_ts is at most one leaf round
        # fresher, never staler.
        chosen: dict[tuple, tuple[float, dict]] = {}
        order: list[tuple] = []
        duplicates = 0
        for leaf in self._leaves:
            env = envelopes.get(leaf)
            if env is None:
                continue
            for row in self._rows_of(route, env):
                if not isinstance(row, dict):
                    continue
                try:
                    key = (
                        row.get("metric", ""),
                        tuple(sorted((row.get("labels") or {}).items())),
                    )
                except TypeError:
                    continue
                ts = row.get("last_sample_wall_ts")
                ts_f = float(ts) if isinstance(ts, (int, float)) else 0.0
                prev = chosen.get(key)
                if prev is None:
                    chosen[key] = (ts_f, row)
                    order.append(key)
                else:
                    duplicates += 1
                    if ts_f > prev[0]:
                        chosen[key] = (ts_f, row)
        merged = [chosen[k][1] for k in order]

        # Per-target union across leaf envelopes: best state stands.
        targets: dict[str, dict] = {}
        for leaf in self._leaves:
            env = envelopes.get(leaf)
            if env is None:
                continue
            for t, st in (env.get("targets") or {}).items():
                prev_st = targets.get(t)
                if prev_st is None or (
                    _STATE_RANK.get(str(st.get("state")), 9)
                    < _STATE_RANK.get(str(prev_st.get("state")), 9)
                ):
                    targets[t] = st

        covered = {
            shard: any(
                leaf_states.get(leaf, {}).get("state") in ("ok", "no_data")
                for leaf in leaves
            )
            for shard, leaves in self.topology.items()
        }
        uncovered = sorted(s for s, ok in covered.items() if not ok)
        partial = bool(uncovered) or any(
            str(st.get("state")) in ("error", "timeout", "quarantined")
            for st in targets.values()
        )
        took = time.monotonic() - t0
        return {
            "status": "ok",
            "partial": partial,
            "route": route,
            # Two-level fan-out answers are "live"; the store-backed
            # wrapper (store.StoreQueryPlane) upgrades this to
            # live|store|merged — one envelope contract across tiers.
            "source": "live",
            "data": self._data_shape(route, merged),
            "targets": targets,
            "leaves": leaf_states,
            "fleet": {
                "shards": len(self.topology),
                "uncovered_shards": uncovered,
                "leaves": len(self._leaves),
                "leaves_ok": sum(
                    1 for st in leaf_states.values()
                    if st.get("state") == "ok"
                ),
                "targets": len(targets),
                "ok": sum(1 for st in targets.values()
                          if st.get("state") == "ok"),
                "merged_series": len(merged),
                "duplicate_series": duplicates,
            },
            "generation": generation,
            "took_s": round(took, 6),
        }

    # ------------------------------------------------- pressure shed hooks

    def cache_bytes(self) -> int:
        """Result-cache byte estimate for the memory ladder's component
        accounting (same number /debug/vars would report)."""
        return self._cache.bytes()

    def set_cache_enabled(self, enabled: bool) -> None:
        """fleet_cache memory rung, root flavor: clear + disable (every
        query re-fans-out; correctness unchanged). Reversible."""
        self._cache.set_enabled(enabled)

    def close(self) -> None:
        self._pool.shutdown(wait=False)


# ---------------------------------------------------------------- replicas


class ReplicaSourceProxy:
    """The /api/v1 front of a stateless read replica.

    Live queries serve from the replica's own two-level fan-out
    (``RootQueryPlane``) — identical to the root's answers by the
    freshest-wins dedup contract. ``?source=`` queries need the fleet
    store, which exactly one root owns: with ``--root-url`` configured
    they are proxied there verbatim (tagged ``proxied: true``, counted in
    ``tpu_replica_store_proxied_total``); without it they 400 with an
    actionable message — a replica silently answering ``source=store``
    from live data would let an operator trust history that is not there
    (the store.StoreQueryPlane honesty rule, one tier over).
    """

    # The server threads ?source= through to planes that declare it.
    handles_source = True

    def __init__(
        self,
        inner: RootQueryPlane,
        replica_id: str = "replica",
        root_url: str = "",
        fetch: Callable[..., dict] = default_api_fetch,
        timeout_s: float = 5.0,
    ) -> None:
        self._inner = inner
        self.replica_id = replica_id
        self._root_url = root_url.strip().rstrip("/")
        self._fetch = fetch
        self._timeout_s = timeout_s
        self._counters = CounterStore()
        for result in ("ok", "error"):
            self._counters.inc(
                schema.TPU_REPLICA_STORE_PROXIED_TOTAL.name, (result,), 0.0)

    # ------------------------------------------------------------- queries

    def series(self, source: str = "") -> dict:
        if source:
            return self._proxy("/api/v1/series", {"source": source})
        return self._inner.series()

    def query_range(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        start: float | None = None,
        end: float | None = None,
        step: float = 0.0,
        agg: str = "last",
        source: str = "",
    ) -> dict:
        if source:
            if end is None:
                end = time.time()
            if start is None:
                start = end - 300.0
            params = {"metric": metric, "start": f"{start:.3f}",
                      "end": f"{end:.3f}", "step": f"{step:g}",
                      "agg": agg, "source": source}
            for k, v in dict(match or {}).items():
                params[f"match[{k}]"] = v
            return self._proxy("/api/v1/query_range", params)
        return self._inner.query_range(metric, match, start, end, step,
                                       agg=agg)

    def window_stats(
        self,
        metric: str,
        match: Mapping[str, str] | None = None,
        window_s: float = 60.0,
        source: str = "",
    ) -> dict:
        if source:
            params = {"metric": metric, "window": f"{window_s:g}",
                      "source": source}
            for k, v in dict(match or {}).items():
                params[f"match[{k}]"] = v
            return self._proxy("/api/v1/window_stats", params)
        return self._inner.window_stats(metric, match, window_s=window_s)

    def _proxy(self, path: str, params: Mapping[str, str]) -> dict:
        if not self._root_url:
            # Mapped to the same 400 contract as every other param error.
            raise ValueError(
                "source= requires the root's fleet store; this replica "
                "owns no store and has no --root-url to proxy to — query "
                "the root directly or start the replica with --root-url"
            )
        url = target_query_url(self._root_url, path, params)
        try:
            doc = self._fetch(url, self._timeout_s)
        except urllib.error.HTTPError as e:
            # The root ANSWERED (e.g. its own 400 for a store-less
            # ?source=): relay the refusal as a refusal, not an outage.
            self._counters.inc(
                schema.TPU_REPLICA_STORE_PROXIED_TOTAL.name, ("error",))
            raise ValueError(
                f"root store proxy refused: HTTP {e.code}") from e
        except Exception as e:  # noqa: BLE001 — a dead root degrades, never kills
            self._counters.inc(
                schema.TPU_REPLICA_STORE_PROXIED_TOTAL.name, ("error",))
            return {
                "status": "error", "proxied": True,
                "error": f"root store proxy failed: {e}",
                "root_url": self._root_url,
            }
        self._counters.inc(
            schema.TPU_REPLICA_STORE_PROXIED_TOTAL.name, ("ok",))
        if isinstance(doc, dict):
            return {**doc, "proxied": True}
        return {"status": "error", "proxied": True,
                "error": "root store proxy returned a non-object"}

    # ----------------------------------------------------------- exposition

    def emit(self, b: SnapshotBuilder) -> None:
        """Replica identity + proxy accounting (rides the replica's
        publish via its emit hook). tpu_replica_info doubles as the
        'am I talking to a replica?' probe for clients and drills."""
        for spec in schema.REPLICA_SPECS:
            b.declare(spec)
        b.add(schema.TPU_REPLICA_INFO, 1.0, (self.replica_id,))
        for lv, v in self._counters.items_for(
                schema.TPU_REPLICA_STORE_PROXIED_TOTAL.name):
            b.add(schema.TPU_REPLICA_STORE_PROXIED_TOTAL, v, lv)

    def close(self) -> None:
        self._inner.close()


# ------------------------------------------------------------------------ CLI


def _add_common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--port", type=int, default=9100)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--interval-s", type=float, default=5.0)
    p.add_argument("--timeout-s", type=float, default=2.0)
    p.add_argument("--debug-addr", default="127.0.0.1",
                   help="/debug/* exposure (same policy as the exporter)")
    p.add_argument("--render-splice", default="on", choices=("on", "off"),
                   help="incremental exposition render (splice changed "
                        "cells into a pre-rendered body template per "
                        "round); off restores the per-family full "
                        "re-render — the RUNBOOK's bisection step, same "
                        "switch as the exporter tier")
    p.add_argument("--state-dir", default="",
                   help="persist breaker + shard-map state here (atomic "
                        "JSON) so restarts keep quarantines and count real "
                        "reshard moves; empty disables")
    p.add_argument("--num-shards", type=int, default=1,
                   help="size of the consistent-hash ring (shard-0..n-1); "
                        "every leaf and the root must agree")
    p.add_argument("--targets-file", default="",
                   help="global node-target list, one per line; re-read on "
                        "mtime change (leaves re-apply their hash cut — "
                        "live resharding; the root counts fleet-wide "
                        "assignment moves)")
    p.add_argument("--log-level", default="info")
    p.add_argument("--log-format", default="text", choices=("text", "json"),
                   help="json = one Cloud-Logging-shaped object per line")
    # Streaming dashboard plane (tpu_pod_exporter.stream): every
    # aggregation tier can serve /api/v1/stream — viewers register a
    # query once and receive per-round deltas instead of polling.
    p.add_argument("--stream", default="on", choices=("on", "off"),
                   help="/api/v1/stream subscriptions (SSE + long-poll "
                        "fallback): per-round deltas of a registered "
                        "query, one delta computation per query shape "
                        "per round however many viewers share it")
    p.add_argument("--stream-max-subscribers", type=int, default=10000,
                   help="admission cap on live stream subscriptions; "
                        "past it new subscribers get 429 and should "
                        "retry against a read replica")
    p.add_argument("--stream-heartbeat-s", type=float, default=10.0,
                   help="heartbeat frames to quiet subscribers (keeps "
                        "NAT/proxy paths alive between rounds); 0 "
                        "disables")
    p.add_argument("--stream-full-sync-s", type=float, default=60.0,
                   help="periodic full-answer frames on every stream "
                        "(delta-only streams rot — the egress full-sync "
                        "lesson); 0 disables")
    p.add_argument("--memory-budget-mb", type=float, default=0.0,
                   help="memory budget over the serving-tier components "
                        "(query result cache, stream hub retained "
                        "answers), enforced by the pressure governor: "
                        "past it the ladder sheds the result cache "
                        "first, then the OLDEST stream subscriptions "
                        "(stream_shed rung, counted + labeled; viewers "
                        "reconnect against a replica). 0 = no budget")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-shard",
        description="Sharded HA aggregation tree: consistent-hash leaf "
                    "aggregators plus a freshest-wins root merge tier.",
    )
    p.add_argument("--role", required=True,
                   choices=("leaf", "root", "replica"))
    _add_common_flags(p)
    # Leaf-only:
    p.add_argument("--shard-index", type=int, default=0,
                   help="[leaf] which shard of --num-shards this leaf owns")
    p.add_argument("--leaf-id", default="",
                   help="[leaf] identity within the (possibly HA-paired) "
                        "shard, e.g. 2a/2b; default <shard-index>a")
    p.add_argument("--targets", default="",
                   help="[leaf] static global target list (the hash cut is "
                        "applied to it); prefer --targets-file")
    p.add_argument("--breaker-failures", type=int, default=3)
    p.add_argument("--breaker-backoff-s", type=float, default=0.0,
                   help="0 = auto: max(2x --interval-s, --timeout-s)")
    p.add_argument("--breaker-backoff-max-s", type=float, default=120.0)
    p.add_argument("--history-fallback-window", type=float, default=0.0)
    # Root-only:
    p.add_argument("--leaves", default="",
                   help="[root] shard topology: 'shard-0=addrA|addrB,"
                        "shard-1=addrC' — two addresses make an HA pair")
    p.add_argument("--fleet-query", default="on", choices=("on", "off"),
                   help="[root] two-level /api/v1 fan-out through the "
                        "leaves' federated query planes")
    p.add_argument("--stale-serve-s", type=float, default=0.0,
                   help="[root] keep merging an unreachable leaf's LAST-"
                        "KNOWN view for this many seconds (leaf_up stays "
                        "0, staleness grows, tpu_root_leaf_stale_served "
                        "flags it) so a root-leaf network partition "
                        "degrades the fleet view to stale-but-labeled "
                        "instead of emptying it; 0 disables, try 3x "
                        "--interval-s")
    p.add_argument("--store-dir", default="",
                   help="[root] fleet TSDB-lite: persist each round's "
                        "merged rollups + per-target series into disk-"
                        "backed downsample tiers here, so fleet history "
                        "spans DAYS and survives root restarts, leaf "
                        "death and resharding; /api/v1 answers gain "
                        "source=live|store|merged (store fills what the "
                        "live fan-out cannot reach; ?source=store "
                        "answers from the store alone). Empty disables")
    p.add_argument("--store-tiers", default="",
                   help="[root] store downsample tiers, step:capacity "
                        "pairs finest first (default 60:240,600:1008 = "
                        "4 h at 1 min + exactly 7 d at 10 min)")
    p.add_argument("--store-rules", default="",
                   help="[root] recording-rule file: one "
                        "'name = agg(metric{label=\"v\"}) by (labels)' "
                        "per line, evaluated each round into its own "
                        "stored series so dashboards hit precomputed "
                        "rollups instead of fan-outs; malformed rules "
                        "fail startup loudly")
    p.add_argument("--alert-rules", default="",
                   help="[root] native alerting-rule file: 'alert NAME = "
                        "<expr>' blocks with indented for/keep_firing/"
                        "labels/annotations/suppress clauses, evaluated "
                        "at the root each merge round (no external "
                        "Prometheus on the incident path); malformed "
                        "rules or unknown metric names fail startup "
                        "loudly. Generate one from prometheus-rules.yaml "
                        "with `python -m tpu_pod_exporter.alerting "
                        "--import`. Empty disables alerting")
    p.add_argument("--alert-dir", default="",
                   help="[root] alerting state dir: the alert-status.json "
                        "sidecar (status --tree reads it) and the "
                        "notification WAL + exactly-once ledger live "
                        "here; required with --alert-webhook-url")
    p.add_argument("--alert-webhook-url", default="",
                   help="[root] POST firing/resolved transitions here as "
                        "JSON, exactly-once (WAL-buffered, seq-framed, "
                        "breaker-gated; outages backlog on disk and "
                        "drain contiguously across root restarts). "
                        "Empty = evaluate + record + stream, no "
                        "notifications")
    p.add_argument("--alert-webhook-timeout-s", type=float, default=5.0,
                   help="[root] per-notification webhook POST timeout")
    p.add_argument("--alert-suppression", default="on",
                   choices=("on", "off"),
                   help="[root] honor rules' suppress(...) clauses (the "
                        "partition false-positive guard). 'off' is the "
                        "drill negative control and an incident kill "
                        "switch — suppressed_total goes quiet and every "
                        "condition fires raw")
    p.add_argument("--store-max-disk-mb", type=float, default=0.0,
                   help="[root] disk budget over the store dir, enforced "
                        "by the pressure governor: past it the disk "
                        "ladder sheds store_thin (finest tier dropped "
                        "first, counted as reason=\"shed\"; coarse tiers "
                        "— the days-long window — shed last). 0 = no "
                        "budget (retention trim alone bounds disk)")
    # Replica-only:
    p.add_argument("--replica-id", default="",
                   help="[replica] identity published as tpu_replica_info"
                        "{replica=...}; default replica-<pid>")
    p.add_argument("--root-url", default="",
                   help="[replica] the real root's base URL: ?source= "
                        "store queries are proxied there (replicas own "
                        "no store); empty = such queries 400 honestly")
    ns = p.parse_args(argv)
    utils.setup_logging(ns.log_level, ns.log_format)
    if ns.role == "leaf":
        return _run_leaf(ns, p)
    if ns.role == "replica":
        return _run_replica(ns, p)
    return _run_root(ns, p)


def _serve_until_signal(loop: Any, server: Any,
                        closers: Sequence[Any]) -> int:
    stop = threading.Event()

    def _on_signal(signum: int, frame: Any) -> None:  # noqa: ARG001
        log.info("signal %d: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    loop.start()
    server.start()
    stop.wait()
    loop.stop()
    server.stop()
    for c in closers:
        try:
            c.close()
        except Exception:  # noqa: BLE001 — draining must finish
            pass
    return 0


def _attach_stream_cli(ns: argparse.Namespace, agg: Any,
                       plane: Any,
                       alerts_fn: Any = None) -> tuple[Any, Any]:
    """Stream-hub wiring shared by every role: (hub, pump), or (None,
    None) with --stream off or no query plane to answer through."""
    if ns.stream != "on" or plane is None:
        return None, None
    from tpu_pod_exporter.stream import attach_stream

    return attach_stream(
        agg, plane,
        heartbeat_s=ns.stream_heartbeat_s,
        full_sync_s=ns.stream_full_sync_s,
        max_subscribers=ns.stream_max_subscribers,
        alerts_fn=alerts_fn,
    )


def _run_leaf(ns: argparse.Namespace, p: argparse.ArgumentParser) -> int:
    from tpu_pod_exporter.collector import CollectorLoop
    from tpu_pod_exporter.server import MetricsServer

    if not ns.targets and not ns.targets_file:
        p.error("leaf role needs --targets or --targets-file")
    if not 0 <= ns.shard_index < ns.num_shards:
        p.error("--shard-index must be in [0, --num-shards)")
    shard_map = ShardMap(default_shards(ns.num_shards))
    shard_id = f"shard-{ns.shard_index}"
    leaf_id = ns.leaf_id or f"{ns.shard_index}a"
    breaker_store = shard_map_store = None
    if ns.state_dir:
        from tpu_pod_exporter.persist import BreakerStateFile, ShardMapFile

        breaker_store = BreakerStateFile(
            os.path.join(ns.state_dir, f"leaf-{leaf_id}-breakers.json"))
        shard_map_store = ShardMapFile(
            os.path.join(ns.state_dir, f"leaf-{leaf_id}-shardmap.json"))
    store = SnapshotStore()
    backoff = (ns.breaker_backoff_s if ns.breaker_backoff_s > 0
               else max(2.0 * ns.interval_s, ns.timeout_s))
    agg = LeafAggregator(
        shard_id, leaf_id, shard_map,
        shard_map_store=shard_map_store,
        targets=tuple(
            t.strip() for t in ns.targets.split(",") if t.strip()
        ),
        targets_file=ns.targets_file,
        store=store,
        timeout_s=ns.timeout_s,
        loop_overruns_fn=lambda: loop.overruns,
        history_fallback_window_s=ns.history_fallback_window,
        breaker_failures=ns.breaker_failures,
        breaker_backoff_s=backoff,
        breaker_backoff_max_s=max(ns.breaker_backoff_max_s, backoff),
        breaker_store=breaker_store,
        render_splice=ns.render_splice == "on",
    )
    from tpu_pod_exporter.fleet import FleetQueryPlane

    fleet = FleetQueryPlane(
        agg.targets,
        timeout_s=ns.timeout_s,
        breakers=agg.breakers,
        generation_fn=lambda: agg.rounds,
        targets_fn=lambda: agg.targets,
    )
    agg.set_fleet(fleet)
    hub, pump = _attach_stream_cli(ns, agg, fleet)
    loop = CollectorLoop(agg, interval_s=ns.interval_s)
    server = MetricsServer(
        store, host=ns.host, port=ns.port,
        health_max_age_s=max(10.0 * ns.interval_s, 10.0),
        debug_vars=agg.debug_vars, debug_addr=ns.debug_addr, fleet=fleet,
        ready_detail_fn=agg.ready_detail,
        stream_hub=hub,
    )
    agg.poll_once()  # synchronous first round so /readyz flips immediately
    log.info("leaf %s (%s) aggregating %d/%s targets on :%d every %.1fs",
             leaf_id, shard_id, len(agg.targets),
             ns.targets_file or "static", server.port, ns.interval_s)
    return _serve_until_signal(
        loop, server,
        [c for c in (pump, hub, fleet, agg) if c is not None])


def _run_root(ns: argparse.Namespace, p: argparse.ArgumentParser) -> int:
    from tpu_pod_exporter.collector import CollectorLoop
    from tpu_pod_exporter.server import MetricsServer

    if not ns.leaves:
        p.error("root role needs --leaves")
    topology = parse_leaf_topology(ns.leaves)
    # The ring: --num-shards when given, else inferred from the topology
    # (a partial rollout may list fewer shards than the ring has, so an
    # EXPLICIT flag wins — but never silently shrunk below the topology,
    # and every listed shard id must exist on the ring, or a config typo
    # would refuse every healthy leaf's body at runtime as 'all down').
    ring_n = max(ns.num_shards, 1)
    if ring_n < len(topology):
        if ns.num_shards > 1:
            p.error(f"--leaves lists {len(topology)} shards but "
                    f"--num-shards is {ns.num_shards}")
        ring_n = len(topology)
    shard_map = ShardMap(default_shards(ring_n))
    unknown = sorted(set(topology) - set(shard_map.shards))
    if unknown:
        p.error(f"--leaves names shard(s) {unknown} outside the "
                f"{ring_n}-shard ring (shard-0..shard-{ring_n - 1}); "
                f"check --num-shards")
    shard_map_store = breaker_store = None
    if ns.state_dir:
        from tpu_pod_exporter.persist import BreakerStateFile, ShardMapFile

        shard_map_store = ShardMapFile(
            os.path.join(ns.state_dir, "root-shardmap.json"))
        breaker_store = BreakerStateFile(
            os.path.join(ns.state_dir, "root-leaf-breakers.json"))
    store = SnapshotStore()
    # Fleet TSDB-lite: open (and replay) the store BEFORE the root so the
    # first round already appends; malformed rules and an uncreatable dir
    # are startup errors, never silent no-ops.
    fleet_store: Any = None
    governor: Any = None
    if not ns.store_dir and (ns.store_max_disk_mb > 0 or ns.store_tiers
                             or ns.store_rules):
        # A budget/tier/rule flag without the store itself would silently
        # enforce nothing — the operator believes history is governed.
        p.error("--store-max-disk-mb/--store-tiers/--store-rules require "
                "--store-dir (no fleet store is configured)")
    if ns.store_dir:
        from tpu_pod_exporter.store import (
            DEFAULT_STORE_TIERS,
            FleetStore,
            load_rules_file,
        )

        try:
            rules = (load_rules_file(ns.store_rules)
                     if ns.store_rules else ())
            fleet_store = FleetStore(
                ns.store_dir, tiers=ns.store_tiers or DEFAULT_STORE_TIERS,
                rules=rules)
            info = fleet_store.open()
        except (OSError, ValueError) as e:
            p.error(f"--store-dir/--store-rules: {e}")
        log.info("fleet store %s: %d tier(s), %d rule(s), replayed %d "
                 "buckets across %d series",
                 ns.store_dir, len(fleet_store.tier_spec),
                 len(fleet_store.rules), info["buckets"], info["series"])
        if ns.store_max_disk_mb > 0:
            from tpu_pod_exporter.pressure import (
                PressureGovernor,
                register_store_rungs,
            )

            budget = int(ns.store_max_disk_mb * (1 << 20))
            governor = PressureGovernor(disk_budget_bytes=budget,
                                        sidecar_dir=ns.store_dir)
            register_store_rungs(governor, fleet_store)
            fleet_store.disk_budget_bytes = budget
            governor.start()
    # Native alerting plane: rules parse + validate BEFORE the first
    # round (a typo'd rule file is a startup error, never a silent
    # no-op), the notifier replays its WAL before the evaluator can
    # enqueue (backlog from a previous run drains first, in seq order).
    evaluator: Any = None
    if not ns.alert_rules and (ns.alert_dir or ns.alert_webhook_url):
        p.error("--alert-dir/--alert-webhook-url require --alert-rules "
                "(no alerting plane is configured)")
    if ns.alert_rules:
        from tpu_pod_exporter.alerting import (
            AlertEvaluator,
            AlertNotifier,
            load_alert_rules_file,
        )

        if ns.alert_webhook_url and not ns.alert_dir:
            p.error("--alert-webhook-url needs --alert-dir (the "
                    "notification WAL and exactly-once ledger live "
                    "there)")
        notifier: Any = None
        try:
            alert_rules = load_alert_rules_file(ns.alert_rules)
            if ns.alert_dir:
                os.makedirs(ns.alert_dir, exist_ok=True)
            if ns.alert_webhook_url:
                notifier = AlertNotifier(
                    ns.alert_webhook_url, ns.alert_dir,
                    timeout_s=ns.alert_webhook_timeout_s)
                notifier.load()
                notifier.start()
            evaluator = AlertEvaluator(
                alert_rules,
                alert_dir=ns.alert_dir or None,
                notifier=notifier,
                store=fleet_store,
                recording_rules=(fleet_store.rules
                                 if fleet_store is not None else ()),
                suppression=ns.alert_suppression == "on",
            )
        except (OSError, ValueError) as e:
            p.error(f"--alert-rules: {e}")
        log.info("alerting plane: %d rule(s) from %s%s%s",
                 len(alert_rules), ns.alert_rules,
                 (f", webhook {ns.alert_webhook_url}"
                  if ns.alert_webhook_url else ", no webhook"),
                 ("" if ns.alert_suppression == "on"
                  else " [suppression OFF]"))
    root = RootAggregator(
        topology, store, timeout_s=ns.timeout_s,
        loop_overruns_fn=lambda: loop.overruns,
        targets_file=ns.targets_file,
        shard_map=shard_map,
        shard_map_store=shard_map_store,
        breaker_store=breaker_store,
        stale_serve_s=ns.stale_serve_s,
        fleet_store=fleet_store,
        alert_evaluator=evaluator,
        render_splice=ns.render_splice == "on",
    )
    if evaluator is not None:
        root.emit_hooks.append(evaluator.emit)
    plane: Any = None
    inner_plane: Any = None
    if ns.fleet_query == "on":
        plane = inner_plane = RootQueryPlane(
            topology, timeout_s=ns.timeout_s + 0.5,
            leaf_breakers=root._breakers,
            generation_fn=lambda: root.rounds)
    if fleet_store is not None:
        from tpu_pod_exporter.store import StoreQueryPlane

        # Source-aware front: live fan-out + store fills (store-only when
        # --fleet-query off). Serves through the same server hook.
        plane = StoreQueryPlane(plane, fleet_store)
    hub, pump = _attach_stream_cli(
        ns, root, plane,
        alerts_fn=(evaluator.rows if evaluator is not None else None))
    if ns.memory_budget_mb > 0:
        from tpu_pod_exporter.pressure import build_serving_governor

        # Serving-tier memory ladder: result cache sheds first, oldest
        # stream subscriptions last. Extends the store governor when one
        # exists (one governor per process), else builds + starts one.
        governor = build_serving_governor(
            int(ns.memory_budget_mb * (1 << 20)),
            sidecar_dir=ns.state_dir or ns.store_dir,
            cache_plane=inner_plane, hub=hub, governor=governor,
        )
    loop = CollectorLoop(root, interval_s=ns.interval_s)
    server = MetricsServer(
        store, host=ns.host, port=ns.port,
        health_max_age_s=max(10.0 * ns.interval_s, 10.0),
        debug_vars=root.debug_vars, debug_addr=ns.debug_addr, fleet=plane,
        ready_detail_fn=root.ready_detail,
        stream_hub=hub,
    )
    root.poll_once()
    log.info("root merging %d shard(s) / %d leaf(s) on :%d every %.1fs",
             len(topology), sum(len(v) for v in topology.values()),
             server.port, ns.interval_s)
    closers = [c for c in (pump, hub, plane, governor, root)
               if c is not None]
    return _serve_until_signal(loop, server, closers)


def _run_replica(ns: argparse.Namespace, p: argparse.ArgumentParser) -> int:
    """Stateless root read replica: scrape the leaves read-only exactly
    like the root (same merge, same freshest-wins dedup — replica reads
    are consistent by construction), serve /metrics + /api/v1 + the
    stream endpoint, own NOTHING durable: no egress, no persistence, no
    store writes. Viewer fan-out scales by adding replicas while exactly
    one root keeps the write-side duties."""
    from tpu_pod_exporter.collector import CollectorLoop
    from tpu_pod_exporter.server import MetricsServer

    if not ns.leaves:
        p.error("replica role needs --leaves (same topology as the root)")
    if ns.state_dir:
        p.error("replicas are stateless by design: --state-dir would "
                "persist breaker/shard state a replica must not own — "
                "drop the flag (the root keeps the durable state)")
    if ns.store_dir or ns.store_max_disk_mb > 0 or ns.store_tiers \
            or ns.store_rules:
        p.error("replicas own no fleet store: use --root-url to proxy "
                "?source= queries to the root's store instead of "
                "--store-* flags")
    topology = parse_leaf_topology(ns.leaves)
    ring_n = max(ns.num_shards, 1)
    if ring_n < len(topology):
        if ns.num_shards > 1:
            p.error(f"--leaves lists {len(topology)} shards but "
                    f"--num-shards is {ns.num_shards}")
        ring_n = len(topology)
    shard_map = ShardMap(default_shards(ring_n))
    unknown = sorted(set(topology) - set(shard_map.shards))
    if unknown:
        p.error(f"--leaves names shard(s) {unknown} outside the "
                f"{ring_n}-shard ring (shard-0..shard-{ring_n - 1}); "
                f"check --num-shards")
    store = SnapshotStore()
    replica_id = ns.replica_id or f"replica-{os.getpid()}"
    root = RootAggregator(
        topology, store, timeout_s=ns.timeout_s,
        loop_overruns_fn=lambda: loop.overruns,
        targets_file=ns.targets_file,
        shard_map=shard_map,
        stale_serve_s=ns.stale_serve_s,
        render_splice=ns.render_splice == "on",
    )
    plane: Any = None
    inner_plane: Any = None
    if ns.fleet_query == "on":
        inner_plane = RootQueryPlane(
            topology, timeout_s=ns.timeout_s + 0.5,
            leaf_breakers=root._breakers,
            generation_fn=lambda: root.rounds)
        plane = ReplicaSourceProxy(
            inner_plane,
            replica_id=replica_id,
            root_url=ns.root_url,
        )
        root.emit_hooks.append(plane.emit)
    else:
        # Identity must publish even without a query plane — clients and
        # drills probe tpu_replica_info to tell a replica from the root.
        def _emit_identity(b: SnapshotBuilder) -> None:
            for spec in schema.REPLICA_SPECS:
                b.declare(spec)
            b.add(schema.TPU_REPLICA_INFO, 1.0, (replica_id,))

        root.emit_hooks.append(_emit_identity)
    hub, pump = _attach_stream_cli(ns, root, plane)
    governor: Any = None
    if ns.memory_budget_mb > 0:
        from tpu_pod_exporter.pressure import build_serving_governor

        governor = build_serving_governor(
            int(ns.memory_budget_mb * (1 << 20)),
            cache_plane=inner_plane, hub=hub,
        )
    loop = CollectorLoop(root, interval_s=ns.interval_s)
    server = MetricsServer(
        store, host=ns.host, port=ns.port,
        health_max_age_s=max(10.0 * ns.interval_s, 10.0),
        debug_vars=root.debug_vars, debug_addr=ns.debug_addr, fleet=plane,
        ready_detail_fn=root.ready_detail,
        stream_hub=hub,
    )
    root.poll_once()
    log.info("replica %s merging %d shard(s) / %d leaf(s) READ-ONLY on "
             ":%d every %.1fs (store proxy: %s)",
             replica_id, len(topology),
             sum(len(v) for v in topology.values()),
             server.port, ns.interval_s, ns.root_url or "off")
    closers = [c for c in (pump, hub, plane, governor, root)
               if c is not None]
    return _serve_until_signal(loop, server, closers)


if __name__ == "__main__":
    import sys

    sys.exit(main())
