"""JAX environment hardening for the CPU-mesh code paths.

On this machine a ``sitecustomize.py`` (triggered by the
``PALLAS_AXON_POOL_IPS`` env var) registers an experimental TPU-tunnel PJRT
plugin in *every* Python interpreter and force-updates
``jax.config.jax_platforms`` to ``"axon,cpu"`` — overriding any
``JAX_PLATFORMS`` env var the caller set. Because ``jax.devices("cpu")``
initializes *all* configured platforms before filtering, even a
CPU-only query then dials the tunnel and can hang the process forever
(round 1's ``MULTICHIP`` rc=124).

Two escapes, both verified on this image:

1. **In-process pin** (:func:`pin_cpu_inprocess`): the plugin registration
   does not eagerly initialize backends, so re-updating
   ``jax_platforms="cpu"`` *before the first backend init* restores a pure
   CPU world. ``XLA_FLAGS`` is also still effective at that point (XLA
   reads it at client creation, not at import).
2. **Sanitized subprocess** (:func:`cpu_subprocess_env`): drop the
   sitecustomize trigger var entirely so the child never registers the
   plugin, and pin ``JAX_PLATFORMS=cpu`` + the virtual device count.

The in-process pin is used by the test suite (fast, granular); the
subprocess is used by ``__graft_entry__.dryrun_multichip`` where the
calling process may already have initialized (or wedged) backends.

Reference contrast: the reference exporter's only device runtime is NVML,
initialized once and fatally (``main.go:44-54``); here the accelerator
runtime is actively hostile to naive init and must be fenced.
"""

from __future__ import annotations

import os
import sys

# Env vars that make sitecustomize register the TPU-tunnel PJRT plugin.
HAZARD_ENV_VARS = ("PALLAS_AXON_POOL_IPS",)

# Loopback ports the tunnel relay serves when alive (leader :8082, device
# RPC :8083 — from the plugin's own registration docs). Port liveness is
# only a *fast negative* signal: nothing listening ⇒ backend init is
# guaranteed to block; something listening proves nothing (an unrelated
# dev server may squat the port), so callers must escalate to
# default_backend_usable() before trusting the tunnel.
TUNNEL_RELAY_PORTS = (8083, 8082)


def tunnel_relay_listening() -> bool:
    """Whether anything accepts TCP on the tunnel relay ports."""
    import socket

    for port in TUNNEL_RELAY_PORTS:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return True
        except OSError:
            continue
    return False


# Process-wide memo: the child probe costs seconds (up to its timeout on a
# squatted-but-dead port), so every guard in one process shares one verdict.
_default_backend_usable: bool | None = None


def default_backend_usable(timeout_s: float = 120.0, refresh: bool = False) -> bool:
    """Probe default-platform backend init in a killable child process
    (inheriting this env verbatim). True iff ``jax.devices()`` completes —
    the only trustworthy positive signal that the tunnel actually works;
    an in-process attempt would hang unrecoverably on a wedged tunnel.
    Memoized per process (``refresh=True`` re-probes)."""
    global _default_backend_usable
    if _default_backend_usable is None or refresh:
        import subprocess

        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
            )
            _default_backend_usable = proc.returncode == 0
        except subprocess.TimeoutExpired:
            _default_backend_usable = False
    return _default_backend_usable


def ensure_usable_backend(timeout_s: float = 120.0) -> str:
    """The guard for anything that initializes JAX *in-process* on this
    image: returns ``"default"`` when the default platform is safe to
    initialize, or pins CPU and returns ``"pinned-cpu"`` when the
    TPU-tunnel env is present but the tunnel is dead (backend init would
    block forever, round 1's rc=124). Raises with a diagnostic if the pin
    is impossible. The fallback is logged — a wedged tunnel must be
    observable, not indistinguishable from a healthy run."""
    if not any(os.environ.get(v) for v in HAZARD_ENV_VARS):
        return "default"
    if tunnel_relay_listening() and default_backend_usable(timeout_s):
        return "default"
    if not pin_cpu_inprocess():
        raise RuntimeError(
            "TPU tunnel is dead and the CPU pin failed (backends already "
            "initialized on a non-CPU platform?) — refusing to continue "
            "into a guaranteed backend-init hang"
        )
    import logging

    logging.getLogger("tpu_pod_exporter.jaxenv").warning(
        "TPU tunnel is not usable; JAX pinned to CPU for this process — "
        "accelerator code paths are NOT being exercised"
    )
    print(
        "[jaxenv] TPU tunnel not usable; pinned JAX to CPU (accelerator "
        "paths not exercised)",
        file=sys.stderr,
    )
    return "pinned-cpu"

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _with_device_count(flags: str, n_devices: int) -> str:
    """XLA_FLAGS string with the host-device-count flag forced to n."""
    kept = [f for f in flags.split() if not f.startswith(_COUNT_FLAG)]
    kept.append(f"{_COUNT_FLAG}={n_devices}")
    return " ".join(kept)


def cpu_subprocess_env(n_devices: int, base: dict | None = None) -> dict:
    """Environment for a child process that must see an n-device CPU mesh
    and must never initialize the TPU-tunnel plugin."""
    env = dict(os.environ if base is None else base)
    for var in HAZARD_ENV_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _with_device_count(env.get("XLA_FLAGS", ""), n_devices)
    return env


def _backends_initialized() -> bool:
    """Whether JAX backends are already initialized. Uses a private API
    (``jax._src.xla_bridge``) with a graceful fallback: if a jax upgrade
    moves it, treat the state as not-initialized — the config update then
    either takes effect (fine) or is a no-op against live caches, which
    the device verification below catches."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False


def pin_cpu_inprocess(n_devices: int | None = None, verify: bool = True) -> bool:
    """Pin this process's JAX to the CPU platform; return True on success.

    Must run before the first backend initialization. If backends are
    already initialized, succeeds only when the default platform is
    already CPU. Never raises; never dials the tunnel plugin. On failure
    the env mutations are rolled back so later-spawned children don't
    inherit a pin that never took effect.

    ``verify=False`` skips the ``jax.devices()`` check — it pins the
    config without creating the XLA CPU client (seconds of startup),
    for eager use at import time; call again with ``verify=True``
    before trusting the mesh size.
    """
    saved = {
        k: os.environ.get(k) for k in ("XLA_FLAGS", "JAX_PLATFORMS")
    }

    def _rollback() -> bool:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        return False

    if n_devices:
        os.environ["XLA_FLAGS"] = _with_device_count(
            os.environ.get("XLA_FLAGS", ""), n_devices
        )
    os.environ["JAX_PLATFORMS"] = "cpu"  # children + late config reads
    if not verify and "jax" not in sys.modules:
        # jax was never imported in this process (no sitecustomize hook):
        # the env vars alone govern the eventual import, so skip paying
        # the multi-second jax import at pin time.
        return True
    try:
        import jax
    except Exception:
        return _rollback()
    try:
        if not _backends_initialized():
            jax.config.update("jax_platforms", "cpu")
        elif jax.default_backend() != "cpu":
            return _rollback()
        if not verify:
            return True
        devs = jax.devices()
    except Exception:
        return _rollback()
    if devs and devs[0].platform != "cpu":
        return _rollback()
    if n_devices and len(devs) < n_devices:
        return _rollback()
    return True
