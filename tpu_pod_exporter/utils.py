"""Small shared utilities.

RateLimitedLogger: the reference prints per-pod PID dumps every 30 s cycle
(``main.go:81,89,104,108``) — at a 1 s interval the equivalent would be
86 400 lines/day per failing source. Repeated messages are keyed and
suppressed within a window; the next emission reports how many were
dropped, so operators see both the fault and its frequency.
"""

from __future__ import annotations

import logging
import time

from tpu_pod_exporter import trace as trace_mod

# Per-key cap on distinct trace ids tracked while suppressing: the tally is
# a correlation hint, not a full index — one poll per second over a 30 s
# window is ≤30 traces, and a flapping key must not grow an unbounded map.
_MAX_TRACES_PER_KEY = 32


class RateLimitedLogger:
    def __init__(
        self,
        logger: logging.Logger,
        min_interval_s: float = 30.0,
        clock=time.monotonic,
    ) -> None:
        self._logger = logger
        self._min_interval_s = min_interval_s
        self._clock = clock
        self._last_emit: dict[str, float] = {}
        # key -> (count, last suppression time, {trace_id: count}); counts
        # expire with the window so an old incident's tally is never
        # attributed to a new one. The per-trace sub-counts let the next
        # emission say how many suppressed lines belonged to the trace
        # that is active WHEN it finally emits — the line an operator uses
        # to jump from the log stream into /debug/trace.
        self._suppressed: dict[str, tuple[int, float, dict]] = {}

    def _emit(self, level: int, key: str, msg: str, *args, **kwargs) -> None:
        now = self._clock()
        last = self._last_emit.get(key)
        if last is not None and now - last < self._min_interval_s:
            count, _, traces = self._suppressed.get(key, (0, now, {}))
            tid = trace_mod.current_ids()[0]
            if tid is not None and (
                tid in traces or len(traces) < _MAX_TRACES_PER_KEY
            ):
                traces[tid] = traces.get(tid, 0) + 1
            self._suppressed[key] = (count + 1, now, traces)
            return
        dropped, dropped_at, traces = self._suppressed.pop(key, (0, 0.0, {}))
        # Report a tally only if the suppressed burst is recent (within two
        # windows) — a count left over from an incident days ago must not be
        # attributed to a new, unrelated fault.
        if dropped and now - dropped_at <= 2 * self._min_interval_s:
            # Trace breakdown of the suppressed burst: prefer the CURRENT
            # trace when it suppressed any lines (intra-poll bursts), else
            # the trace that suppressed the most — at one poll per second
            # the window spans ~30 traces, and the emitting poll's fresh
            # trace is almost never the one that did the suppressing, so
            # current-trace-only would report nothing exactly when the
            # operator needs a /debug/trace join key.
            tid = trace_mod.current_ids()[0]
            in_trace = traces.get(tid, 0) if tid is not None else 0
            if not in_trace and traces:
                tid, in_trace = max(traces.items(), key=lambda kv: kv[1])
            if in_trace:
                msg = (f"{msg} (+{dropped} similar suppressed, "
                       f"{in_trace} in trace {tid[:8]})")
            else:
                msg = f"{msg} (+{dropped} similar suppressed)"
        self._last_emit[key] = now
        self._logger.log(level, msg, *args, **kwargs)

    def recovery(self, key: str, msg: str, *args, **kwargs) -> None:
        """Log a recovery transition at WARNING, independent of the fault
        lines' rate limit.

        The end of an incident must be as visible as its start: fault
        lines for ``key`` are throttled to one per window while the source
        is down, and a recovery landing inside that suppression window
        must still log — operators would otherwise see incidents open and
        never close. So recovery emits under its OWN window
        (``key + ":recovered"``) rather than the faults': an isolated
        incident's recovery always logs, no matter how recently a fault
        line did. The same window throttles pathological flapping — a
        source failing and recovering every poll logs one fault line and
        one recovery line per window (each later carrying its suppressed
        tally), not two unthrottled WARNINGs per flap cycle."""
        self._emit(logging.WARNING, key + ":recovered", msg, *args, **kwargs)

    def warning(self, key: str, msg: str, *args, **kwargs) -> None:
        self._emit(logging.WARNING, key, msg, *args, **kwargs)

    def error(self, key: str, msg: str, *args, **kwargs) -> None:
        self._emit(logging.ERROR, key, msg, *args, **kwargs)

    def info(self, key: str, msg: str, *args, **kwargs) -> None:
        self._emit(logging.INFO, key, msg, *args, **kwargs)


# --- Process self-resource accounting --------------------------------------
# Shared by the exporter collector and the slice aggregator: both publish
# their own CPU seconds and RSS so the <1% CPU / bounded-memory budgets
# (BASELINE.md) are auditable in production, not just in bench.py. Both
# functions are exception-safe (None on failure) — accounting must never
# fail a poll or an aggregation round.

_PAGE_SIZE: int | None = None


def process_cpu_seconds() -> float | None:
    """Total user+system CPU time of this process, or None off-POSIX."""
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        return ru.ru_utime + ru.ru_stime
    except Exception:  # noqa: BLE001
        return None


def process_rss_bytes() -> float | None:
    """Current RSS from /proc/self/statm (field 2, pages); None off-Linux."""
    global _PAGE_SIZE
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        if _PAGE_SIZE is None:
            import os

            _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
        return float(pages * _PAGE_SIZE)
    except Exception:  # noqa: BLE001
        return None


# --- Logging setup ----------------------------------------------------------


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line, Cloud-Logging-shaped.

    ``severity`` (not ``levelname``) is the key GKE's logging agent
    promotes to a first-class field, which makes exporter warnings
    filterable/alertable in a fleet instead of being grepped out of text
    blobs. json.dumps handles every escape (quotes, newlines in tracebacks,
    non-UTF8-able code points) — a malformed pod name can't corrupt the
    log stream's line framing.
    """

    def format(self, record: logging.LogRecord) -> str:
        import json
        from datetime import datetime, timezone

        # RFC3339 with sub-second precision and a colon in the offset
        # ("+00:00") — strftime's %z yields "+0000", which strict Cloud
        # Logging parsers reject, silently falling back to ingestion time
        # exactly when ordering matters (code-review r5). timespec pinned:
        # bare isoformat() OMITS the fractional field when microsecond == 0
        # (~one log line per million), flapping the timestamp shape under
        # strict parsers (advisor r5).
        ts = datetime.fromtimestamp(record.created, timezone.utc).isoformat(
            timespec="microseconds"
        )
        out = {
            "severity": record.levelname,
            "time": ts,
            "logger": record.name,
            "message": record.getMessage(),
        }
        # Trace correlation: a line emitted inside a poll (collector,
        # supervisor, chaos — including supervised worker threads, which
        # inherit the poll's context) carries the active trace/span ids, so
        # `jq 'select(.trace_id == "…")'` reconstructs one poll's log
        # slice and joins it to /debug/trace. Formatting runs synchronously
        # on the emitting thread, so the thread-local context is the line's.
        trace_id, span_id = trace_mod.current_ids()
        if trace_id is not None:
            out["trace_id"] = trace_id
            out["span_id"] = span_id
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False, default=str)


def setup_logging(level: str, fmt: str = "text") -> None:
    """Root-logger setup shared by the exporter and aggregator CLIs.

    Unknown ``fmt`` or ``level`` raises instead of silently degrading
    (to text / to INFO): an operator who set TPE_LOG_FORMAT=JSONL or
    --log-level=verbose must find out at startup, not mid-incident when
    the logs aren't what they configured."""
    lvl = getattr(logging, level.upper(), None)
    # `not lvl` also rejects NOTSET (0), whose effective root level is
    # WARNING — accepting it would silently drop debug/info, the exact
    # misconfiguration this fail-loud contract exists to prevent.
    if not isinstance(lvl, int) or not lvl:
        raise ValueError(
            "--log-level must be one of debug/info/warning/error/critical, "
            f"got {level!r}"
        )
    fmt = fmt.lower()
    if fmt == "json":
        handler = logging.StreamHandler()
        handler.setFormatter(JsonLogFormatter())
        logging.basicConfig(level=lvl, handlers=[handler])
    elif fmt == "text":
        logging.basicConfig(
            level=lvl,
            format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        )
    else:
        raise ValueError(f"--log-format must be 'text' or 'json', got {fmt!r}")
