"""Resource-pressure governor — degradation by policy, not by exception.

Every durable-state layer added since PR 4 assumes a healthy machine: the
checkpoint + WAL (``persist.py``) assume the disk accepts writes, the
egress send buffer assumes it can grow, every ring and cache (history
tiers, trace ring, fleet query cache, root stale-serve views) assumes
memory is free, and the thread-per-connection server assumes scrapers are
polite. When the node itself misbehaves — ENOSPC, RSS pressure, FD
exhaustion, an NTP clock step — the exporter previously degraded by
*whatever exception surfaced first*. A production DaemonSet must degrade
by **explicit, documented policy** instead.

:class:`PressureGovernor` owns two degradation ladders, each a fixed
ordered list of rungs that shed the least valuable capability first and
recover rung by rung with hysteresis when the pressure lifts:

**Disk** (``--state-max-disk-mb`` across ``--state-dir`` + ``--egress-dir``,
plus immediate reaction to reported ENOSPC/EDQUOT):

1. ``wal_coarse``     — WAL sample coverage thinned (every Nth poll; the
   coarsest history tiers still rebuild from the checkpoint, so the cut
   costs raw-resolution restore fidelity, nothing else);
2. ``egress_compact`` — the egress send buffer rotates tiny segments so
   acked-but-unrotated bytes reclaim promptly, and the pending-backlog
   cap tightens (sheds via the existing ``WalBuffer.trim_to_bytes`` — a
   bounded, counted loss only while the receiver is down);
3. ``checkpoint_halved`` — checkpoint frequency halves (the worst-case
   restore staleness doubles — still bounded, still serving);
4. ``wal_off``        — the WAL stops entirely; the exporter keeps
   serving and checkpointing at the reduced cadence (restart loses the
   tail since the last checkpoint — the documented floor).

**Memory** (``--memory-budget-mb`` over the byte-accounted components —
coarse tiers shed LAST, because they are the cheapest bytes per second of
answerable history):

1. ``fleet_cache``  — the fleet query result cache is cleared and
   disabled (dashboard refreshes re-fan-out; correctness unchanged);
2. ``trace_halved`` — the trace ring halves (shorter incident lookback);
3. ``history_cut``  — the raw history rings rebuild at half capacity
   (retention cut: the downsample tiers keep answering the long windows).

Shedding decisions and the exposition read the SAME numbers: the
accounted usage, the budget, the ladder rung and every transition are
published (``tpu_exporter_pressure_state{resource}`` et al.,
:data:`~tpu_pod_exporter.metrics.schema.PRESSURE_SPECS`) and mirrored to
a ``pressure-status.json`` sidecar for the ``status`` footer.

The governor runs on its own thread (the poll thread never touches the
disk-usage walk — same discipline as persistence and egress); component
hooks it calls are cheap attribute flips or bounded rebuilds on the
owning component's lock.

``python -m tpu_pod_exporter.pressure --demo`` (``make pressure-demo``)
drills the ladders end to end: a disk drill against a real exporter on a
tiny budget (ladder climbs, WAL growth stops, the egress exactly-once
ledger stays intact, scraping keeps serving), a memory drill (sheds in
order until the accounted bytes fit), and a scrape-storm drill (admission
control keeps a polite scraper's p99 flat while hundreds of connections
are refused). ``--negative-control`` reruns a drill WITHOUT the governor
and passes only when the invariant visibly breaks — proving the drills
can fail.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.utils import RateLimitedLogger

log = logging.getLogger("tpu_pod_exporter.pressure")

# errnos that mean "the disk is FULL", as opposed to flaky/unreachable —
# the distinction the persist `reason="disk_full"` counter split exists for.
_DISK_FULL_ERRNOS = frozenset({errno.ENOSPC, errno.EDQUOT})

# How long one reported ENOSPC keeps the disk ladder under pressure even
# when the byte budget (if any) is not breached: the write just failed, so
# the filesystem is full regardless of what our own directories measure.
FAULT_WINDOW_S = 30.0

SIDE_CAR_NAME = "pressure-status.json"


def is_disk_full_error(exc: BaseException) -> bool:
    """ENOSPC/EDQUOT detection shared by persist/egress error accounting."""
    return isinstance(exc, OSError) and exc.errno in _DISK_FULL_ERRNOS


def reclaim_tmp_files(dirs: list[str], min_age_s: float = 60.0,
                      now: float | None = None) -> int:
    """Unlink orphaned ``*.tmp`` files left by failed atomic writes
    (``persist.atomic_write`` interrupted by ENOSPC or a crash between
    write and rename). The age guard keeps a CONCURRENT atomic write's
    live temp file safe — pass ``min_age_s=0`` only at boot, before any
    writer thread exists. Returns the number of files reclaimed."""
    reclaimed = 0
    now = time.time() if now is None else now
    for d in dirs:
        if not d:
            continue
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if not name.endswith(".tmp"):
                continue
            path = os.path.join(d, name)
            try:
                if min_age_s > 0 and now - os.stat(path).st_mtime < min_age_s:
                    continue
                os.unlink(path)
                reclaimed += 1
            except OSError:
                continue
    if reclaimed:
        log.warning("reclaimed %d orphaned .tmp file(s) from failed atomic "
                    "writes", reclaimed)
    return reclaimed


def dir_usage_bytes(path: str) -> int:
    """Total bytes of regular files directly under ``path`` (the state and
    egress dirs are flat by construction — no recursion needed)."""
    total = 0
    try:
        with os.scandir(path) as it:
            for entry in it:
                try:
                    if entry.is_file(follow_symlinks=False):
                        total += entry.stat(follow_symlinks=False).st_size
                except OSError:
                    continue
    except OSError:
        return 0
    return total


@dataclass
class Rung:
    """One ladder rung: ``apply`` sheds, ``release`` restores. Both must be
    idempotent and cheap (attribute flips / bounded rebuilds) — they run on
    the governor thread while the component keeps serving."""

    name: str
    apply: Callable[[], None]
    release: Callable[[], None]


@dataclass
class _Ladder:
    resource: str
    usage_fn: Callable[[], int]
    budget_bytes: int = 0          # 0 = no byte budget (fault-driven only)
    recover_frac: float = 0.85     # hysteresis: recover below this fraction
    rungs: list[Rung] = field(default_factory=list)
    level: int = 0
    sheds: int = 0
    recovers: int = 0
    last_usage: int = 0
    last_shed_wall: float = 0.0
    last_recover_wall: float = 0.0
    fault_until_mono: float = 0.0  # ENOSPC window (disk ladder only)
    quiet_since_mono: float | None = None

    def under_pressure(self, now_mono: float) -> bool:
        if now_mono < self.fault_until_mono:
            return True
        return bool(self.budget_bytes) and self.last_usage > self.budget_bytes

    def can_recover(self, now_mono: float) -> bool:
        if now_mono < self.fault_until_mono:
            return False
        if not self.budget_bytes:
            return True  # fault window expired — the only pressure source
        return self.last_usage <= self.recover_frac * self.budget_bytes


class PressureGovernor:
    """The two-ladder resource governor. Construction wires budgets; the
    component rungs are registered by ``app.py`` (exporter shape) or a
    harness; ``start()`` spawns the check thread. Every method is safe to
    call from any thread; rung callbacks run on the governor thread only.
    """

    def __init__(
        self,
        disk_budget_bytes: int = 0,
        memory_budget_bytes: int = 0,
        check_interval_s: float = 2.0,
        hysteresis_s: float = 30.0,
        sidecar_dir: str = "",
        clock: Callable[[], float] = time.monotonic,
        wallclock: Callable[[], float] = time.time,
    ) -> None:
        self.check_interval_s = check_interval_s
        self.hysteresis_s = hysteresis_s
        self.sidecar_dir = sidecar_dir
        self._clock = clock
        self._wallclock = wallclock
        self._rlog = RateLimitedLogger(log)
        self._lock = threading.Lock()
        self._disk = _Ladder("disk", self._disk_usage, disk_budget_bytes)
        self._memory = _Ladder("memory", self._memory_usage,
                               memory_budget_bytes)
        self._disk_paths: list[str] = []
        # name -> () -> int; the byte-accounted memory components. The
        # shed decision and the published tpu_exporter_pressure_bytes read
        # the SAME sum — no second accounting.
        self._memory_components: dict[str, Callable[[], int]] = {}
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None
        self._disk_full_errors = 0
        self._last_sidecar_wall = 0.0

    # -------------------------------------------------------------- wiring

    def add_disk_path(self, path: str) -> None:
        if path and path not in self._disk_paths:
            self._disk_paths.append(path)

    def add_disk_rung(self, name: str, apply: Callable[[], None],
                      release: Callable[[], None]) -> None:
        self._disk.rungs.append(Rung(name, apply, release))

    def add_memory_rung(self, name: str, apply: Callable[[], None],
                        release: Callable[[], None]) -> None:
        self._memory.rungs.append(Rung(name, apply, release))

    def register_memory_component(self, name: str,
                                  bytes_fn: Callable[[], int]) -> None:
        self._memory_components[name] = bytes_fn

    def set_disk_budget_bytes(self, n: int) -> None:
        with self._lock:
            self._disk.budget_bytes = n
        self._kick.set()

    def set_memory_budget_bytes(self, n: int) -> None:
        with self._lock:
            self._memory.budget_bytes = n
        self._kick.set()

    @property
    def disk_budget_bytes(self) -> int:
        return self._disk.budget_bytes

    @property
    def memory_budget_bytes(self) -> int:
        return self._memory.budget_bytes

    # ------------------------------------------------------------- signals

    def report_io_error(self, exc: BaseException) -> bool:
        """Component hook for write failures: an ENOSPC/EDQUOT arms the
        disk ladder's fault window and triggers an immediate check (called
        from the persist writer / egress threads — never blocks). Returns
        True when the error was disk-full-shaped."""
        if not is_disk_full_error(exc):
            return False
        with self._lock:
            self._disk_full_errors += 1
            self._disk.fault_until_mono = self._clock() + FAULT_WINDOW_S
        self._kick.set()
        return True

    # ----------------------------------------------------------- the check

    def _disk_usage(self) -> int:
        return sum(dir_usage_bytes(p) for p in self._disk_paths)

    def _memory_usage(self) -> int:
        total = 0
        for fn in self._memory_components.values():
            try:
                total += int(fn())
            except Exception:  # noqa: BLE001 — accounting must not kill the governor
                continue
        return total

    def tick(self) -> bool:
        """One evaluation of both ladders (normally driven by the governor
        thread; public so tests and drills can step deterministically).
        Returns True when any rung moved."""
        changed = False
        for ladder in (self._disk, self._memory):
            changed = self._tick_ladder(ladder) or changed
        if changed or self._wallclock() - self._last_sidecar_wall >= 30.0:
            self._write_sidecar()
        return changed

    def _tick_ladder(self, ladder: _Ladder) -> bool:
        usage = ladder.usage_fn()
        now_mono = self._clock()
        with self._lock:
            ladder.last_usage = usage
            pressured = ladder.under_pressure(now_mono)
            shed_rung: Rung | None = None
            release_rung: Rung | None = None
            if pressured:
                ladder.quiet_since_mono = None
                if ladder.level < len(ladder.rungs):
                    shed_rung = ladder.rungs[ladder.level]
                    ladder.level += 1
                    ladder.sheds += 1
                    ladder.last_shed_wall = self._wallclock()
            elif ladder.level > 0 and ladder.can_recover(now_mono):
                if ladder.quiet_since_mono is None:
                    ladder.quiet_since_mono = now_mono
                elif now_mono - ladder.quiet_since_mono >= self.hysteresis_s:
                    release_rung = ladder.rungs[ladder.level - 1]
                    ladder.level -= 1
                    ladder.recovers += 1
                    ladder.last_recover_wall = self._wallclock()
                    # Each further recovery needs its own quiet window —
                    # rung-by-rung, never a cliff back to full throughput.
                    ladder.quiet_since_mono = now_mono
            else:
                ladder.quiet_since_mono = None
        # Callbacks OUTSIDE the governor lock: they take component locks.
        if shed_rung is not None:
            self._rlog.warning(
                f"shed:{ladder.resource}",
                "%s pressure: usage %d bytes vs budget %d — shedding rung "
                "%d (%s)", ladder.resource, usage, ladder.budget_bytes,
                ladder.level, shed_rung.name,
            )
            self._run_rung(shed_rung.apply, ladder, shed_rung.name, "apply")
            if ladder.resource == "disk":
                # A full disk is exactly when orphaned temp files matter.
                reclaim_tmp_files(self._disk_paths)
            return True
        if release_rung is not None:
            self._rlog.recovery(
                f"shed:{ladder.resource}",
                "%s pressure lifted: usage %d bytes vs budget %d — "
                "recovering rung %s (level now %d)", ladder.resource,
                usage, ladder.budget_bytes, release_rung.name, ladder.level,
            )
            self._run_rung(release_rung.release, ladder, release_rung.name,
                          "release")
            return True
        return False

    def _run_rung(self, fn: Callable[[], None], ladder: _Ladder,
                  name: str, what: str) -> None:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — a broken rung must not kill the governor
            self._rlog.warning(
                f"rung:{ladder.resource}:{name}",
                "pressure rung %s/%s %s failed: %s", ladder.resource, name,
                what, e,
            )

    # ------------------------------------------------------------- thread

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="tpu-exporter-pressure", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._kick.clear()
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the governor must survive anything
                log.exception("pressure check failed")
            # Either the interval elapses or a reported ENOSPC / budget
            # change kicks an immediate re-check.
            self._kick.wait(self.check_interval_s)

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._kick.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None

    # -------------------------------------------------------- introspection

    def _ladder_stats(self, ladder: _Ladder) -> dict[str, Any]:
        rungs = [r.name for r in ladder.rungs]
        return {
            "level": ladder.level,
            "rung": rungs[ladder.level - 1] if ladder.level else "",
            "rungs": rungs,
            "usage_bytes": ladder.last_usage,
            "budget_bytes": ladder.budget_bytes,
            "sheds": ladder.sheds,
            "recovers": ladder.recovers,
            "last_shed_wall": ladder.last_shed_wall,
            "last_recover_wall": ladder.last_recover_wall,
        }

    def stats(self) -> dict[str, Any]:
        """Cached-usage snapshot (no disk walk — safe on the poll thread;
        usage numbers are as of the governor thread's last tick)."""
        with self._lock:
            out: dict[str, Any] = {
                "disk": self._ladder_stats(self._disk),
                "memory": self._ladder_stats(self._memory),
                "disk_full_errors": self._disk_full_errors,
            }
        out["disk"]["paths"] = list(self._disk_paths)
        out["memory"]["components"] = sorted(self._memory_components)
        return out

    def emit(self, b: Any) -> None:
        """Publish the pressure surface into a SnapshotBuilder (collector
        publish hook — conditional surface, PRESSURE_SPECS)."""
        for spec in schema.PRESSURE_SPECS:
            b.declare(spec)
        with self._lock:
            rows = [
                (ladder.resource, ladder.level, ladder.last_usage,
                 ladder.budget_bytes, ladder.sheds, ladder.recovers)
                for ladder in (self._disk, self._memory)
            ]
        for resource, level, usage, budget, sheds, recovers in rows:
            b.add(schema.TPU_EXPORTER_PRESSURE_STATE, float(level),
                  (resource,))
            b.add(schema.TPU_EXPORTER_PRESSURE_BYTES, float(usage),
                  (resource,))
            b.add(schema.TPU_EXPORTER_PRESSURE_BUDGET_BYTES, float(budget),
                  (resource,))
            b.add(schema.TPU_EXPORTER_PRESSURE_TRANSITIONS_TOTAL,
                  float(sheds), (resource, "shed"))
            b.add(schema.TPU_EXPORTER_PRESSURE_TRANSITIONS_TOTAL,
                  float(recovers), (resource, "recover"))

    def memory_component_bytes(self) -> dict[str, int]:
        """Per-component byte breakdown (/debug/vars — the same callables
        the shed decision sums)."""
        out: dict[str, int] = {}
        for name, fn in self._memory_components.items():
            try:
                out[name] = int(fn())
            except Exception:  # noqa: BLE001
                out[name] = -1
        return out

    def _write_sidecar(self) -> None:
        """Operator-facing sidecar for the ``status`` pressure footer.
        Best-effort by design: on a genuinely full disk this write fails —
        the footer then shows the last state that fit, which is still
        truer than nothing."""
        if not self.sidecar_dir:
            return
        self._last_sidecar_wall = self._wallclock()
        doc = {"wall": self._last_sidecar_wall, **self.stats()}
        from tpu_pod_exporter.persist import atomic_write

        try:
            atomic_write(
                os.path.join(self.sidecar_dir, SIDE_CAR_NAME),
                json.dumps(doc).encode(),
            )
        except OSError:
            pass


def pressure_status_summary(sidecar_dir: str) -> dict[str, Any] | None:
    """Read the governor's on-disk sidecar for the out-of-process
    ``status`` footer (None when absent/unreadable — no governor ran
    here, or nothing was writable)."""
    try:
        with open(os.path.join(sidecar_dir, SIDE_CAR_NAME),
                  encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


# ------------------------------------------------------- exporter-shape wiring


def build_exporter_governor(
    cfg: Any,
    persister: Any = None,
    shipper: Any = None,
    history: Any = None,
    trace_store: Any = None,
) -> PressureGovernor | None:
    """Wire the exporter-shaped ladders from an ExporterConfig and the
    components app.py built. Returns None when nothing is governable
    (no budgets configured and no durable-state layer to protect)."""
    disk_budget = int(cfg.state_max_disk_mb * (1 << 20))
    memory_budget = int(cfg.memory_budget_mb * (1 << 20))
    has_disk = bool(cfg.state_dir) or shipper is not None
    if not has_disk and memory_budget <= 0:
        return None
    gov = PressureGovernor(
        disk_budget_bytes=disk_budget if has_disk else 0,
        memory_budget_bytes=memory_budget,
        sidecar_dir=cfg.state_dir,
    )
    if cfg.state_dir:
        gov.add_disk_path(cfg.state_dir)
    if shipper is not None:
        gov.add_disk_path(shipper.egress_dir)
    # --- disk ladder, shallowest shed first -----------------------------
    if persister is not None:
        gov.add_disk_rung(
            "wal_coarse",
            lambda: persister.set_wal_stride(4),
            lambda: persister.set_wal_stride(1),
        )
    if shipper is not None:
        gov.add_disk_rung(
            "egress_compact",
            lambda: shipper.set_disk_pressure(True),
            lambda: shipper.set_disk_pressure(False),
        )
    if persister is not None:
        gov.add_disk_rung(
            "checkpoint_halved",
            lambda: persister.set_snapshot_interval_factor(2.0),
            lambda: persister.set_snapshot_interval_factor(1.0),
        )
        gov.add_disk_rung(
            "wal_off",
            lambda: persister.set_wal_enabled(False),
            lambda: persister.set_wal_enabled(True),
        )
        persister.set_pressure_hook(gov.report_io_error)
    if shipper is not None:
        shipper.set_pressure_hook(gov.report_io_error)
    # --- memory ladder, coarse tiers last -------------------------------
    if memory_budget > 0:
        # The exporter has no fleet cache; the rung exists on aggregator
        # shapes (the harness registers it). Trace then history.
        if trace_store is not None:
            gov.register_memory_component(
                "trace", trace_store.memory_bytes)
            gov.add_memory_rung(
                "trace_halved",
                lambda: trace_store.set_max_traces(
                    max(trace_store.max_traces // 2, 8)),
                lambda: trace_store.set_max_traces(cfg.trace_max_traces),
            )
        if history is not None:
            gov.register_memory_component(
                "history", lambda: int(history.stats()["memory_bytes"]))
            base_capacity = history.capacity
            gov.add_memory_rung(
                "history_cut",
                lambda: history.set_capacity(
                    max(history.capacity // 2, 16)),
                lambda: history.set_capacity(base_capacity),
            )
    return gov


# ------------------------------------------------------- root-store wiring


def register_store_rungs(
    gov: PressureGovernor, store: Any,
    store_fn: Callable[[], Any] | None = None,
) -> None:
    """Wire a root-side FleetStore (tpu_pod_exporter.store) into the
    governor: the disk ladder gains the ``store_thin`` rung — the store
    drops its FINEST tier first (coarse tiers last: they are the cheapest
    bytes per second of answerable history), with the dropped records
    counted as ``reason="shed"`` — and the store's in-memory tier bytes
    register with the memory ladder's component accounting (the shed
    decision and ``tpu_root_store_memory_bytes`` read the same number).
    The store's WAL appends also report ENOSPC through the same fault
    window the persist/egress writers use.

    ``store_fn``: harnesses that SWAP store instances mid-run (the
    scenario engine's root_restart, the retention demo's kill/replay)
    pass a getter so the rungs and accounting follow the live instance;
    the swapping caller must re-apply ``set_pressure_hook`` (and any held
    thin state) to each fresh instance — hooks live on the instance. The
    disk paths are registered once: they derive from the tier config,
    which an instance swap on the same dir preserves."""
    get = store_fn if store_fn is not None else (lambda: store)
    for path in store.disk_paths():
        gov.add_disk_path(path)
    gov.add_disk_rung(
        "store_thin",
        lambda: get().set_thin(True),
        lambda: get().set_thin(False),
    )
    gov.register_memory_component("store", lambda: int(get().memory_bytes()))
    store.set_pressure_hook(gov.report_io_error)


# ----------------------------------------------------- stream-hub wiring


def register_stream_rung(
    gov: PressureGovernor, hub: Any,
    hub_fn: Callable[[], Any] | None = None,
) -> None:
    """Wire a streaming dashboard hub (tpu_pod_exporter.stream.StreamHub)
    into the memory ladder: the ``stream_shed`` rung sheds the OLDEST
    half of the live subscriptions (each gets a final ``shed`` frame and
    a counted ``tpu_stream_sheds_total{reason="pressure"}``) and halves
    the effective subscriber cap so a storm cannot instantly refill what
    was shed; recovery restores the configured cap. Ordered after the
    fleet-cache rung by registration order in the harnesses: dropping a
    cache is cheaper than dropping viewers, so viewers shed last among
    the cheap rungs but before history cuts. The hub's retained bytes
    (last answers + catch-up rings) register as a memory component — the
    shed decision and /debug/vars read the same number."""
    get = hub_fn if hub_fn is not None else (lambda: hub)
    gov.register_memory_component("stream",
                                  lambda: int(get().memory_bytes()))
    gov.add_memory_rung(
        "stream_shed",
        lambda: get().apply_pressure(),
        lambda: get().release_pressure(),
    )


def build_serving_governor(
    memory_budget_bytes: int,
    sidecar_dir: str = "",
    cache_plane: Any = None,
    hub: Any = None,
    governor: "PressureGovernor | None" = None,
) -> "PressureGovernor | None":
    """The serving-tier memory ladder the CLIs share (flat aggregator,
    root, replica — ``--memory-budget-mb``): the query-plane result
    cache sheds FIRST (queries re-fan-out; pure speed, zero viewers
    lost), live stream subscriptions LAST via :func:`register_stream_rung`
    (dropping viewers costs reconnects). Extends ``governor`` when the
    tier already built one (the root's store disk budget) — one governor
    per process — else builds and STARTS a fresh one. Returns the
    governor (unchanged when no budget is configured)."""
    if memory_budget_bytes <= 0:
        return governor
    gov = governor if governor is not None else PressureGovernor(
        sidecar_dir=sidecar_dir)
    gov.set_memory_budget_bytes(memory_budget_bytes)
    if cache_plane is not None and hasattr(cache_plane, "cache_bytes"):
        gov.register_memory_component(
            "fleet_cache", lambda: int(cache_plane.cache_bytes()))
        gov.add_memory_rung(
            "fleet_cache",
            lambda: cache_plane.set_cache_enabled(False),
            lambda: cache_plane.set_cache_enabled(True),
        )
    if hub is not None:
        register_stream_rung(gov, hub)
    if governor is None:
        gov.start()
    return gov


# --------------------------------------------------------------------- demo


def main(argv: list[str] | None = None) -> int:
    import argparse

    from tpu_pod_exporter.pressure_demo import (
        run_disk_drill,
        run_memory_drill,
        run_storm_drill,
    )

    p = argparse.ArgumentParser(
        prog="tpu-pod-exporter-pressure",
        description="Resource-pressure governor drills: disk-full ladder, "
                    "memory-budget shedding, scrape-storm admission "
                    "control (make pressure-demo).",
    )
    p.add_argument("--demo", action="store_true",
                   help="run the three pressure drills against real "
                        "components and fail on any broken invariant")
    p.add_argument("--drill", default="all",
                   help="disk | memory | storm | all")
    p.add_argument("--negative-control", action="store_true",
                   help="re-run the disk drill WITHOUT the governor and "
                        "succeed only if the budget invariant visibly "
                        "breaks (proves the drill can fail)")
    p.add_argument("--storm-conns", type=int, default=500,
                   help="concurrent storm connections for the scrape-storm "
                        "drill (CI uses a reduced count)")
    p.add_argument("--p99-slack-frac", type=float, default=0.05,
                   help="allowed fractional p99 regression for the polite "
                        "scraper during the storm")
    p.add_argument("--p99-slack-ms", type=float, default=5.0,
                   help="absolute p99 noise floor added to the budget")
    p.add_argument("--state-dir", default="",
                   help="disk-drill state dir (default: temp, removed on "
                        "success)")
    ns = p.parse_args(argv)

    if ns.negative_control:
        return run_disk_drill(ns.state_dir, governor=False)
    if not ns.demo:
        p.error("need --demo or --negative-control")
    rc = 0
    if ns.drill in ("all", "disk"):
        rc = rc or run_disk_drill(ns.state_dir, governor=True)
    if ns.drill in ("all", "memory"):
        rc = rc or run_memory_drill()
    if ns.drill in ("all", "storm"):
        rc = rc or run_storm_drill(ns.storm_conns, ns.p99_slack_frac,
                                   ns.p99_slack_ms / 1e3)
    if rc == 0:
        print("pressure-demo OK: ladder sheds by policy, recovers with "
              "hysteresis, and every rung is attributable from the "
              "exposition")
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
