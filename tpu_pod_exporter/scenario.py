"""Declarative fleet scenario timelines — the chaos DSL for end-to-end drills.

Every chaos tool so far is per-subsystem: ``chaos.py`` wraps one source,
``LeafKillHook`` kills one leaf, ``ChaosReceiver`` flaps one receiver. A
real TPU-fleet incident is a *composition* — a network partition during a
reshard during an egress backlog drain — and nothing scripted those
against the whole stack. This module is the timeline language for the
fleet scenario engine (``tpu_pod_exporter.loadgen.scenario``): a seeded,
deterministic schedule of named events on a logical round clock, parsed
up front with loud, actionable errors (the ``parse_chaos_spec`` contract:
a typo'd drill must fail at parse time, not silently inject nothing).

Grammar (``--timeline``; events separated by ``;`` or top-level ``,``)::

    timeline := event ((";" | ",") event)*
    event    := kind "(" args ")" "@" round ["+" duration]

    partition(tierA<->tierB, symmetric|asymmetric|flapping)
    preempt(slice-N)                  SIGTERM-shaped: every host of the slice
    restart_wave(N [, stagger=K])     N hosts restart, K per round
    churn_storm(N)                    N targets removed+added per window,
                                      plus a workload label-churn wave
    hotspot(podname)                  one workload's duty/HBM spikes
    recv_outage()                     the remote-write receiver answers 503
    disk_full()                       the disk budget collapses under the
                                      durable-state dirs (pressure governor
                                      must shed, reclaim, and recover)
    mem_pressure()                    the memory budget collapses under the
                                      byte-accounted caches/rings
    scrape_storm(N)                   N aggressive keep-alive connections
                                      hammer the serving tier
    clock_step(S)                     one NTP-shaped wall-clock step of S
                                      seconds (signed; instantaneous)
    root_restart()                    SIGKILL-shaped root death for the
                                      +duration window, then a fresh root
                                      (and fleet store) on the same dirs

``@round`` is the event's first engine round (0-based); ``+duration`` is
the window length in rounds (default 1). Examples::

    partition(leaf<->root, symmetric)@3+3
    partition(leaf<->root, asymmetric)@2+4; recv_outage()@4+2
    preempt(slice-2)@3+3, restart_wave(6, stagger=2)@8

Partition semantics (interpreted by the engine through
``chaos.PartitionState``):

- ``symmetric`` — every edge between the two tiers is cut, both logical
  directions (for an HTTP pull seam the fetch direction is the wire; a
  symmetric tier cut means *no* leaf of an HA pair is reachable).
- ``asymmetric`` — a one-sided cut: only the FIRST leaf of each HA pair
  (or, for ``node<->leaf``, only the ``a`` leaves' paths) loses the edge,
  so every shard keeps a healthy path via its twin. This is the
  "reachable by everyone except the root" shape the HA dedup must absorb
  without losing a series or flapping the freshest-wins winner.
- ``flapping`` — the cut alternates open/cut per engine round on a
  seeded phase (``chaos.Cut``), the shape that punishes breakers whose
  half-open probe success resets their backoff.

Named scenarios (the ``make scenario-demo`` set) live in
:data:`SCENARIOS`; each is just a timeline string plus the engine's
per-tick invariants, so new drills are one dict entry, not new code.
"""

from __future__ import annotations

import random
import re
from collections.abc import Callable
from dataclasses import dataclass, field

EVENT_KINDS: tuple[str, ...] = (
    "partition", "preempt", "restart_wave", "churn_storm", "hotspot",
    "recv_outage",
    # Resource-pressure kinds (ISSUE 10): the MACHINE misbehaving —
    # interpreted by the engine through the pressure governor and the
    # chaos host-level injectors (ClockStepper / ScrapeStorm).
    "disk_full", "mem_pressure", "scrape_storm", "clock_step",
    # Fleet-store kind (ISSUE 11): SIGKILL-shaped root death for
    # +duration rounds, then a fresh root (and fleet store, when one is
    # attached) rebuilt on the same state dirs — the store-continuity
    # drill's boundary.
    "root_restart",
    # Streaming dashboard kind (ISSUE 15): N stream subscriptions held
    # against the root's /api/v1/stream for the window; per-tick
    # invariants assert delta-replay == polled answer, zero seq gaps/
    # dups, and bounded push latency. --stream off is the drill's
    # negative control (the subscriptions cannot register; the run must
    # fail).
    "dashboard_storm",
)

TIERS: tuple[str, ...] = ("node", "leaf", "root", "recv")

# The scenario engine's invariant families, enumerable for the fuzzer's
# (seam × invariant) coverage ledger. Names, not code: each maps to a
# check documented in tpu_pod_exporter.loadgen.scenario (its docstring's
# numbered invariants plus the PR-16 alerting verdict). Declared here —
# the typed DSL layer — so the fuzzer and tests can enumerate them
# without importing the engine.
INVARIANTS: tuple[str, ...] = (
    "oracle_equality",      # quiet-round root == oracle rollup equality
    "egress_ledger",        # exactly-once receiver ledger
    "bounded_staleness",    # per-tier staleness budgets
    "series_rss_leaks",     # series set + RSS bounded after churn
    "fault_attribution",    # every fault readable from the exposition
    "alerts_correctness",   # fired-set / suppress-aware alert verdict
)

PARTITION_MODES: tuple[str, ...] = ("symmetric", "asymmetric", "flapping")

# Tier pairs the engine knows how to cut (unordered): the three seams the
# stack actually crosses. node<->root would be meaningless (the root never
# talks to nodes) and is rejected at parse time.
PARTITION_EDGES: frozenset[frozenset[str]] = frozenset({
    frozenset({"node", "leaf"}),
    frozenset({"leaf", "root"}),
    frozenset({"root", "recv"}),
})

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)\((?P<args>[^()]*)\)"
    r"@(?P<round>-?\d+)(?:\+(?P<dur>-?\d+))?$"
)
_EDGE_RE = re.compile(r"^(?P<a>[a-z]+)\s*<->\s*(?P<b>[a-z]+)$")
_SLICE_RE = re.compile(r"^slice-(?P<n>\d+)$")


@dataclass
class ScenarioEvent:
    """One parsed timeline event. ``at_round`` .. ``end_round`` (exclusive)
    is the injected window; single-round events have duration 1."""

    kind: str
    at_round: int
    duration: int = 1
    edge: tuple[str, str] | None = None  # partition: (tierA, tierB) as given
    mode: str = ""                       # partition: symmetric|asymmetric|flapping
    subject: str = ""                    # preempt: slice id; hotspot: pod
    count: int = 0                       # restart_wave / churn_storm / scrape_storm
    stagger: int = 1                     # restart_wave: hosts per round
    step_s: float = 0.0                  # clock_step: signed seconds
    raw: str = field(default="", compare=False)

    @property
    def end_round(self) -> int:
        return self.at_round + self.duration

    def overlap_key(self) -> tuple:
        """Identity for the no-overlapping-events rule: two events with the
        same key may not have intersecting windows (the engine cannot
        apply e.g. two preempts of the same slice at once, and silently
        merging them would make the drill lie about what it injected)."""
        if self.kind == "partition":
            return ("partition", frozenset(self.edge or ()))
        if self.kind in ("preempt", "hotspot"):
            return (self.kind, self.subject)
        return (self.kind,)


def _err(raw: str, msg: str) -> ValueError:
    return ValueError(f"scenario event {raw!r}: {msg}")


def _split_events(spec: str) -> list[str]:
    """Split a timeline on ``;`` and top-level ``,`` (commas inside an
    event's parens belong to its arg list)."""
    out: list[str] = []
    buf: list[str] = []
    depth = 0
    for ch in spec:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == ";" or (ch == "," and depth == 0):
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return [s.strip() for s in out if s.strip()]


def parse_event(raw: str) -> ScenarioEvent:
    """One event string → :class:`ScenarioEvent`; raises ValueError with a
    message naming the offending token and what would be accepted."""
    m = _EVENT_RE.match(raw.strip())
    if m is None:
        raise _err(raw, "want kind(args)@round[+duration], e.g. "
                        "partition(leaf<->root, symmetric)@3+2")
    kind = m.group("kind")
    if kind not in EVENT_KINDS:
        raise _err(raw, f"unknown event kind {kind!r} "
                        f"(want one of {'/'.join(EVENT_KINDS)})")
    at_round = int(m.group("round"))
    if at_round < 0:
        raise _err(raw, f"round {at_round} is negative — the timeline "
                        f"starts at round 0")
    duration = int(m.group("dur")) if m.group("dur") is not None else 1
    if duration < 1:
        raise _err(raw, f"duration +{duration} must be at least +1 round")
    args = [a.strip() for a in m.group("args").split(",") if a.strip()]
    ev = ScenarioEvent(kind=kind, at_round=at_round, duration=duration,
                       raw=raw.strip())

    if kind == "partition":
        if len(args) != 2:
            raise _err(raw, "partition wants exactly (tierA<->tierB, mode)")
        em = _EDGE_RE.match(args[0])
        if em is None:
            raise _err(raw, f"bad edge {args[0]!r}: want tierA<->tierB "
                            f"with tiers from {'/'.join(TIERS)}")
        a, b = em.group("a"), em.group("b")
        for t in (a, b):
            if t not in TIERS:
                raise _err(raw, f"unknown tier {t!r} "
                                f"(want one of {'/'.join(TIERS)})")
        if a == b:
            raise _err(raw, f"edge {args[0]!r} connects {a!r} to itself")
        if frozenset({a, b}) not in PARTITION_EDGES:
            valid = ", ".join(sorted(
                "<->".join(sorted(e)) for e in PARTITION_EDGES))
            raise _err(raw, f"the stack has no {a}<->{b} seam "
                            f"(cuttable edges: {valid})")
        if args[1] not in PARTITION_MODES:
            raise _err(raw, f"unknown partition mode {args[1]!r} "
                            f"(want one of {'/'.join(PARTITION_MODES)})")
        ev.edge = (a, b)
        ev.mode = args[1]
        return ev

    if kind == "preempt":
        if len(args) != 1:
            raise _err(raw, "preempt wants exactly (slice-N)")
        if _SLICE_RE.match(args[0]) is None:
            raise _err(raw, f"bad slice coordinate {args[0]!r}: want "
                            f"slice-N (the farm's slice ids)")
        ev.subject = args[0]
        return ev

    if kind == "restart_wave":
        if not args or len(args) > 2:
            raise _err(raw, "restart_wave wants (N[, stagger=K])")
        try:
            ev.count = int(args[0])
        except ValueError:
            raise _err(raw, f"bad host count {args[0]!r}: want an integer"
                       ) from None
        if ev.count < 1:
            raise _err(raw, f"host count {ev.count} must be >= 1")
        if len(args) == 2:
            k, sep, v = args[1].partition("=")
            if not sep or k.strip() != "stagger":
                raise _err(raw, f"unknown restart_wave option {args[1]!r} "
                                f"(want stagger=K)")
            try:
                ev.stagger = int(v)
            except ValueError:
                raise _err(raw, f"bad stagger {v!r}: want an integer"
                           ) from None
            if ev.stagger < 1:
                raise _err(raw, f"stagger {ev.stagger} must be >= 1")
        # A wave IS its own duration: ceil(count / stagger) rounds of
        # restarts. An explicit +duration on a wave would either truncate
        # it (silently skipping restarts) or pad it (idle rounds lying in
        # the injected window), so it is rejected.
        if m.group("dur") is not None:
            raise _err(raw, "restart_wave derives its duration from "
                            "count/stagger; drop the +duration")
        ev.duration = -(-ev.count // ev.stagger)
        return ev

    if kind == "churn_storm":
        if len(args) != 1:
            raise _err(raw, "churn_storm wants exactly (N targets per wave)")
        try:
            ev.count = int(args[0])
        except ValueError:
            raise _err(raw, f"bad churn size {args[0]!r}: want an integer"
                       ) from None
        if ev.count < 2:
            raise _err(raw, f"churn size {ev.count} must be >= 2 "
                            f"(each wave removes and adds)")
        return ev

    if kind == "hotspot":
        if len(args) != 1 or not args[0]:
            raise _err(raw, "hotspot wants exactly (podname)")
        ev.subject = args[0]
        return ev

    if kind == "scrape_storm":
        if len(args) != 1:
            raise _err(raw, "scrape_storm wants exactly (N connections)")
        try:
            ev.count = int(args[0])
        except ValueError:
            raise _err(raw, f"bad connection count {args[0]!r}: want an "
                            f"integer") from None
        if ev.count < 1:
            raise _err(raw, f"connection count {ev.count} must be >= 1")
        return ev

    if kind == "dashboard_storm":
        if len(args) != 1:
            raise _err(raw, "dashboard_storm wants exactly "
                            "(N subscriptions)")
        try:
            ev.count = int(args[0])
        except ValueError:
            raise _err(raw, f"bad subscription count {args[0]!r}: want an "
                            f"integer") from None
        if ev.count < 1:
            raise _err(raw, f"subscription count {ev.count} must be >= 1")
        if ev.duration < 2:
            raise _err(raw, "dashboard_storm needs +duration >= 2 — a "
                            "one-round stream never receives a delta, so "
                            "the replay invariant would assert nothing")
        return ev

    if kind == "clock_step":
        if len(args) != 1:
            raise _err(raw, "clock_step wants exactly (±seconds)")
        try:
            ev.step_s = float(args[0])
        except ValueError:
            raise _err(raw, f"bad step {args[0]!r}: want signed seconds, "
                            f"e.g. -45 or +3600") from None
        if ev.step_s == 0:
            raise _err(raw, "a clock step of 0 seconds injects nothing")
        # A step is an INSTANT, not a window: an explicit +duration would
        # either re-step every round (compounding, lying about the fault)
        # or idle (padding the injected window) — same rule as
        # restart_wave's derived duration.
        if m.group("dur") is not None:
            raise _err(raw, "clock_step is instantaneous; drop the "
                            "+duration")
        return ev

    # recv_outage / disk_full / mem_pressure / root_restart
    # (root_restart's +duration is the DOWNTIME window in rounds: the
    # root is dead for the window, restarted when it closes.)
    if args:
        raise _err(raw, f"{kind} takes no arguments (got {args})")
    return ev


def parse_scenario(spec: str) -> list[ScenarioEvent]:
    """Full timeline → event list sorted by start round, with the
    no-overlap rule enforced across events of the same identity."""
    events = [parse_event(raw) for raw in _split_events(spec)]
    if not events:
        raise ValueError(f"scenario timeline {spec!r} contains no events")
    events.sort(key=lambda e: (e.at_round, e.raw))
    by_key: dict[tuple, ScenarioEvent] = {}
    for ev in events:
        prev = by_key.get(ev.overlap_key())
        if prev is not None and ev.at_round < prev.end_round:
            raise ValueError(
                f"scenario events {prev.raw!r} and {ev.raw!r} overlap "
                f"(rounds {ev.at_round}..{min(prev.end_round, ev.end_round) - 1}); "
                f"the engine applies one event per identity at a time — "
                f"stagger them or merge the windows"
            )
        by_key[ev.overlap_key()] = ev
    return events


def total_rounds(events: list[ScenarioEvent], settle: int = 3) -> int:
    """Driver rounds a timeline needs: past the last window plus settle
    rounds for heal/recovery assertions."""
    return max(ev.end_round for ev in events) + settle


# ---------------------------------------------------------------- rendering

# Tier order for canonical edge rendering: node<->leaf, never leaf<->node.
_TIER_RANK: dict[str, int] = {t: i for i, t in enumerate(TIERS)}


def render_event(ev: ScenarioEvent) -> str:
    """One event → its canonical DSL text (the alert-rule ``render_rules``
    pattern). ``parse_event`` accepts every output: edges are tier-ordered,
    defaulted fields (``+1`` duration, ``stagger=1``) are omitted, and the
    kinds whose duration is derived or rejected (restart_wave, clock_step)
    never render one — so render∘parse is idempotent and a minimized fuzz
    reproducer commits as a plain string that replays byte-identically."""
    if ev.kind == "partition":
        a, b = sorted(ev.edge or ("?", "?"), key=lambda t: _TIER_RANK.get(t, 9))
        args = f"{a}<->{b}, {ev.mode}"
    elif ev.kind in ("preempt", "hotspot"):
        args = ev.subject
    elif ev.kind == "restart_wave":
        args = str(ev.count)
        if ev.stagger != 1:
            args += f", stagger={ev.stagger}"
    elif ev.kind in ("churn_storm", "scrape_storm", "dashboard_storm"):
        args = str(ev.count)
    elif ev.kind == "clock_step":
        args = f"{ev.step_s:g}"
    else:
        args = ""
    out = f"{ev.kind}({args})@{ev.at_round}"
    if ev.duration != 1 and ev.kind not in ("restart_wave", "clock_step"):
        out += f"+{ev.duration}"
    return out


def render_timeline(events: list[ScenarioEvent]) -> str:
    """Event list → canonical timeline text. Events sort by
    ``(at_round, rendered)`` — exactly the order ``parse_scenario`` yields
    for canonical text (it sorts on ``raw``, which IS the rendered form
    after one round trip) — so ``render_timeline(parse_scenario(s))`` is a
    fixpoint for every valid ``s``."""
    return "; ".join(
        r for _at, r in sorted((e.at_round, render_event(e)) for e in events)
    )


# --------------------------------------------------------------- generation

@dataclass(frozen=True)
class GenBounds:
    """The fuzzer's draw envelope. Bounds are ENGINE-facing, not
    grammar-facing: the grammar allows unbounded counts and rounds, but a
    generated drill must finish inside a smoke budget against a small
    farm, so coordinates and sizes are capped here. Every value is a cap
    on what :func:`generate_event` draws — the generated text still goes
    through :func:`parse_event`, whose rules remain the only validity
    oracle."""

    # Window coordinates: after the engine's 2 warmup rounds, bounded so
    # total_rounds stays smoke-sized.
    min_round: int = 2
    max_round: int = 8
    max_duration: int = 3
    # Farm-shape caps (the fuzz harness runs small fleets).
    slices: int = 4
    pods: int = 8
    max_wave: int = 6
    max_churn: int = 12
    max_storm_conns: int = 64
    max_dash_subs: int = 32
    # NTP-shaped steps the clock fence must absorb, both directions.
    clock_steps: tuple[float, ...] = (-3600.0, -45.0, 45.0, 3600.0)


def generate_event(kind: str, rng: random.Random,
                   bounds: GenBounds = GenBounds()) -> str:
    """Draw one random event of ``kind`` as DSL text. Each branch mirrors
    ``parse_event``'s argument shape; an unknown kind raises, so the
    every-kind property test fails loudly when a new EVENT_KINDS entry
    lands without a generator branch (the can't-silently-omit rule)."""
    at = rng.randint(bounds.min_round, bounds.max_round)
    dur = rng.randint(1, bounds.max_duration)
    suffix = f"@{at}" + (f"+{dur}" if dur != 1 else "")
    if kind == "partition":
        edges = sorted(
            "<->".join(sorted(e, key=lambda t: _TIER_RANK.get(t, 9)))
            for e in PARTITION_EDGES
        )
        return (f"partition({rng.choice(edges)}, "
                f"{rng.choice(PARTITION_MODES)}){suffix}")
    if kind == "preempt":
        return f"preempt(slice-{rng.randrange(bounds.slices)}){suffix}"
    if kind == "restart_wave":
        count = rng.randint(1, bounds.max_wave)
        stagger = rng.randint(1, count)
        opt = f", stagger={stagger}" if stagger != 1 else ""
        return f"restart_wave({count}{opt})@{at}"
    if kind == "churn_storm":
        return f"churn_storm({rng.randint(2, bounds.max_churn)}){suffix}"
    if kind == "hotspot":
        return f"hotspot(job-{rng.randrange(bounds.pods)}){suffix}"
    if kind == "recv_outage" or kind == "disk_full" \
            or kind == "mem_pressure" or kind == "root_restart":
        return f"{kind}(){suffix}"
    if kind == "scrape_storm":
        return f"scrape_storm({rng.randint(1, bounds.max_storm_conns)}){suffix}"
    if kind == "clock_step":
        return f"clock_step({rng.choice(bounds.clock_steps):g})@{at}"
    if kind == "dashboard_storm":
        dur = rng.randint(2, max(2, bounds.max_duration))
        return f"dashboard_storm({rng.randint(1, bounds.max_dash_subs)})@{at}+{dur}"
    raise ValueError(
        f"no generator for event kind {kind!r} — every EVENT_KINDS entry "
        f"needs a generate_event branch (the fuzzer's coverage depends on "
        f"it)")


def generate_timeline(
    rng: random.Random,
    bounds: GenBounds = GenBounds(),
    max_events: int = 4,
    kinds: tuple[str, ...] = EVENT_KINDS,
    weights: dict[str, float] | None = None,
    reject: Callable[[list[ScenarioEvent]], bool] | None = None,
) -> str:
    """Compose one random VALID timeline and return its canonical text.

    Kinds are drawn (optionally weighted — the fuzzer biases toward dark
    coverage pairs), each event generated, and a draw survives only if
    the grown timeline still parses: :func:`parse_scenario` IS the
    rejection oracle (overlap rule included), so the generator can never
    drift from the grammar. ``reject(events) -> bool`` layers an
    engine-level validity predicate on top (the fuzz harness passes its
    supported-composition rule); rejected draws are redrawn, never
    repaired, so the output distribution stays a pure function of the
    rng stream."""
    want = rng.randint(1, max(1, max_events))
    chosen: list[str] = []
    kind_list = list(kinds)
    weight_list = (
        [float(weights.get(k, 1.0)) for k in kind_list]
        if weights is not None else None
    )
    for _attempt in range(32 * max(want, 1)):
        if len(chosen) >= want:
            break
        if weight_list is not None:
            kind = rng.choices(kind_list, weights=weight_list, k=1)[0]
        else:
            kind = rng.choice(kind_list)
        cand = [*chosen, generate_event(kind, rng, bounds)]
        try:
            events = parse_scenario("; ".join(cand))
        except ValueError:
            continue
        if reject is not None and reject(events):
            continue
        chosen = cand
    if not chosen:
        # Unreachable with sane bounds (any single partition parses), but
        # a degenerate reject predicate must not return an empty timeline.
        raise ValueError("generate_timeline: no valid draw survived the "
                         "rejection oracle — bounds or reject predicate "
                         "exclude every single-event timeline")
    return render_timeline(parse_scenario("; ".join(chosen)))


@dataclass(frozen=True)
class Scenario:
    """One named drill: a timeline plus what the engine should expect."""

    name: str
    timeline: str
    description: str
    # Tunables the engine reads:
    settle_rounds: int = 3
    uses_egress: bool = True
    # Attach a FleetStore to the root (tpu_pod_exporter.store): the
    # store-continuity drill's subject. The engine's --store off flag
    # is this drill's negative control — the continuity invariant still
    # runs and must FAIL on the gap.
    uses_store: bool = False
    # Minimum wall time per engine round. The store drill NEEDS paced
    # rounds: a bucket only becomes durable when the NEXT one opens, and
    # a SIGKILL legitimately loses the open bucket — back-to-back
    # subsecond rounds would cram every pre-kill sample into one open
    # bucket and make the (correct) continuity invariant flaky.
    round_pause_s: float = 0.0
    # Mixed-fleet drills: the engine farm's LAST gpu_slices slices become
    # GPU node pools (gpu_* node surface, family="gpu" rollups). 0 keeps
    # the farm homogeneous — every pre-GPU drill runs byte-identically.
    gpu_slices: int = 0
    # Alerting teeth: when non-None the engine attaches an in-root
    # AlertEvaluator (tpu_pod_exporter.alerting) with the drill rule set
    # and asserts at finish that EXACTLY this set of alert names reached
    # firing — no more, no fewer. () means "alerting on, nothing may
    # fire". None keeps the drill alert-free (pre-alerting drills run
    # byte-identically). --alert-suppression off is the negative
    # control: suppression is disabled, the suppressed alert fires too,
    # and the fired-set assertion must FAIL.
    expected_alerts: tuple[str, ...] | None = None
    # Suppress-aware BOUND mode for GENERATED timelines (the fuzzer): a
    # random composition can make an allowed-but-not-required alert fire
    # legitimately (a symmetric cut leaves no twin to vouch, so
    # TpuRootLeafDown is correct, not a violation). When non-None the
    # finish asserts expected ⊆ fired ⊆ expected ∪ allowed instead of
    # exact equality — and anything the evaluator SUPPRESSED must also
    # sit inside that envelope. None keeps the hand-written drills'
    # exact-set assertion (strictly stronger; never weakened by fuzzing).
    allowed_alerts: tuple[str, ...] | None = None

    def events(self) -> list[ScenarioEvent]:
        return parse_scenario(self.timeline)


# The make scenario-demo set. Round coordinates assume the engine's 2
# warmup rounds (0-1) before any window opens; every scenario ends with
# settle rounds in which the stack must return to oracle-equal health.
SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario(
            name="partition_symmetric",
            timeline="partition(leaf<->root, symmetric)@3+3",
            description=(
                "Every leaf unreachable from the root for 3 rounds: the "
                "root must keep serving last-known shard data (stale-but-"
                "labeled, leaf_up=0, staleness growing), flip /readyz to "
                "degraded, and converge back to oracle-equal after heal. "
                "No alert assertion here: staggered post-heal "
                "re-admission makes one twin reachable while the other "
                "is still quarantined — honestly one-sided to the root, "
                "so partition suspicion transiently (and correctly) "
                "latches. The clean alert drills are the asymmetric ones."
            ),
        ),
        Scenario(
            name="partition_asymmetric",
            timeline="partition(leaf<->root, asymmetric)@3+4",
            description=(
                "The root loses one leaf of every HA pair while the twins "
                "stay reachable: zero series lost, rollups oracle-equal "
                "THROUGH the window (the twin is fresh), partition "
                "suspicion attributable per cut leaf, and the two-level "
                "query plane stays partial-free."
            ),
            expected_alerts=("TpuRootLeafPartitioned",),
        ),
        Scenario(
            name="partition_flapping",
            timeline=(
                "partition(leaf<->root, flapping)@3+6; "
                "partition(root<->recv, flapping)@3+6"
            ),
            description=(
                "Alternating cuts on the root-leaf and egress seams: "
                "freshest-wins must not flap (no series lost any round), "
                "and the egress breaker's half-open probes must not reset "
                "its backoff each open half-round — the ledger stays "
                "exactly-once through the whole window."
            ),
        ),
        Scenario(
            name="preempt_slice",
            timeline="preempt(slice-1)@3+3",
            description=(
                "Slice preemption: every host of slice-1 goes down for 3 "
                "rounds (quarantines learned), then returns — the healed "
                "targets must be re-admitted by the leaf breakers within "
                "the backoff budget, never black-holed as dead."
            ),
            settle_rounds=4,
        ),
        Scenario(
            name="restart_wave",
            timeline="restart_wave(6, stagger=2)@3; hotspot(job-3)@3+4",
            description=(
                "A 6-host rolling restart, 2 per round, composed with a "
                "workload hotspot: never more than one stagger-width of "
                "targets down in any round (read from the exposition), "
                "the hot pod attributable from the workload rollups "
                "while hosts churn, full recovery after the wave."
            ),
        ),
        Scenario(
            name="churn_storm",
            timeline="churn_storm(16)@3+2",
            description=(
                "Target add/remove waves through the shared targets file "
                "plus a workload label-churn storm: bounded reshard "
                "moves, and NO series or RSS leak — the exposition "
                "returns to exactly the expected series set after settle."
            ),
            settle_rounds=4,
        ),
        Scenario(
            name="disk_full",
            timeline="clock_step(-45)@2; disk_full()@3+4",
            description=(
                "The disk budget under the durable-state dirs collapses "
                "(with a backward NTP step landing first): the pressure "
                "governor must shed by policy — egress segment "
                "compaction reclaims acked bytes — bring usage back "
                "down, keep the egress exactly-once ledger intact "
                "through the whole window, and recover rung by rung "
                "after the budget returns. The backward step must not "
                "stall batch shipping (the clock fence)."
            ),
            settle_rounds=4,
        ),
        Scenario(
            name="mem_pressure",
            timeline="mem_pressure()@3+4; hotspot(job-2)@3+3",
            description=(
                "The memory budget over the byte-accounted components "
                "collapses while a workload hotspot churns the caches: "
                "the governor sheds coarse-tiers-last (fleet caches "
                "first), the accounted bytes come back under budget, "
                "every shed is attributable from the governor's own "
                "surface, and RSS growth stays bounded."
            ),
            settle_rounds=4,
        ),
        Scenario(
            name="scrape_storm",
            timeline="scrape_storm(120)@3+2",
            description=(
                "An aggressive keep-alive scrape fleet hammers the root's "
                "serving tier: admission control holds open connections "
                "at the cap (the storm costs rejected requests, never "
                "FDs), a polite scraper's latency stays flat, and the "
                "rejects are attributable from the reject counters."
            ),
            settle_rounds=3,
        ),
        Scenario(
            name="store_continuity",
            timeline="root_restart()@4+2; churn_storm(8)@7+1",
            description=(
                "Fleet TSDB-lite continuity: the root dies SIGKILL-shaped "
                "for 2 rounds mid-retention, restarts on the same store "
                "dir (tier replay), and a reshard churn wave lands right "
                "after. A query over the boundary must be gap-free — the "
                "store fills the dead window from replayed buckets — with "
                "per-row source attribution honest (store rows say store, "
                "live rows say live) and recording-rule series answerable "
                "from the store alone. With --store off the SAME check "
                "must fail on the gap (the negative control CI asserts)."
            ),
            settle_rounds=4,
            uses_egress=False,
            uses_store=True,
            # One finest store bucket (engine tiers: 0.25 s) must
            # finalize per pre-kill round — see round_pause_s above.
            round_pause_s=0.35,
        ),
        Scenario(
            name="mixed_wedge",
            timeline="preempt(slice-1)@3+3; preempt(slice-2)@10+3",
            description=(
                "The GPU parity drill (mixed TPU+GPU tree, 2 of 4 slices "
                "GPU): wedge one whole TPU slice, settle, then wedge one "
                "whole GPU slice the same way. Both wedges must degrade "
                "IDENTICALLY — target_up drops for exactly the victims, "
                "leaf breakers quarantine them, the wedged family's fleet "
                "chip count drops by exactly the victims' chips while the "
                "OTHER family's sums hold steady — and the egress ledger "
                "stays exactly-once through both windows. slice-1 is TPU, "
                "slice-2 GPU (the farm's last gpu_slices slices)."
            ),
            settle_rounds=4,
            gpu_slices=2,
        ),
        Scenario(
            name="dashboard_storm",
            timeline=("dashboard_storm(192)@2+6; "
                      "partition(leaf<->root, asymmetric)@4+2"),
            description=(
                "The streaming dashboard plane under viewer load WITH a "
                "mid-stream partial partition: 192 subscriptions register "
                "against the root's /api/v1/stream and ride per-round "
                "deltas while the root loses one leaf of every HA pair. "
                "Per tick: every sampled subscriber's delta replay equals "
                "the polled answer at the same generation (bit for bit, "
                "through the partition — streamed and polled viewers must "
                "never disagree), zero seq gaps/dups across subscribers, "
                "push latency bounded, and the subscription count "
                "attributable from the tpu_stream_* exposition. With "
                "--stream off the SAME drill must fail (subscriptions "
                "cannot register) — the negative control CI asserts."
            ),
            settle_rounds=3,
        ),
        Scenario(
            name="recv_outage",
            timeline="recv_outage()@3+4",
            description=(
                "The remote-write receiver answers 503 for 4 rounds: the "
                "egress breaker opens (attributable from the egress "
                "exposition), the backlog buffers to disk, and the drain "
                "after heal delivers every batch exactly once. No leaf "
                "is cut, so NO alert may fire — the empty expected set "
                "is asserted, not assumed."
            ),
            settle_rounds=4,
            expected_alerts=(),
        ),
        Scenario(
            name="alert_partition",
            timeline=("partition(leaf<->root, asymmetric)@3+5; "
                      "recv_outage()@2+4"),
            description=(
                "The alerting-teeth drill: an asymmetric cut makes every "
                "cut leaf look down (leaf_up=0) while its HA twin proves "
                "the pod is alive (partition_suspected=1) — "
                "TpuRootLeafPartitioned must fire, TpuRootLeafDown must "
                "be suppressed, and nothing else may fire. The receiver "
                "outage covers the partition onset, so the firing "
                "notifications wedge the alert webhook too: "
                "notifications buffer through the WAL-backed backlog and "
                "the post-heal drain must land a contiguous exactly-once "
                "ledger. The firing states ride the FleetStore as ALERTS "
                "series (queryable source=store) and the stream plane's "
                "alerts route must agree with the evaluator. "
                "--alert-suppression off is the negative control: "
                "TpuRootLeafDown fires as well and the fired-set "
                "assertion must FAIL (CI asserts the non-zero exit)."
            ),
            settle_rounds=4,
            uses_store=True,
            expected_alerts=("TpuRootLeafPartitioned",),
        ),
        Scenario(
            name="fuzz_root_restart_egress",
            timeline="root_restart()@2",
            description=(
                "Fuzzer-found regression (minimized by ddmin from a "
                "4-event composite; replay: fuzz seed 1 trial 7): a dead "
                "root freezes the snapshot, and with the engine's "
                "interval_s=0 shipper the heartbeat ride-along re-framed "
                "the SAME poll instant every round — identical (series, "
                "timestamp) samples under fresh seqs, duplicate samples "
                "in the exactly-once ledger. The shipper now refuses to "
                "frame a poll instant twice (_same_poll_instant); this "
                "drill pins it. The root-process seam was never composed "
                "with an armed egress ledger by any hand-written drill — "
                "the coverage matrix's first dark-pair catch."
            ),
            settle_rounds=3,
        ),
        Scenario(
            name="fuzz_hotspot_churn",
            timeline="hotspot(job-3)@3+4; churn_storm(8)@4+2",
            description=(
                "Fuzzer-found regression (surfaced by generated "
                "hotspot+churn overlaps; minimized by hand — the "
                "campaign artifact predates the coverage ledger): a "
                "churn storm bumping pod_gen "
                "mid-hotspot rotated every pod label, orphaning the hot "
                "index set resolved at window start — the subject rolled "
                "up to zero and attributability collapsed (the old code "
                "admitted the composition was unsupported 'only by "
                "convention'). The engine now re-resolves the hot set "
                "after ALL events have mutated membership each round."
            ),
            settle_rounds=3,
        ),
    )
}

DEFAULT_SCENARIO_ORDER: tuple[str, ...] = tuple(SCENARIOS)
