"""End-to-end hardware validation: live exporter + real load + assertions.

The instrument for VERDICT r1 #4/#5 — run it on a machine with a working
accelerator runtime and it produces the round artifact showing the
exporter's values *respond to real load* (the reference never had such a
check; its values were believed, not validated — `main.go:147-150`):

    python -m tpu_pod_exporter.hwcheck --out HWCHECK.json --record-to trace.jsonl

Three phases against a live exporter scraped over real HTTP:
  1. **idle** — baseline HBM/duty readings.
  2. **load** — hold a large HBM allocation and spin MXU matmul chains
     (``loadgen``) while scraping.
  3. **release** — free the allocation, scrape again.

Assertions: HBM used rises under load and falls after release; duty cycle
responds when the backend reports it (the jax backend cannot — that is
documented in the artifact, and the libtpu service is probed so the
artifact records what the runtime's metric surface actually serves).

``--backend fake`` drives the identical orchestration against a scripted
backend — how the harness itself is tested with zero hardware.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.request


def _scrape(base: str) -> dict:
    """One /metrics scrape → {(name, chip_id): value} for chip families."""
    from tpu_pod_exporter.metrics.parse import parse_exposition

    with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
        text = resp.read().decode()
    out: dict = {}
    for s in parse_exposition(text):
        if s.name in (
            "tpu_hbm_used_bytes",
            "tpu_hbm_total_bytes",
            "tpu_hbm_peak_bytes",
            "tpu_tensorcore_duty_cycle_percent",
        ):
            out[(s.name, s.labels.get("chip_id", ""))] = s.value
    return out


def _totals(series: dict) -> dict:
    """Sum per family across chips; duty is max (any busy core counts)."""
    used = sum(v for (n, _), v in series.items() if n == "tpu_hbm_used_bytes")
    total = sum(v for (n, _), v in series.items() if n == "tpu_hbm_total_bytes")
    duties = [
        v for (n, _), v in series.items()
        if n == "tpu_tensorcore_duty_cycle_percent"
    ]
    peaks = [v for (n, _), v in series.items() if n == "tpu_hbm_peak_bytes"]
    return {
        "hbm_used_bytes": used,
        "hbm_total_bytes": total,
        "hbm_peak_bytes_max": max(peaks) if peaks else None,
        "duty_cycle_max_percent": max(duties) if duties else None,
        "series": len(series),
    }


class FakeStimulus:
    """Flips the fake backend's script values — tests the orchestration."""

    def __init__(self, backend):
        # --record-to wraps the backend in a RecordingBackend; unwrap.
        scripts = getattr(backend, "_scripts", None)
        if scripts is None:
            scripts = backend._inner._scripts
        self._scripts = scripts

    def start(self) -> None:
        for s in self._scripts:
            s.hbm_used_bytes = 8 * 1024**3
            s.duty_cycle_percent = 85.0

    def stop(self) -> None:
        for s in self._scripts:
            s.hbm_used_bytes = 1 * 1024**3
            s.duty_cycle_percent = 0.0


class JaxStimulus:
    """Real load: hold an HBM allocation + spin bf16 matmul chains."""

    def __init__(self, hbm_bytes: int = 1 << 30, width: int = 1024):
        self._hbm_bytes = hbm_bytes
        self._width = width
        self._held = None
        self._burning = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        import jax.numpy as jnp

        from tpu_pod_exporter.loadgen.workload import (
            burn_step,
            hbm_fill,
            init_params,
        )

        self._held = hbm_fill(self._hbm_bytes)
        params = init_params(width=self._width, depth=4)
        x = jnp.ones((256, self._width), jnp.bfloat16)
        self._burning.set()

        def burn() -> None:
            while self._burning.is_set():
                burn_step(params, x, iters=20).block_until_ready()

        self._thread = threading.Thread(
            target=burn, name="tpu-hwcheck-burn", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._burning.clear()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._held = None  # drop the reference; allocator reclaims


def run_check(
    backend: str = "jax",
    idle_s: float = 2.0,
    load_s: float = 8.0,
    record_to: str = "",
    libtpu_addr: str = "localhost:8431",
    _app=None,
    _stimulus=None,
) -> dict:
    """Run the three-phase check; returns the artifact dict."""
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.config import ExporterConfig

    jax_mode = None
    if backend == "jax":
        # Same tunnel fence as __graft_entry__.entry(): never let an
        # in-process JAX init hang on a dead tunnel; a CPU fallback is
        # recorded in the artifact (the checks will then fail honestly —
        # CPU devices report no memory stats — instead of hanging).
        from tpu_pod_exporter.jaxenv import ensure_usable_backend

        jax_mode = ensure_usable_backend()

    cfg = ExporterConfig(
        port=0,
        host="127.0.0.1",
        interval_s=0.25,
        backend=backend,
        attribution="none",
        fake_chips=2 if backend == "fake" else 0,
        record_to=record_to,
    )
    app = _app if _app is not None else ExporterApp(cfg)
    report: dict = {"backend": backend, "phases": {}, "checks": {}, "ok": False}
    if jax_mode is not None:
        report["jax_backend_mode"] = jax_mode  # "default" | "pinned-cpu"
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        if _stimulus is not None:
            stim = _stimulus
        elif backend == "fake":
            stim = FakeStimulus(app.backend)
        else:
            stim = JaxStimulus()

        time.sleep(idle_s)
        idle = _totals(_scrape(base))
        report["phases"]["idle"] = idle

        stim.start()
        try:
            time.sleep(load_s)
            loaded = _totals(_scrape(base))
            report["phases"]["load"] = loaded
        finally:
            stim.stop()

        time.sleep(max(idle_s, 1.0))
        after = _totals(_scrape(base))
        report["phases"]["release"] = after

        checks = report["checks"]
        checks["hbm_rises_under_load"] = (
            loaded["hbm_used_bytes"] > idle["hbm_used_bytes"]
        )
        checks["hbm_falls_after_release"] = (
            after["hbm_used_bytes"] < loaded["hbm_used_bytes"]
        )
        if loaded["duty_cycle_max_percent"] is None:
            checks["duty_cycle_responds"] = None  # backend doesn't report it
            report["duty_cycle_note"] = (
                f"backend {backend!r} reports no duty cycle; the libtpu "
                "probe below records whether the runtime serves one"
            )
        else:
            checks["duty_cycle_responds"] = (
                loaded["duty_cycle_max_percent"]
                > (idle["duty_cycle_max_percent"] or 0.0)
            )
        report["ok"] = all(v is not False for v in checks.values())
    finally:
        app.stop()

    # Record what the local libtpu metric service actually serves (the
    # ground-truth half of the artifact; unreachable is itself a finding).
    try:
        from tpu_pod_exporter.probe import probe

        lp = probe(libtpu_addr, timeout_s=2.0)
        report["libtpu"] = {
            "addr": libtpu_addr,
            "reachable": lp["reachable"],
            "supported": lp["supported"],
            "served_metrics": sorted(lp["metrics"]),
        }
    except Exception as e:  # noqa: BLE001 — the probe must not fail the check
        # Same shape as the success case so artifact consumers never fork.
        report["libtpu"] = {
            "addr": libtpu_addr,
            "reachable": False,
            "supported": None,
            "served_metrics": [],
            "error": str(e),
        }
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--backend", default="jax", choices=["jax", "fake"])
    p.add_argument("--idle-s", type=float, default=2.0)
    p.add_argument("--load-s", type=float, default=8.0)
    p.add_argument("--record-to", default="")
    p.add_argument("--libtpu-addr", default="localhost:8431")
    p.add_argument("--out", default="", help="write the artifact JSON here")
    args = p.parse_args(argv)
    report = run_check(
        backend=args.backend,
        idle_s=args.idle_s,
        load_s=args.load_s,
        record_to=args.record_to,
        libtpu_addr=args.libtpu_addr,
    )
    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
