"""Scenario fuzzer — generate the drill corpus, minimize failures, track
(seam × invariant) coverage.

The scenario engine holds ~16 hand-written timelines against a far larger
injection surface. This module is breadth-by-generation: a seeded
generator composes random *valid* timelines from every DSL event kind
(:func:`tpu_pod_exporter.scenario.generate_timeline` — the parser's
overlap/validity rules ARE the rejection oracle), drives each through the
full scenario engine with all per-tick invariants armed, and on failure
runs a delta-debugging minimizer that shrinks the timeline to a minimal
reproducer emitted as canonical DSL text plus the exact (seed, trial)
coordinates for deterministic replay.

Determinism contract — the whole point of the design:

- ``timeline_for_trial(seed, trial)`` is a pure function: generation
  draws only from ``random.Random(f"{seed}:{trial}:timeline")`` plus the
  coverage-bias weights, which are themselves derived from the GENERATED
  timelines of trials ``0..trial-1`` (never from run outcomes, which
  would make replay depend on wall-clock-flavored engine state).
- The engine run is seeded the same way every named drill is; the
  injected schedule (rounds, active windows, effective cuts — see
  :func:`schedule_trace`) is identical across replays of one trial.
- So ``--replay SEED:TRIAL`` (also reachable as the engine's
  ``--fuzz-replay``) rebuilds the exact failing run from two integers.

Coverage: a :class:`CoverageLedger` tracks which (injection seam ×
invariant) pairs each trial exercised. Seams are enumerated from the
chaos seam registry (:data:`tpu_pod_exporter.chaos.SEAM_REGISTRY`) and
cross-checked against :data:`KIND_SEAMS` in BOTH directions — an
injector registered without a generator path, or a generator naming a
ghost seam, fails :func:`seam_map_problems` (asserted under tier-1), so
a seam added later can't be silently omitted. Generation is biased
toward kinds that reach still-dark seams.

CLI (``make fuzz-smoke``)::

    python -m tpu_pod_exporter.fuzz --seeds 5,7 --trials 6 \\
        --state-root fuzz-state

On failure: the original + minimized timelines, the engine result, and
the per-tick trace land under ``<state-root>/failure-s<seed>-t<trial>/``
(uploaded as CI artifacts), and the exit is non-zero. See RUNBOOK
"Reproducing a fuzzer failure".
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import shutil
import sys
from collections.abc import Callable, Iterable

from tpu_pod_exporter.chaos import SEAM_REGISTRY, registered_seams
from tpu_pod_exporter.scenario import (
    EVENT_KINDS,
    INVARIANTS,
    GenBounds,
    Scenario,
    ScenarioEvent,
    generate_timeline,
    parse_scenario,
    render_event,
    render_timeline,
)

# ----------------------------------------------------------- trial envelope

# Fixed per-trial engine shape: replay from (seed, trial) alone requires
# these to be constants, not flags. Small fleet — a trial is a smoke
# drill, not a soak; the named demo set keeps the big fleets.
TRIAL_TARGETS = 24
TRIAL_SHARDS = 2
TRIAL_CHIPS = 1
TRIAL_MAX_EVENTS = 4
TRIAL_BOUNDS = GenBounds()

# Invariants a generated trial arms BY CONSTRUCTION (egress + alerting
# always attached; the three always-on tick checks). oracle_equality
# arms lazily at runtime — the ledger records what the run reports.
TRIAL_STATIC_INVARIANTS: tuple[str, ...] = (
    "egress_ledger", "alerts_correctness", "bounded_staleness",
    "fault_attribution", "series_rss_leaks",
)

# ------------------------------------------------------------ seam mapping

# DSL event kind → chaos seams it injects through. partition is resolved
# per edge by seams_of (one wire seam per cut edge). Cross-checked
# against SEAM_REGISTRY in both directions by seam_map_problems().
KIND_SEAMS: dict[str, tuple[str, ...]] = {
    "partition": ("wire:node-leaf", "wire:leaf-root", "wire:root-recv"),
    "preempt": ("target-process",),
    "restart_wave": ("target-process",),
    "churn_storm": ("membership", "workload"),
    "hotspot": ("workload",),
    "recv_outage": ("receiver",),
    "disk_full": ("disk",),
    "mem_pressure": ("memory",),
    "scrape_storm": ("serving",),
    "clock_step": ("wallclock",),
    "root_restart": ("root-process",),
    "dashboard_storm": ("stream",),
}

_TIER_ORDER = {"node": 0, "leaf": 1, "root": 2, "recv": 3}


def seams_of(events: list[ScenarioEvent]) -> set[str]:
    """The chaos seams a timeline injects through. An unmapped kind
    yields an ``unmapped:`` pseudo-seam the ledger flags as unregistered
    — a new EVENT_KINDS entry cannot silently contribute zero
    coverage."""
    out: set[str] = set()
    for ev in events:
        if ev.kind == "partition":
            a, b = sorted(ev.edge or ("?", "?"),
                          key=lambda t: _TIER_ORDER.get(t, 9))
            out.add(f"wire:{a}-{b}")
        else:
            out.update(KIND_SEAMS.get(ev.kind, (f"unmapped:{ev.kind}",)))
    return out


def seam_map_problems() -> list[str]:
    """Both directions of the registry cross-check: every kind mapped,
    every mapped seam registered, every registered seam reachable by
    some kind. Non-empty means the coverage matrix would lie — asserted
    under tier-1 and checked again by the CLI before any trial runs."""
    problems: list[str] = []
    for kind in EVENT_KINDS:
        if kind not in KIND_SEAMS:
            problems.append(
                f"event kind {kind!r} has no KIND_SEAMS entry — its "
                f"trials would count zero seam coverage")
    mapped: set[str] = set()
    for kind, seams in KIND_SEAMS.items():
        mapped.update(seams)
        for s in seams:
            if s not in SEAM_REGISTRY:
                problems.append(
                    f"kind {kind!r} maps to unregistered seam {s!r} "
                    f"(register it in tpu_pod_exporter.chaos)")
    for s in registered_seams():
        if s not in mapped:
            problems.append(
                f"registered seam {s!r} unreachable by any event kind — "
                f"the fuzzer can never exercise it (map a kind to it or "
                f"drop the registration)")
    return problems


# ---------------------------------------------------------- alert envelope

def expected_alert_bounds(
    events: list[ScenarioEvent],
) -> tuple[tuple[str, ...], tuple[str, ...]]:
    """Derive (required, allowed) alert names for a generated timeline
    under the engine's drill rule set. Required alerts MUST fire; allowed
    ones MAY (a random composition can make them legitimately correct —
    a symmetric cut leaves no twin to vouch, so TpuRootLeafDown firing is
    the evaluator being right). Anything outside the union may neither
    fire nor be suppressed (the bound-mode verdict)."""
    required: set[str] = set()
    allowed: set[str] = set()
    root_dead = [(e.at_round, e.end_round) for e in events
                 if e.kind == "root_restart"]
    if root_dead:
        # A fresh root's first merge rounds can transiently drop/suspect
        # leaves; either alert may (correctly) latch around the boundary.
        allowed |= {"TpuRootLeafPartitioned", "TpuRootLeafDown"}
    for e in events:
        if e.kind != "partition" or frozenset(e.edge or ()) != frozenset(
                {"leaf", "root"}):
            continue
        overlaps_dead = any(
            e.at_round < dead_end and dead_start < e.end_round
            for dead_start, dead_end in root_dead
        )
        if e.mode == "asymmetric":
            # One-sided cut with reachable twins: suspicion must latch
            # and the partition alert must fire — unless the root is dead
            # for (part of) the window and may never observe the cut.
            allowed |= {"TpuRootLeafPartitioned", "TpuRootLeafDown"}
            if not overlaps_dead:
                required.add("TpuRootLeafPartitioned")
        else:
            # symmetric/flapping: no twin reachable on cut rounds, so
            # LeafDown is legitimate; staggered heal re-admission can
            # also transiently latch suspicion (the partition_symmetric
            # drill's documented shape).
            allowed |= {"TpuRootLeafPartitioned", "TpuRootLeafDown"}
    return tuple(sorted(required)), tuple(sorted(allowed - required))


def scenario_for_timeline(timeline: str, name: str) -> Scenario:
    """Wrap one generated timeline as an engine Scenario with every
    armable invariant on: egress attached, alerting attached in
    suppress-aware bound mode with the derived envelope."""
    required, allowed = expected_alert_bounds(parse_scenario(timeline))
    return Scenario(
        name=name,
        timeline=timeline,
        description="fuzzer-generated timeline",
        settle_rounds=3,
        uses_egress=True,
        expected_alerts=required,
        allowed_alerts=allowed,
    )


# --------------------------------------------------------- coverage ledger

class CoverageLedger:
    """The (injection seam × invariant) coverage matrix across trials.

    Rows come from the chaos seam registry at construction time (never a
    hardcoded list — a later-registered seam appears as a dark row, not
    a missing one); columns from the engine's INVARIANTS. ``record``
    flags any seam outside the registry instead of counting it, so the
    report's ``unregistered_seams`` is the loud path for drift."""

    def __init__(self) -> None:
        self.seams: tuple[str, ...] = registered_seams()
        self.invariants: tuple[str, ...] = INVARIANTS
        self.trials = 0
        self.pair_trials: dict[tuple[str, str], int] = {}
        self.seam_trials: dict[str, int] = {s: 0 for s in self.seams}
        self.unregistered: set[str] = set()

    def record(self, seams: set[str], invariants: Iterable[str]) -> None:
        """One trial's coverage: every (seam, armed-invariant) pair it
        exercised."""
        self.trials += 1
        armed = tuple(invariants)
        for s in seams:
            if s not in SEAM_REGISTRY:
                self.unregistered.add(s)
                continue
            self.seam_trials[s] = self.seam_trials.get(s, 0) + 1
            for inv in armed:
                self.pair_trials[(s, inv)] = (
                    self.pair_trials.get((s, inv), 0) + 1)

    def dark_pairs(self) -> list[tuple[str, str]]:
        """(seam, invariant) pairs no trial has exercised yet — the
        generation bias's target."""
        return [(s, inv) for s in self.seams for inv in self.invariants
                if (s, inv) not in self.pair_trials]

    def report(self) -> dict:
        matrix = {
            s: {inv: self.pair_trials.get((s, inv), 0)
                for inv in self.invariants}
            for s in self.seams
        }
        pairs_total = len(self.seams) * len(self.invariants)
        dark = self.dark_pairs()
        return {
            "trials": self.trials,
            "seams": list(self.seams),
            "invariants": list(self.invariants),
            "matrix": matrix,
            "pairs_total": pairs_total,
            "pairs_covered": pairs_total - len(dark),
            "dark_pairs": [list(p) for p in dark],
            "unregistered_seams": sorted(self.unregistered),
        }


def kind_weights(seam_trials: dict[str, int]) -> dict[str, float]:
    """Generation bias: kinds whose seams are still dark draw more
    often. Seam-level darkness is the right proxy for pair-level
    darkness here because a trial's armed-invariant set is fixed by
    construction (TRIAL_STATIC_INVARIANTS) — once a seam has been hit,
    its reachable pairs light together."""
    out: dict[str, float] = {}
    for kind, seams in KIND_SEAMS.items():
        dark = sum(1 for s in seams if seam_trials.get(s, 0) == 0)
        out[kind] = 1.0 + 2.0 * dark
    return out


# -------------------------------------------------------------- generation

def _trial_rng(seed: int, trial: int) -> random.Random:
    return random.Random(f"{seed}:{trial}:timeline")


def timeline_for_trial(seed: int, trial: int) -> str:
    """The pure (seed, trial) → canonical timeline function. Bias weights
    are reconstructed by replaying GENERATION (not engine runs) of the
    earlier trials of this seed — cheap, and the reason a reproducer is
    two integers instead of a corpus file."""
    counts: dict[str, int] = {s: 0 for s in registered_seams()}
    for t in range(trial + 1):
        tl = generate_timeline(
            _trial_rng(seed, t), TRIAL_BOUNDS, TRIAL_MAX_EVENTS,
            weights=kind_weights(counts),
        )
        if t == trial:
            return tl
        for s in seams_of(parse_scenario(tl)):
            if s in counts:
                counts[s] += 1
    raise AssertionError("unreachable")


def schedule_trace(trace: list[dict]) -> list[dict]:
    """The deterministic projection of a per-tick engine trace: the
    injected schedule (round, active windows, effective cuts — flap
    phases included, they are seeded). Wall-clock-paced fields (breaker
    re-admission, stale-serve flips) are excluded by design; the
    determinism audit asserts THIS projection is identical across two
    runs of one (seed, trial)."""
    return [{"round": t["round"], "active": t["active"],
             "cuts": t["cuts"]} for t in trace]


# --------------------------------------------------------------- minimizer

def _revalidate(events: list[ScenarioEvent]) -> list[ScenarioEvent] | None:
    """Canonical render→parse round trip; None when the candidate is not
    a valid timeline (overlaps introduced by a shrink, empty list). The
    minimizer only ever hands VALIDATED candidates to its predicate."""
    if not events:
        return None
    try:
        return parse_scenario(render_timeline(events))
    except ValueError:
        return None


def _shrink_variants(ev: ScenarioEvent) -> list[ScenarioEvent]:
    """Single-field shrinks of one event, strongest first. Every variant
    goes through render→parse (restart_wave re-derives its duration;
    anything the grammar rejects is dropped here, not downstream)."""
    out: list[ScenarioEvent] = []

    def variant(**kw: object) -> None:
        cand = dataclasses.replace(ev, **kw)  # type: ignore[arg-type]
        if cand.kind == "restart_wave":
            cand.duration = -(-cand.count // cand.stagger)
        try:
            parsed = parse_scenario(render_event(cand))
        except ValueError:
            return
        out.append(parsed[0])

    floor = 2 if ev.kind == "dashboard_storm" else 1
    if ev.kind not in ("restart_wave", "clock_step") and ev.duration > floor:
        variant(duration=floor)
    count_floors = {"restart_wave": 1, "churn_storm": 2,
                    "scrape_storm": 1, "dashboard_storm": 1}
    if ev.kind in count_floors and ev.count > count_floors[ev.kind]:
        variant(count=count_floors[ev.kind],
                stagger=min(ev.stagger, count_floors[ev.kind])
                if ev.kind == "restart_wave" else ev.stagger)
    if ev.kind == "restart_wave" and ev.stagger > 1:
        variant(stagger=1)
    if ev.kind == "clock_step" and abs(ev.step_s) > 45.0:
        variant(step_s=45.0 if ev.step_s > 0 else -45.0)
    if ev.at_round > TRIAL_BOUNDS.min_round:
        variant(at_round=TRIAL_BOUNDS.min_round)
    return out


def minimize(
    events: list[ScenarioEvent],
    failing: Callable[[list[ScenarioEvent]], bool],
    max_checks: int = 64,
) -> list[ScenarioEvent]:
    """Delta-debugging minimizer: ddmin over the event list, then greedy
    per-event field shrinks. ``failing(candidate)`` returns True when the
    candidate still fails; candidates are enumerated in a fixed order and
    validated (render→parse) BEFORE the predicate sees them, so shrink
    steps never produce an invalid timeline and the result is
    deterministic for a deterministic predicate. ``max_checks`` bounds
    predicate invocations (each may be a full engine run)."""
    checks = 0

    def still_fails(cand: list[ScenarioEvent]) -> bool:
        nonlocal checks
        if checks >= max_checks:
            return False
        valid = _revalidate(cand)
        if valid is None:
            return False
        checks += 1
        return failing(valid)

    cur = _revalidate(events)
    if cur is None:
        raise ValueError("minimize: the input timeline is not valid")

    # Phase 1: classic ddmin to a 1-minimal SUBSET of events.
    granularity = 2
    while len(cur) >= 2:
        chunk = max(len(cur) // granularity, 1)
        reduced = False
        for i in range(0, len(cur), chunk):
            cand = cur[:i] + cur[i + chunk:]
            if cand and still_fails(cand):
                cur = _revalidate(cand) or cur
                granularity = max(granularity - 1, 2)
                reduced = True
                break
        if not reduced:
            if chunk == 1:
                break
            granularity = min(granularity * 2, len(cur))

    # Phase 2: greedy field shrinks, repeated until a full pass holds.
    improved = True
    while improved and checks < max_checks:
        improved = False
        for idx in range(len(cur)):
            for shrunk in _shrink_variants(cur[idx]):
                cand = [*cur[:idx], shrunk, *cur[idx + 1:]]
                if still_fails(cand):
                    cur = _revalidate(cand) or cur
                    improved = True
                    break
            if improved:
                break
    return cur


# ------------------------------------------------------------- trial runs

def run_trial(seed: int, trial: int, timeline: str,
              state_dir: str) -> tuple[dict, list[dict]]:
    """One generated timeline through the full engine (same _Run the
    named drills use — zero harness drift).

    The state dir is wiped first: a leftover WAL from a previous run
    makes the shipper resume its persisted seq counter against a fresh
    receiver ledger, which the contiguity invariant would (correctly,
    but spuriously for replay purposes) flag as acked-sample loss.
    """
    from tpu_pod_exporter.loadgen.scenario import run_one

    shutil.rmtree(state_dir, ignore_errors=True)
    scn = scenario_for_timeline(timeline, f"fuzz_s{seed}_t{trial}")
    return run_one(scn, TRIAL_TARGETS, TRIAL_SHARDS, TRIAL_CHIPS,
                   state_dir, seed)


def _engine_predicate(seed: int,
                      min_root: str) -> Callable[[list[ScenarioEvent]], bool]:
    """The minimizer's predicate for real failures: render the candidate,
    run it on a fresh stack, True when the run fails. Each candidate gets
    its own state dir so reproducer state survives for the artifact."""
    counter = [0]

    def failing(events: list[ScenarioEvent]) -> bool:
        counter[0] += 1
        result, _trace = run_trial(
            seed, 10_000 + counter[0], render_timeline(events),
            os.path.join(min_root, f"min-{counter[0]:03d}"))
        return not result["ok"]

    return failing


def _write_failure_artifacts(state_root: str, seed: int, trial: int,
                             timeline: str, minimized: str,
                             result: dict, trace: list[dict]) -> str:
    fdir = os.path.join(state_root, f"failure-s{seed}-t{trial}")
    os.makedirs(fdir, exist_ok=True)
    def _put(name: str, text: str) -> None:
        with open(os.path.join(fdir, name), "w", encoding="utf-8") as f:
            f.write(text)
    _put("timeline.txt", timeline + "\n")
    _put("minimized.txt", minimized + "\n")
    _put("replay.txt",
         f"python -m tpu_pod_exporter.loadgen.scenario "
         f"--fuzz-replay {seed}:{trial}\n"
         f"python -m tpu_pod_exporter.loadgen.scenario "
         f"--timeline '{minimized}'\n")
    _put("result.json", json.dumps(result, indent=1, default=str))
    _put("scenario-trace.json", json.dumps(trace, indent=1, default=str))
    return fdir


def replay(seed: int, trial: int, state_root: str = "fuzz-state") -> int:
    """Deterministic replay of one trial from its coordinates alone (the
    engine's ``--fuzz-replay`` delegates here). Regenerates the timeline,
    reruns it, writes the same artifacts a fuzzing run would."""
    timeline = timeline_for_trial(seed, trial)
    print(f"fuzz replay s{seed} t{trial}: {timeline}")
    state_dir = os.path.join(state_root, f"replay-s{seed}-t{trial}")
    result, trace = run_trial(seed, trial, timeline, state_dir)
    if result["ok"]:
        print(f"fuzz replay s{seed} t{trial} OK "
              f"({result.get('trace_ticks')} ticks)")
        return 0
    fdir = _write_failure_artifacts(state_root, seed, trial, timeline,
                                    timeline, result, trace)
    print(f"fuzz replay s{seed} t{trial} FAILED: "
          f"{'; '.join(result.get('problems', [])[:2])} — artifacts in "
          f"{fdir}", file=sys.stderr)
    return 1


# -------------------------------------------------------------------- CLI

def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpu-scenario-fuzz",
        description="Seeded scenario fuzzer: random valid timelines "
                    "through the full engine with every invariant armed; "
                    "failures delta-debugged to minimal reproducers; "
                    "(seam x invariant) coverage tracked against the "
                    "chaos seam registry (make fuzz-smoke).",
    )
    p.add_argument("--seeds", default="5",
                   help="comma-separated seed list (each runs --trials "
                        "trials)")
    p.add_argument("--trials", type=int, default=6,
                   help="trials per seed")
    p.add_argument("--state-root", default="fuzz-state",
                   help="per-trial state dirs + coverage.json + failure "
                        "artifact dirs (uploaded by CI on failure)")
    p.add_argument("--replay", default="", metavar="SEED:TRIAL",
                   help="replay one trial deterministically from its "
                        "coordinates instead of fuzzing")
    p.add_argument("--max-shrink-runs", type=int, default=24,
                   help="minimizer budget: engine runs spent shrinking "
                        "one failure")
    p.add_argument("--keep-going", action="store_true",
                   help="run every trial even after a failure (default: "
                        "stop at the first, like the scenario demo)")
    p.add_argument("--log-level", default="warning")
    ns = p.parse_args(argv)

    from tpu_pod_exporter import utils as _utils
    _utils.setup_logging(ns.log_level)

    problems = seam_map_problems()
    if problems:
        for msg in problems:
            print(f"SEAM REGISTRY DRIFT: {msg}", file=sys.stderr)
        return 2

    if ns.replay:
        try:
            seed_s, _, trial_s = ns.replay.partition(":")
            seed, trial = int(seed_s), int(trial_s)
        except ValueError:
            p.error(f"--replay wants SEED:TRIAL (got {ns.replay!r})")
        return replay(seed, trial, state_root=ns.state_root)

    try:
        seeds = [int(s) for s in ns.seeds.split(",") if s.strip()]
    except ValueError:
        p.error(f"--seeds wants comma-separated integers "
                f"(got {ns.seeds!r})")
    os.makedirs(ns.state_root, exist_ok=True)
    ledger = CoverageLedger()
    failures: list[tuple[int, int]] = []
    for seed in seeds:
        # Bias weights replay generation per seed (see timeline_for_trial
        # — the incremental form of the same pure function).
        counts: dict[str, int] = {s: 0 for s in registered_seams()}
        for trial in range(ns.trials):
            timeline = generate_timeline(
                _trial_rng(seed, trial), TRIAL_BOUNDS, TRIAL_MAX_EVENTS,
                weights=kind_weights(counts),
            )
            events = parse_scenario(timeline)
            seams = seams_of(events)
            for s in seams:
                if s in counts:
                    counts[s] += 1
            state_dir = os.path.join(ns.state_root, f"s{seed}-t{trial}")
            result, trace = run_trial(seed, trial, timeline, state_dir)
            ledger.record(
                seams,
                result.get("invariants_armed") or TRIAL_STATIC_INVARIANTS)
            status = "ok" if result["ok"] else "FAILED"
            print(f"  s{seed} t{trial:<3} {status:<7} {timeline}",
                  flush=True)
            if result["ok"]:
                continue
            failures.append((seed, trial))
            print(f"    problems: "
                  f"{'; '.join(result.get('problems', [])[:2])}",
                  flush=True)
            minimized_events = minimize(
                events,
                _engine_predicate(
                    seed, os.path.join(ns.state_root,
                                       f"minimize-s{seed}-t{trial}")),
                max_checks=ns.max_shrink_runs,
            )
            minimized = render_timeline(minimized_events)
            fdir = _write_failure_artifacts(
                ns.state_root, seed, trial, timeline, minimized,
                result, trace)
            print(f"    minimized: {minimized}\n"
                  f"    replay:    python -m "
                  f"tpu_pod_exporter.loadgen.scenario --fuzz-replay "
                  f"{seed}:{trial}\n"
                  f"    artifacts: {fdir}", flush=True)
            if not ns.keep_going:
                break
        if failures and not ns.keep_going:
            break

    report = ledger.report()
    try:
        with open(os.path.join(ns.state_root, "coverage.json"), "w",
                  encoding="utf-8") as f:
            json.dump(report, f, indent=1)
    except OSError:
        pass
    print(f"fuzz: {report['trials']} trial(s), "
          f"{report['pairs_covered']}/{report['pairs_total']} "
          f"(seam x invariant) pairs covered, "
          f"{len(report['dark_pairs'])} dark, "
          f"{len(failures)} failure(s)")
    if report["unregistered_seams"]:
        print(f"fuzz: UNREGISTERED seams referenced: "
              f"{report['unregistered_seams']}", file=sys.stderr)
        return 2
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
