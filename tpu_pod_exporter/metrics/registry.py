"""Snapshot-based metrics model with Prometheus text exposition.

Design (deliberate delta from the reference, ``main.go:21-42``):

The reference mutates long-lived ``GaugeVec`` cells in place and never deletes
them, so a series for a dead pod persists at its last value forever
(``main.go:147-150``; no ``Delete``/``Reset`` anywhere). Here the collector
builds a complete :class:`Snapshot` every poll and atomically swaps it in.
Stale-series garbage collection is therefore *structural*: a series that is
not re-emitted simply does not exist in the next snapshot. This is the
series-lifecycle semantics the pod-churn config requires.

The snapshot also pre-renders the Prometheus text format once, at poll time.
A scrape serves the cached bytes — O(1), no label formatting, no float
rendering, no lock contention with the poll loop beyond one reference swap.
This preserves (and sharpens) the reference's one good architectural
property: collection decoupled from scraping (``main.go:67-72`` vs the poll
loop at ``main.go:74-157``).

Counters are supported for monotonic device counters (e.g. ICI transferred
bytes); their *state* lives with the owner (the collector), the snapshot just
renders current values.
"""

from __future__ import annotations

import math
import threading
import time
from array import array
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Iterable, Mapping, Sequence

try:  # C-speed value-vector diff for the splice render; optional.
    import numpy as _np
except ImportError:  # pragma: no cover - numpy is a declared dependency
    _np = None  # type: ignore[assignment]

GAUGE = "gauge"
COUNTER = "counter"
HISTOGRAM = "histogram"

_VALID_TYPES = (GAUGE, COUNTER, HISTOGRAM)


@dataclass(frozen=True)
class MetricSpec:
    """Static definition of one metric family (name, help, type, label names).

    Analog of the reference's ``prometheus.NewGaugeVec`` options
    (``main.go:22-35``), except label names are part of a frozen schema and
    validated once.
    """

    name: str
    help: str
    type: str = GAUGE
    label_names: tuple[str, ...] = ()
    # Histogram child families (_bucket/_sum/_count) render their sample
    # lines under the PARENT family's single `# TYPE <name> histogram`
    # header, so their own HELP/TYPE lines are suppressed. Everything else
    # about them (layout cache, native render, value formatting) is the
    # ordinary family machinery — that is the point of this representation.
    suppress_header: bool = False
    # Raw-lines family: each sample's label "tuple" is a 1-tuple holding the
    # FULLY pre-rendered series prefix (``name_bucket{phase="x",le="0.1"}``).
    # This is what lets one family carry a histogram's _bucket/_count/_sum
    # lines in the per-label-set order OpenMetrics requires (MetricPoints of
    # one label set must be contiguous) while still riding the FamilyLayout
    # and native render paths, which only ever see opaque prefix bytes.
    raw_lines: bool = False

    def __post_init__(self) -> None:
        if self.type not in _VALID_TYPES:
            raise ValueError(f"metric type must be one of {_VALID_TYPES}: {self.type}")
        if not _valid_metric_name(self.name):
            raise ValueError(f"invalid metric name: {self.name!r}")
        for ln in self.label_names:
            if not _valid_label_name(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        if len(set(self.label_names)) != len(self.label_names):
            raise ValueError(f"duplicate label names in {self.name}")


def _valid_metric_name(name: str) -> bool:
    if not name:
        return False
    head = name[0]
    if not (head.isascii() and (head.isalpha() or head in "_:")):
        return False
    return all(c.isascii() and (c.isalnum() or c in "_:") for c in name[1:])


def _valid_label_name(name: str) -> bool:
    if not name or name.startswith("__"):
        return False
    head = name[0]
    if not (head.isascii() and (head.isalpha() or head == "_")):
        return False
    return all(c.isascii() and (c.isalnum() or c == "_") for c in name[1:])


def escape_label_value(value: str) -> str:
    # NUL would truncate the line in the native (C-string) render path and
    # is meaningless in a label; strip it from untrusted input.
    return (
        value.replace("\x00", "")
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(value: str) -> str:
    return value.replace("\x00", "").replace("\\", "\\\\").replace("\n", "\\n")


def format_value(value: float) -> str:
    """Render a sample value the way Prometheus expects."""
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 2**53:
        return str(int(value))
    return repr(float(value))


@dataclass
class _Family:
    spec: MetricSpec
    # label-values-tuple -> value; insertion order is emission order
    samples: dict[tuple[str, ...], float] = field(default_factory=dict)


def render_prefix(spec: MetricSpec, lvs: tuple[str, ...]) -> bytes:
    """The `metric{label="…"}` part of one exposition line — the single
    source of truth for both the cached and uncached render paths."""
    if spec.raw_lines:
        return lvs[0].encode()
    if not spec.label_names and not lvs:
        return spec.name.encode()
    if len(lvs) != len(spec.label_names):
        raise ValueError(
            f"{spec.name}: got {len(lvs)} label values, "
            f"want {len(spec.label_names)}"
        )
    for lv in lvs:
        if type(lv) is not str:
            raise TypeError(f"{spec.name}: label value {lv!r} is not str")
    pairs = ",".join(
        f'{ln}="{escape_label_value(lv)}"'
        for ln, lv in zip(spec.label_names, lvs)
    )
    return f"{spec.name}{{{pairs}}}".encode()


class FamilyLayout:
    """One family's frozen series order plus everything derivable from it.

    Between churn events the key sequence of a family is identical poll to
    poll, so the per-series prefixes, the ctypes pointer array the native
    renderer needs, and even the fully rendered text block (when the values
    did not change either — HBM totals, chip counts, info series) can all be
    reused byte-for-byte. Mutated only by the poll thread (inside
    ``Snapshot.encode`` at swap time), never by scrape threads.
    """

    __slots__ = (
        "keys", "prefixes", "native_arr", "plens_arr", "prefix_total",
        "last_values", "last_block", "out_buf",
    )

    def __init__(self, keys: tuple[tuple[str, ...], ...], prefixes: list[bytes]) -> None:
        self.keys = keys
        self.prefixes = prefixes
        self.native_arr = None  # lazily-built ctypes c_char_p array
        self.plens_arr = None   # lazily-built ctypes c_int array of prefix lengths
        self.prefix_total = sum(map(len, prefixes))
        self.last_values: list[float] | None = None
        self.last_block: bytes | None = None
        self.out_buf = None  # reused ctypes render buffer (native path)


class PrefixCache:
    """Rendered `metric{labels}` byte-prefixes, shared across polls.

    Label sets are stable between churn events, so escaping + joining each
    series' label block every poll is pure waste — the dominant CPU cost at
    256 chips. Keyed by (metric name, label values tuple). Bounded: when the
    cache outgrows ``max_entries`` it is cleared wholesale (churned-away
    label sets would otherwise accumulate forever).

    Also home of the per-family :class:`FamilyLayout` records (the next
    caching tier up): per-series prefixes answer "how does this label set
    render", layouts answer "what is this family's exact series order".
    """

    def __init__(self, max_entries: int = 65536, splice: bool = True) -> None:
        self._cache: dict[tuple[str, tuple[str, ...]], bytes] = {}
        self._layouts: dict[str, FamilyLayout] = {}
        self._max = max_entries
        # Incremental exposition render (ISSUE 13): one template of the
        # whole body is kept across polls and only changed value cells are
        # spliced per snapshot. splice=False restores the per-family
        # layout-block render (the pre-splice behaviour).
        self.template: ExpositionTemplate | None = (
            ExpositionTemplate(self) if splice else None
        )

    def prefix(self, spec: MetricSpec, lvs: tuple[str, ...]) -> bytes:
        key = (spec.name, lvs)
        p = self._cache.get(key)
        if p is None:
            p = render_prefix(spec, lvs)
            if len(self._cache) >= self._max:
                self._cache.clear()
            self._cache[key] = p
        return p

    def layout(self, spec: MetricSpec, keys: tuple[tuple[str, ...], ...]) -> FamilyLayout:
        rec = self._layouts.get(spec.name)
        if rec is not None and rec.keys == keys:
            return rec
        pfx = self.prefix
        rec = FamilyLayout(keys, [pfx(spec, k) for k in keys])
        self._layouts[spec.name] = rec
        return rec


class BodySet:
    """Per-encoding rendered bodies for ONE splice revision of the template.

    A new BodySet is minted every time the template's bytes actually change
    (a cell splice, a block rebuild, a layout churn) — that is the whole
    invalidation story for the per-encoding caches: gzip and OpenMetrics
    variants are derived lazily on first request and live exactly as long
    as the identity body they encode. When consecutive polls produce
    byte-identical expositions the SAME BodySet is handed to each snapshot,
    so a gzip compressed for poll N is still served at poll N+k.

    Lock-free by design: the optional fields are filled by plain attribute
    stores (GIL-atomic). Two scrape threads racing the first gzip may both
    compress; the results are byte-identical and the second store wins —
    duplicate work once, never a lock held across compression (this
    supersedes the old lazy-compress-under-lock idiom and its lock-io
    lint escapes).
    """

    __slots__ = ("text", "revision", "generation", "openmetrics",
                 "text_gzip", "openmetrics_gzip")

    def __init__(self, text: bytes, revision: int, generation: int) -> None:
        self.text = text
        self.revision = revision
        self.generation = generation
        self.openmetrics: bytes | None = None
        self.text_gzip: bytes | None = None
        self.openmetrics_gzip: bytes | None = None


class _TemplateFamily:
    """One family's slice of the exposition template: the rendered sample
    block plus everything needed to splice new values into it in place."""

    __slots__ = ("spec", "layout", "header", "values", "cells", "offsets",
                 "buf")

    def __init__(self, spec: MetricSpec, layout: FamilyLayout | None,
                 header: bytes) -> None:
        self.spec = spec
        self.layout = layout
        self.header = header
        self.values: array = array("d")
        # Formatted value bytes per series, aligned with layout.keys.
        self.cells: list[bytes] = []
        # Byte offset of each value cell inside ``buf``.
        self.offsets: list[int] = []
        self.buf = bytearray()

    def rebuild(self, values: array) -> None:
        """Re-render the block from prefixes + current cell bytes. Called
        when the layout changed or a cell's formatted width changed; cells
        for unchanged values are reused, so the cost is the byte join, not
        re-formatting every float."""
        layout = self.layout
        assert layout is not None
        cells = self.cells
        parts: list[bytes] = []
        offsets: list[int] = []
        off = 0
        for prefix, cell in zip(layout.prefixes, cells):
            parts.append(prefix)
            parts.append(b" ")
            parts.append(cell)
            parts.append(b"\n")
            off += len(prefix) + 1
            offsets.append(off)
            off += len(cell) + 1
        self.buf = bytearray(b"".join(parts))
        self.offsets = offsets
        self.values = values


class ExpositionTemplate:
    """Pre-rendered exposition bytes spliced incrementally across polls.

    The template holds the full text-format body as per-family blocks keyed
    by the layout generation: between churn events the series set and order
    of every family are identical poll to poll, so the only bytes that can
    differ are the float cells. Per poll the value vector of each family is
    diffed (C-level via numpy when available), changed cells are formatted
    and spliced into the block bytearray in place when the width matches,
    and a block is re-joined from cached line fragments when a width
    changed. A layout change (labels added/evicted, a conditional surface
    appearing) bumps ``generation`` and rebuilds the affected family from
    its prefixes.

    Thread contract: mutated only by the thread that calls
    :meth:`Snapshot.encode` at swap time (the poll loop) — the same
    single-writer rule the FamilyLayout cache always had. Scrape threads
    only ever see the immutable bytes handed out through a :class:`BodySet`.
    """

    __slots__ = ("_cache", "_records", "_headers", "_bodyset", "generation",
                 "revision", "polls", "spliced_cells", "rebuilt_blocks",
                 "reused_blocks", "family_rebuilds")

    # numpy wins over the Python zip-loop diff from roughly this many
    # series (measured; below it the frombuffer overhead dominates).
    _NUMPY_DIFF_MIN = 64

    def __init__(self, cache: PrefixCache) -> None:
        self._cache = cache
        self._records: list[_TemplateFamily] = []
        self._headers: dict[str, bytes] = {}
        self._bodyset: BodySet | None = None
        self.generation = 0   # bumped on any layout/family-set change
        self.revision = 0     # bumped whenever the body bytes change
        self.polls = 0
        self.spliced_cells = 0
        self.rebuilt_blocks = 0
        self.reused_blocks = 0
        self.family_rebuilds = 0

    def stats(self) -> dict[str, int]:
        """Render-cache counters for /debug/vars (RUNBOOK 'render')."""
        return {
            "generation": self.generation,
            "revision": self.revision,
            "polls": self.polls,
            "families": len(self._records),
            "spliced_cells": self.spliced_cells,
            "rebuilt_blocks": self.rebuilt_blocks,
            "reused_blocks": self.reused_blocks,
            "family_rebuilds": self.family_rebuilds,
        }

    def _header_for(self, spec: MetricSpec) -> bytes:
        if spec.suppress_header:
            return b""
        h = self._headers.get(spec.name)
        if h is None:
            h = (f"# HELP {spec.name} {escape_help(spec.help)}\n"
                 f"# TYPE {spec.name} {spec.type}\n").encode()
            self._headers[spec.name] = h
        return h

    def _build_family(self, spec: MetricSpec,
                      samples: dict[tuple[str, ...], float]) -> _TemplateFamily:
        self.family_rebuilds += 1
        if not samples:
            return _TemplateFamily(spec, None, self._header_for(spec))
        layout = self._cache.layout(spec, tuple(samples))
        rec = _TemplateFamily(spec, layout, self._header_for(spec))
        values = array("d", samples.values())
        rec.cells = [format_value(v).encode() for v in values]
        rec.rebuild(values)
        return rec

    def _changed_indices(self, old: array, new: array) -> list[int]:
        if _np is not None and len(new) >= self._NUMPY_DIFF_MIN:
            a = _np.frombuffer(old, dtype=_np.float64)
            b = _np.frombuffer(new, dtype=_np.float64)
            # NaN cells compare unequal every poll; _splice_family skips
            # them once their formatted bytes come out identical.
            return _np.nonzero(a != b)[0].tolist()  # type: ignore[no-any-return]
        return [i for i, (x, y) in enumerate(zip(old, new)) if x != y]

    def _splice_family(self, rec: _TemplateFamily,
                       samples: dict[tuple[str, ...], float]) -> bool:
        """Fold one family's new values into its block. True if bytes
        changed."""
        new_values = array("d", samples.values())
        if new_values == rec.values:
            self.reused_blocks += 1
            return False
        idxs = self._changed_indices(rec.values, new_values)
        if not idxs:
            # Only representation-stable differences (NaN vs NaN compares
            # unequal in the array fallback; numpy path returns them).
            rec.values = new_values
            self.reused_blocks += 1
            return False
        cells = rec.cells
        resize = False
        dirty = []
        for i in idxs:
            cell = format_value(new_values[i]).encode()
            if cell == cells[i]:
                # Representation-stable difference: a NaN cell compares
                # unequal every poll but renders the same "NaN" bytes.
                # Counting it as a change would mint a new BodySet per
                # poll and discard the gzip/OpenMetrics caches for a
                # byte-identical body.
                continue
            if len(cell) != len(cells[i]):
                resize = True
            cells[i] = cell
            dirty.append(i)
        if not dirty:
            rec.values = new_values
            self.reused_blocks += 1
            return False
        if resize:
            rec.rebuild(new_values)
            self.rebuilt_blocks += 1
            return True
        buf = rec.buf
        offsets = rec.offsets
        for i in dirty:
            off = offsets[i]
            buf[off:off + len(cells[i])] = cells[i]
        rec.values = new_values
        self.spliced_cells += len(dirty)
        return True

    def render(self, snapshot: "Snapshot") -> tuple[bytes, BodySet]:
        """Produce the full text body for ``snapshot``, reusing the
        template. Returns the immutable body plus the BodySet carrying its
        per-encoding caches."""
        self.polls += 1
        families = snapshot._families
        records = self._records
        specs = [f.spec for f in families.values()]
        aligned = (
            len(records) == len(specs)
            and all(
                r.spec is s or r.spec == s
                for r, s in zip(records, specs)
            )
        )
        changed = False
        if not aligned:
            # Family set or order changed: new layout generation, rebuild
            # the whole record list (prefixes still come from the cache).
            self.generation += 1
            records = [
                self._build_family(fam.spec, fam.samples)
                for fam in families.values()
            ]
            self._records = records
            changed = True
        else:
            for idx, fam in enumerate(families.values()):
                rec = records[idx]
                if not fam.samples:
                    if rec.layout is not None or rec.buf:
                        # Series all churned away: header-only block now.
                        self.generation += 1
                        records[idx] = self._build_family(fam.spec, {})
                        changed = True
                    continue
                layout = self._cache.layout(fam.spec, tuple(fam.samples))
                if layout is not rec.layout:
                    self.generation += 1
                    records[idx] = self._build_family(fam.spec, fam.samples)
                    changed = True
                    continue
                if self._splice_family(rec, fam.samples):
                    changed = True
        bodyset = self._bodyset
        if changed or bodyset is None:
            parts: list[bytes | bytearray] = []
            for rec in records:
                if rec.header:
                    parts.append(rec.header)
                if rec.buf:
                    parts.append(rec.buf)
            self.revision += 1
            bodyset = BodySet(b"".join(parts), self.revision, self.generation)
            self._bodyset = bodyset
        return bodyset.text, bodyset


class SnapshotBuilder:
    """Accumulates one poll's worth of samples, then freezes into a Snapshot.

    ``add`` replaces on duplicate label sets (last write wins within a poll,
    which the collector avoids by construction but must not crash on —
    contrast with the reference silently collapsing multi-device series,
    ``main.go:141-155``).
    """

    def __init__(self, prefix_cache: PrefixCache | None = None) -> None:
        self._families: dict[str, _Family] = {}
        self._order: list[str] = []
        self._prefix_cache = prefix_cache

    def declare(self, spec: MetricSpec) -> None:
        """Register a family so it appears (possibly sample-less) in output."""
        existing = self._families.get(spec.name)
        if existing is not None:
            # identity first: specs are module-level singletons on the hot path
            if existing.spec is not spec and existing.spec != spec:
                raise ValueError(f"conflicting redeclaration of {spec.name}")
            return
        self._families[spec.name] = _Family(spec)
        self._order.append(spec.name)

    def add(
        self,
        spec: MetricSpec,
        value: float,
        labels: Mapping[str, str] | Sequence[str] = (),
    ) -> None:
        fam = self._families.get(spec.name)
        if fam is None:
            self.declare(spec)
            fam = self._families[spec.name]
        elif fam.spec is not spec and fam.spec != spec:
            raise ValueError(f"conflicting redeclaration of {spec.name}")
        if type(labels) is tuple:
            # Hot path: pre-ordered tuple of label values. Contract: elements
            # are already strings — enforced where it's cheap, at the first
            # render of a new label set (PrefixCache miss), not per add.
            values = labels
            if len(values) != len(spec.label_names):
                raise ValueError(
                    f"{spec.name}: got {len(values)} label values, "
                    f"want {len(spec.label_names)}"
                )
        elif isinstance(labels, Mapping):
            try:
                values = tuple(str(labels[ln]) for ln in spec.label_names)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {spec.name}") from e
            extra = set(labels) - set(spec.label_names)
            if extra:
                raise ValueError(f"unknown labels {sorted(extra)} for {spec.name}")
        else:
            values = tuple(str(v) for v in labels)
            if len(values) != len(spec.label_names):
                raise ValueError(
                    f"{spec.name}: got {len(values)} label values, "
                    f"want {len(spec.label_names)}"
                )
        fam.samples[values] = float(value)

    def series(self, spec: MetricSpec) -> dict[tuple[str, ...], float]:
        """Direct handle on a family's samples dict, for the collector's hot
        loop: ``series(SPEC)[label_tuple] = value`` is one dict store, vs the
        per-call family lookup + shape checks of :meth:`add`. Caller contract
        (same as the tuple fast path of ``add``): keys are pre-ordered tuples
        of ``str`` matching ``spec.label_names`` — enforced at first render
        of each new label set."""
        self.declare(spec)
        return self._families[spec.name].samples

    @property
    def series_count(self) -> int:
        return sum(len(f.samples) for f in self._families.values())

    def build(self, timestamp: float | None = None, *, transfer: bool = False) -> "Snapshot":
        """Freeze into a Snapshot. With ``transfer=True`` the family dicts are
        handed off instead of copied (the builder resets to empty) — for the
        poll loop, which discards its builder after every poll anyway."""
        if transfer:
            families = {n: self._families[n] for n in self._order}
            self._families = {}
            self._order = []
        else:
            families = {
                n: _Family(self._families[n].spec, dict(self._families[n].samples))
                for n in self._order
            }
        return Snapshot(
            families=families,
            timestamp=time.time() if timestamp is None else timestamp,
            prefix_cache=self._prefix_cache,
        )


class Snapshot:
    """An immutable, pre-rendered view of all series at one poll instant."""

    def __init__(
        self,
        families: dict[str, _Family],
        timestamp: float,
        prefix_cache: "PrefixCache | None" = None,
    ) -> None:
        self._families = families
        self.timestamp = timestamp
        self._prefix_cache = prefix_cache
        self._text: bytes | None = None
        self._gzipped: bytes | None = None
        self._openmetrics: bytes | None = None
        self._openmetrics_gzipped: bytes | None = None
        # Set by the template render path: shares per-encoding bodies
        # (gzip, OpenMetrics) across snapshots whose bytes did not change.
        self._bodyset: BodySet | None = None

    @property
    def series_count(self) -> int:
        return sum(len(f.samples) for f in self._families.values())

    def families(self) -> Iterable[MetricSpec]:
        return (f.spec for f in self._families.values())

    def value(
        self, name: str, labels: Mapping[str, str] | Sequence[str] = ()
    ) -> float | None:
        """Test/introspection helper: value of one series, or None."""
        fam = self._families.get(name)
        if fam is None:
            return None
        if isinstance(labels, Mapping):
            key = tuple(str(labels.get(ln, "")) for ln in fam.spec.label_names)
        else:
            key = tuple(str(v) for v in labels)
        return fam.samples.get(key)

    def samples(self, name: str) -> dict[tuple[str, ...], float]:
        fam = self._families.get(name)
        return dict(fam.samples) if fam is not None else {}

    def samples_view(self, name: str) -> dict[tuple[str, ...], float] | None:
        """Zero-copy handle on one family's samples dict (None when the
        family is absent). Snapshots are immutable after ``build``, so the
        persistence writer thread reads these without copies or locks —
        callers MUST NOT mutate the returned dict."""
        fam = self._families.get(name)
        return fam.samples if fam is not None else None

    def encode(self) -> bytes:
        """Prometheus text exposition format (rendered once, then cached).

        Called by the poll thread at swap time, so scrapes always see cached
        bytes. With a PrefixCache attached, rendering is layout-aware: the
        family's series order is matched against the previous poll's
        :class:`FamilyLayout`; on a hit, per-series prefix lookups and the
        ctypes marshalling are skipped, and when the value vector is also
        unchanged (constant families: HBM totals, chip counts, info) the
        previous rendered block is reused outright. Sample lines go through
        the native renderer (libtpumon) when available; both paths produce
        parser-equivalent output.
        """
        if self._text is not None:
            return self._text
        cache = self._prefix_cache
        if cache is not None and cache.template is not None:
            # Incremental path: splice changed cells into the shared
            # template instead of re-rendering ~1 MB per poll. Single
            # writer (the poll thread) by the template's thread contract.
            self._text, self._bodyset = cache.template.render(self)
            return self._text
        try:
            from tpu_pod_exporter.metrics import native
        except ImportError:  # partial deployment: never let encode() die
            native = None

        chunks: list[bytes] = []
        for fam in self._families.values():
            spec = fam.spec
            if not spec.suppress_header:
                chunks.append(
                    f"# HELP {spec.name} {escape_help(spec.help)}\n"
                    f"# TYPE {spec.name} {spec.type}\n".encode()
                )
            if not fam.samples:
                continue
            if cache is not None:
                layout = cache.layout(spec, tuple(fam.samples))
                # array('d') packs the value vector at C speed; comparison
                # against the previous poll's vector is likewise C-level.
                values = array("d", fam.samples.values())
                if layout.last_block is not None and layout.last_values == values:
                    chunks.append(layout.last_block)
                    continue
                rendered = native.render_layout(layout, values) if native else None
                if rendered is None:
                    rendered = b"".join(
                        p + b" " + format_value(v).encode() + b"\n"
                        for p, v in zip(layout.prefixes, values)
                    )
                layout.last_values = values
                layout.last_block = rendered
                chunks.append(rendered)
                continue
            prefixes = [render_prefix(spec, lvs) for lvs in fam.samples]
            values = list(fam.samples.values())
            rendered = native.render_lines(prefixes, values) if native else None
            if rendered is None:
                rendered = b"".join(
                    p + b" " + format_value(v).encode() + b"\n"
                    for p, v in zip(prefixes, values)
                )
            chunks.append(rendered)
        self._text = b"".join(chunks)
        return self._text

    def encode_openmetrics(self) -> bytes:
        """OpenMetrics 1.0 exposition, derived lazily from the cached 0.0.4
        body. The sample lines are byte-identical between the two formats for
        gauge/counter families; only two things differ: counter HELP/TYPE
        header lines name the family *without* its ``_total`` suffix, and the
        body ends with ``# EOF``. So this is a handful of header rewrites on
        the cached bytes, not a second render."""
        om = self._openmetrics
        if om is not None:
            return om
        bs = self._bodyset
        if bs is not None and bs.openmetrics is not None:
            self._openmetrics = bs.openmetrics
            return bs.openmetrics

        def _rewrite(body: bytes, old: bytes, new: bytes) -> bytes:
            # Anchor the needle on a line start so a HELP text that happens
            # to *contain* "# HELP <name> " can never be rewritten instead
            # of the real header line; the first family's header has no
            # preceding newline and is handled via startswith.
            if body.startswith(old):
                return new + body[len(old):]
            return body.replace(b"\n" + old, b"\n" + new, 1)

        om = self.encode()
        for fam in self._families.values():
            spec = fam.spec
            if spec.type == COUNTER and spec.name.endswith("_total"):
                base = spec.name[: -len("_total")]
                om = _rewrite(
                    om,
                    f"# HELP {spec.name} ".encode(),
                    f"# HELP {base} ".encode(),
                )
                om = _rewrite(
                    om,
                    f"# TYPE {spec.name} counter".encode(),
                    f"# TYPE {base} counter".encode(),
                )
        om = om + b"# EOF\n"
        # Lock-free publish (GIL-atomic stores): two scrape threads racing
        # here both derive byte-identical bodies; the second store wins.
        self._openmetrics = om
        if bs is not None:
            bs.openmetrics = om
        return om

    def encode_openmetrics_gzip(self) -> bytes:
        gz = self._openmetrics_gzipped
        if gz is not None:
            return gz
        bs = self._bodyset
        if bs is not None and bs.openmetrics_gzip is not None:
            self._openmetrics_gzipped = bs.openmetrics_gzip
            return bs.openmetrics_gzip
        import gzip

        gz = gzip.compress(self.encode_openmetrics(), compresslevel=1)
        self._openmetrics_gzipped = gz
        if bs is not None:
            bs.openmetrics_gzip = gz
        return gz

    def encode_gzip(self) -> bytes:
        """Gzipped exposition, compressed lazily on the first gzip-accepting
        scrape of this snapshot (then cached). Compressing eagerly at swap
        time would cost ~2 ms per poll even when Prometheus scrapes far less
        often than the 1 s poll interval; lazily, the cost lands once per
        SPLICE REVISION: the BodySet carries the compressed bytes across
        snapshots whose exposition did not change. Thread-safe without a
        lock — racing scrapers may both compress once (identical output,
        GIL-atomic publish), and no thread ever holds a lock across the
        compression."""
        gz = self._gzipped
        if gz is not None:
            return gz
        bs = self._bodyset
        if bs is not None and bs.text_gzip is not None:
            self._gzipped = bs.text_gzip
            return bs.text_gzip
        import gzip

        gz = gzip.compress(self.encode(), compresslevel=1)
        self._gzipped = gz
        if bs is not None:
            bs.text_gzip = gz
        return gz

    def cached_exposition(self, openmetrics: bool = False,
                          gzipped: bool = False) -> bytes | None:
        """Already-rendered body for one (format, encoding) pair, or None.

        The event-loop server's inline fast path: a scrape whose body is
        already cached (the common case — the poll thread pre-encodes the
        identity body at swap, and gzip/OpenMetrics variants persist on the
        BodySet across unchanged revisions) is served straight off the
        loop with zero blocking work; a None sends the request to a worker
        thread, which may render."""
        bs = self._bodyset
        if openmetrics:
            if gzipped:
                if self._openmetrics_gzipped is not None:
                    return self._openmetrics_gzipped
                return bs.openmetrics_gzip if bs is not None else None
            if self._openmetrics is not None:
                return self._openmetrics
            return bs.openmetrics if bs is not None else None
        if gzipped:
            if self._gzipped is not None:
                return self._gzipped
            return bs.text_gzip if bs is not None else None
        return self._text


EMPTY_SNAPSHOT = Snapshot({}, timestamp=0.0)


class SnapshotStore:
    """The single cross-thread handoff point between poll loop and scrapes.

    The reference relies on prometheus GaugeVec's internal locking for its
    loop-writes/scrape-reads overlap (``main.go:68-72`` vs ``main.go:147-150``).
    Here *all* shared state is one reference guarded by a lock; scrapes never
    observe a half-written poll.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._snapshot: Snapshot = EMPTY_SNAPSHOT

    def swap(self, snapshot: Snapshot) -> None:
        snapshot.encode()  # render once, off the scrape path (gzip is lazy)
        with self._lock:
            self._snapshot = snapshot

    def current(self) -> Snapshot:
        with self._lock:
            return self._snapshot


class HistogramSpec:
    """One histogram family: a header-only parent spec (``TYPE histogram``)
    plus a single raw-lines child family carrying every ``_bucket`` /
    ``_count`` / ``_sum`` sample in OpenMetrics order.

    Exposition shape (Prometheus text format / OpenMetrics 1.0)::

        # HELP name help
        # TYPE name histogram
        name_bucket{...,le="0.005"} 3
        ...
        name_bucket{...,le="+Inf"} 9
        name_count{...} 9
        name_sum{...} 0.123

    One raw-lines family (not three suffix families) because OpenMetrics
    requires a label set's MetricPoints to be contiguous — bucket/count/sum
    must interleave PER LABEL SET, which per-suffix family blocks cannot
    express. The child's samples still ride the existing fast paths
    (FamilyLayout, native renderer) untouched: those only ever see opaque
    prefix bytes. ``buckets`` are finite upper bounds, strictly increasing;
    the ``+Inf`` bucket is implicit (always emitted, equal to ``_count``).
    Strict OpenMetrics additionally forbids ``_sum`` alongside negative
    buckets or observations — every histogram here is a duration, so keep
    bounds and observed values non-negative.
    """

    __slots__ = ("parent", "lines", "label_names", "buckets", "le_values")

    def __init__(
        self,
        name: str,
        help: str,  # noqa: A002 — mirrors MetricSpec
        buckets: Sequence[float],
        label_names: tuple[str, ...] = (),
    ) -> None:
        bs = tuple(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        if any(math.isinf(b) or math.isnan(b) for b in bs):
            raise ValueError(f"{name}: buckets must be finite (+Inf is implicit)")
        if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"{name}: buckets must be strictly increasing")
        if "le" in label_names:
            raise ValueError(f"{name}: 'le' is reserved for the bucket label")
        self.label_names = tuple(label_names)
        self.buckets = bs
        self.le_values = tuple(format_value(b) for b in bs) + ("+Inf",)
        self.parent = MetricSpec(
            name=name, help=help, type=HISTOGRAM, label_names=self.label_names
        )
        # "_lines" is an internal family key, never rendered (header
        # suppressed, prefixes pre-rendered) — it cannot collide with a real
        # exposition name.
        self.lines = MetricSpec(
            name=name + "_lines", help=help, type=GAUGE,
            label_names=("line",), suppress_header=True, raw_lines=True,
        )


class HistogramStore:
    """Observation state for one histogram family, accumulated across polls.

    Like :class:`CounterStore`, state outlives individual snapshots: the
    snapshot model rebuilds every series each poll, so distributions must
    live with an owner. ``observe`` is safe from any thread (scrape handler
    threads observe while the poll thread emits) and cheap enough for the
    scrape path: a bisect plus three adds under a lock.
    """

    def __init__(self, spec: HistogramSpec) -> None:
        self.spec = spec
        self._lock = threading.Lock()
        # label values tuple -> [per-bucket counts (non-cumulative,
        # +Inf last), sum, count]
        self._data: dict[tuple[str, ...], list] = {}
        # label values tuple -> (bucket key-tuples, count key, sum key):
        # the fully rendered series prefixes, built once per label set.
        # Reusing the same key-tuple OBJECTS every emit keeps the
        # FamilyLayout comparison on its fast path.
        self._line_keys: dict[tuple[str, ...], tuple] = {}

    def observe(self, value: float, labels: tuple[str, ...] = ()) -> None:
        idx = bisect_left(self.spec.buckets, value)  # le: value == bound counts
        with self._lock:
            rec = self._data.get(labels)
            if rec is None:
                rec = self._data[labels] = [
                    [0] * (len(self.spec.buckets) + 1), 0.0, 0,
                ]
            rec[0][idx] += 1
            rec[1] += value
            rec[2] += 1

    def _keys_for(self, lvs: tuple[str, ...]) -> tuple:
        cached = self._line_keys.get(lvs)
        if cached is not None:
            return cached
        spec = self.spec
        name = spec.parent.name
        base = ",".join(
            f'{ln}="{escape_label_value(v)}"'
            for ln, v in zip(spec.label_names, lvs)
        )
        sep = base + "," if base else ""
        bucket_keys = tuple(
            (f'{name}_bucket{{{sep}le="{le}"}}',) for le in spec.le_values
        )
        count_key = (f"{name}_count{{{base}}}" if base else f"{name}_count",)
        sum_key = (f"{name}_sum{{{base}}}" if base else f"{name}_sum",)
        cached = (bucket_keys, count_key, sum_key)
        self._line_keys[lvs] = cached
        return cached

    def emit(self, builder: "SnapshotBuilder") -> None:
        """Declare parent + lines families (adjacent, so the sample lines
        sit under the parent's header) and add every label set's current
        cumulative state in OpenMetrics order: per label set, buckets
        ascending, then count, then sum."""
        spec = self.spec
        builder.declare(spec.parent)
        builder.declare(spec.lines)
        with self._lock:
            snap = {
                lvs: (list(rec[0]), rec[1], rec[2])
                for lvs, rec in self._data.items()
            }
        lines_s = builder.series(spec.lines)
        for lvs, (counts, total, n) in snap.items():
            bucket_keys, count_key, sum_key = self._keys_for(lvs)
            cum = 0
            for key, c in zip(bucket_keys, counts):
                cum += c
                lines_s[key] = float(cum)
            lines_s[count_key] = float(n)
            lines_s[sum_key] = total


class CounterStore:
    """Monotonic counter state that outlives individual snapshots.

    Keyed by (metric name, label values). ``observe_total`` accepts an
    absolute device counter (handles resets by clamping to monotonic);
    ``inc`` adds a delta. Stale keys can be pruned by the collector when the
    underlying entity (chip/link) disappears.
    """

    def __init__(self) -> None:
        self._values: dict[tuple[str, tuple[str, ...]], float] = {}
        self._raw: dict[tuple[str, tuple[str, ...]], float] = {}

    def inc(self, name: str, labels: tuple[str, ...], delta: float = 1.0) -> float:
        key = (name, labels)
        self._values[key] = self._values.get(key, 0.0) + max(delta, 0.0)
        return self._values[key]

    def observe_total(self, name: str, labels: tuple[str, ...], raw_total: float) -> float:
        """Fold an absolute monotonic reading into the exported counter.

        If the raw counter goes backwards (device reset, runtime restart) the
        exported counter holds instead of regressing.
        """
        key = (name, labels)
        prev_raw = self._raw.get(key)
        if prev_raw is None:
            self._values.setdefault(key, raw_total if raw_total >= 0 else 0.0)
        else:
            delta = raw_total - prev_raw
            if delta > 0:
                self._values[key] = self._values.get(key, 0.0) + delta
        self._raw[key] = raw_total
        return self._values[key]

    def get(self, name: str, labels: tuple[str, ...]) -> float:
        return self._values.get((name, labels), 0.0)

    def items_for(self, name: str) -> list[tuple[tuple[str, ...], float]]:
        return [(k[1], v) for k, v in self._values.items() if k[0] == name]

    def prune(self, keep: set[tuple[str, tuple[str, ...]]]) -> int:
        """Drop counter state for entities that no longer exist."""
        stale = [k for k in self._values if k not in keep]
        for k in stale:
            self._values.pop(k, None)
            self._raw.pop(k, None)
        return len(stale)
