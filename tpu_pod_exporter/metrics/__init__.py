from tpu_pod_exporter.metrics.registry import (
    COUNTER,
    GAUGE,
    HISTOGRAM,
    CounterStore,
    HistogramSpec,
    HistogramStore,
    MetricSpec,
    PrefixCache,
    Snapshot,
    SnapshotBuilder,
    SnapshotStore,
)

__all__ = [
    "COUNTER",
    "GAUGE",
    "HISTOGRAM",
    "CounterStore",
    "HistogramSpec",
    "HistogramStore",
    "MetricSpec",
    "PrefixCache",
    "Snapshot",
    "SnapshotBuilder",
    "SnapshotStore",
]
