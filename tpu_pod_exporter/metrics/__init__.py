from tpu_pod_exporter.metrics.registry import (
    COUNTER,
    GAUGE,
    CounterStore,
    MetricSpec,
    Snapshot,
    SnapshotBuilder,
    SnapshotStore,
)

__all__ = [
    "COUNTER",
    "GAUGE",
    "CounterStore",
    "MetricSpec",
    "Snapshot",
    "SnapshotBuilder",
    "SnapshotStore",
]
