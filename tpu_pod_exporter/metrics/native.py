"""Native exposition rendering via libtpumon (see ``nativelib`` for loading).

The render hot path (thousands of `prefix value\n` lines per poll at 256
chips × 1 s) runs in C when the shared library is present; callers fall
back to the pure-Python formatter when ``render_lines`` returns None.
"""

from __future__ import annotations

import ctypes
from typing import TYPE_CHECKING

from tpu_pod_exporter import nativelib

if TYPE_CHECKING:  # typing only
    from array import array

    from tpu_pod_exporter.metrics.parse import LayoutCache
    from tpu_pod_exporter.metrics.registry import FamilyLayout


def render_lines(prefixes: list[bytes], values: list[float]) -> bytes | None:
    """Render `prefix value\\n` lines natively. None → caller falls back."""
    lib = nativelib.load()
    if lib is None or not prefixes:
        return None
    n = len(prefixes)
    arr_p = (ctypes.c_char_p * n)(*prefixes)
    arr_v = (ctypes.c_double * n)(*values)
    # Worst case ~ prefix + " " + 24-char value + "\n".
    cap = sum(len(p) for p in prefixes) + 32 * n
    buf = ctypes.create_string_buffer(cap)
    written = lib.tpumon_render(arr_p, arr_v, n, buf, cap)
    if written < 0:
        return None
    return buf.raw[:written]


def render_layout(layout: "FamilyLayout", values: "array") -> bytes | None:
    """Render one family via its :class:`FamilyLayout`, reusing the ctypes
    pointer array across polls (building it is the per-call cost of
    ``render_lines``; the prefixes themselves are stable between churn
    events). ``values`` is an ``array('d')`` — passed to C by buffer, no
    per-element marshalling. None → caller falls back to the Python
    formatter."""
    lib = nativelib.load()
    if lib is None or not layout.prefixes:
        return None
    n = len(layout.prefixes)
    if layout.native_arr is None:
        layout.native_arr = (ctypes.c_char_p * n)(*layout.prefixes)
        layout.plens_arr = (ctypes.c_int * n)(*map(len, layout.prefixes))
    arr_v = (ctypes.c_double * n).from_buffer(values)
    cap = layout.prefix_total + 32 * n
    buf = layout.out_buf
    if buf is None or len(buf) < cap:
        # Reused across polls: create_string_buffer would malloc + zero-fill
        # hundreds of KB per family per poll on the big (per-link) families.
        buf = layout.out_buf = ctypes.create_string_buffer(cap)
    written = lib.tpumon_render2(
        layout.native_arr, layout.plens_arr, arr_v, n, buf, len(buf)
    )
    if written < 0:
        return None
    return ctypes.string_at(buf, written)


def parse_layout(layout: "LayoutCache", text: str) -> "list[float] | None":
    """Whole-body value-only parse of one exposition body against a warm
    :class:`~tpu_pod_exporter.metrics.parse.LayoutCache` — the parse-side
    inverse of :func:`render_layout`. Returns the kind-2 entry values in
    entry order on a PERFECT byte-level match of every line, else None
    (the Python parser owns all divergence/rebuild semantics). The ctypes
    key arrays are cached on the layout and rebuilt only when its entries
    list is swapped (churn)."""
    lib = nativelib.load()
    entries = layout.entries
    if lib is None or not entries:
        return None
    if layout.native_built_for is not entries or layout.native_out is None:
        keys = [ent[1].encode() for ent in entries]
        n = len(entries)
        # The c_char_p array holds pointers INTO the bytes objects; keep
        # the list alive alongside it.
        layout.native_keybytes = keys
        layout.native_keys = (ctypes.c_char_p * n)(*keys)
        layout.native_klens = (ctypes.c_int * n)(*map(len, keys))
        layout.native_kinds = (ctypes.c_ubyte * n)(*(e[0] for e in entries))
        layout.samples_template = [
            (e[2], e[3]) for e in entries if e[0] == 2
        ]
        layout.native_out = (ctypes.c_double * len(layout.samples_template))()
        layout.native_built_for = entries
    data = text.encode()
    got = lib.tpumon_parse_layout(
        data, len(data), layout.native_keys, layout.native_klens,
        layout.native_kinds, len(entries), layout.native_out,
    )
    if got != len(layout.native_out):
        return None
    return list(layout.native_out)


def load() -> "ctypes.CDLL | None":
    """Kept for tests: the shared library handle (or None)."""
    return nativelib.load()
