"""The ``tpu_*`` metric schema — the exporter's public contract.

Replaces the reference's two inconsistently-named gauges
(``pod_gpu_memory_usage`` / ``docker_gpu_memory_perc_usage``,
``main.go:21-36``) with a consistent ``tpu_`` namespace, and fixes the
reference's label-schema defects:

- adds ``chip_id`` — the reference has no device label, so two processes of
  one pod on different devices collapse into one series
  (``main.go:123-155``);
- adds ``namespace`` — the reference keys only by pod name, so equal names
  in different namespaces collide (``main.go:113``);
- adds ``container`` — the reference harvests per-container but never
  attributes per-container (``main.go:92-110``);
- adds slice/host topology labels for multi-host aggregation in Prometheus
  (cross-host rollups are label joins, not exporter-to-exporter traffic).

Semantic shift, documented rather than faked: NVML reports *per-process*
device memory (``main.go:135,147``); TPU runtimes pin whole chips to one
container, so the honest TPU analog is per-chip metrics labeled with the
owning pod. Core chip metrics carry no ``pid`` label by design; the
per-process dimension lives in :data:`TPU_CHIP_PROCESS_INFO`, fed by the
procfs scanner with *correct* host PIDs (unlike the reference's broken
container-PID join, SURVEY.md §2.6).
"""

from __future__ import annotations

from tpu_pod_exporter.metrics.registry import (
    COUNTER,
    GAUGE,
    HistogramSpec,
    MetricSpec,
)

# Labels identifying one chip on one host, plus its pod attribution and the
# slice topology it belongs to. Empty-string pod/namespace/container means
# "chip not allocated to any pod" — per-chip hardware series exist regardless
# of attribution.
CHIP_LABELS: tuple[str, ...] = (
    "chip_id",        # stable per-host chip index, e.g. "0".."3" on v4-8
    "device_path",    # e.g. /dev/accel0 (or vfio path); "" for fakes
    "accelerator",    # accelerator type, e.g. "v5p-64"
    "slice_name",     # GKE TPU slice / nodepool identity
    "host",           # node/host name
    "worker_id",      # worker index within a multi-host slice
    "pod",
    "namespace",
    "container",
)

ICI_LABELS: tuple[str, ...] = CHIP_LABELS + ("link",)

# --- Device metrics (analog of main.go:147-150, redesigned) -----------------

TPU_HBM_USED_BYTES = MetricSpec(
    name="tpu_hbm_used_bytes",
    help="High-bandwidth memory in use on this TPU chip, in bytes.",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

TPU_HBM_TOTAL_BYTES = MetricSpec(
    name="tpu_hbm_total_bytes",
    help="Total high-bandwidth memory capacity of this TPU chip, in bytes.",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

# Percent analog of docker_gpu_memory_perc_usage (main.go:149-150), per chip.
TPU_HBM_USED_PERCENT = MetricSpec(
    name="tpu_hbm_used_percent",
    help="Percent of this TPU chip's HBM capacity currently in use (0-100).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

TPU_HBM_PEAK_BYTES = MetricSpec(
    name="tpu_hbm_peak_bytes",
    help="Allocator high-water mark of HBM use on this chip since runtime start (absent when the backend cannot report it).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

# Hardware identity, emitted when the backend knows it (jaxdev reports
# device_kind and torus coords; the libtpu metrics service does not).
TPU_CHIP_INFO = MetricSpec(
    name="tpu_chip_info",
    help="Static chip identity; value is always 1. coords is the chip's torus position (x,y,z). Published for every chip each round (possibly with empty kind/coords) — the guaranteed per-chip presence series that slice rollups count chips from, since tpu_hbm_* may be absent on backends that cannot read HBM.",
    type=GAUGE,
    label_names=CHIP_LABELS + ("device_kind", "coords"),
)

TPU_TENSORCORE_DUTY_CYCLE_PERCENT = MetricSpec(
    name="tpu_tensorcore_duty_cycle_percent",
    help="Percent of time the chip's TensorCore was busy over the last sample window (0-100).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

# --- ICI (inter-chip interconnect) metrics ----------------------------------

TPU_ICI_LINK_BANDWIDTH_BYTES_PER_SECOND = MetricSpec(
    name="tpu_ici_link_bandwidth_bytes_per_second",
    help="Observed ICI traffic rate on one inter-chip link since the previous poll.",
    type=GAUGE,
    label_names=ICI_LABELS,
)

TPU_ICI_TRANSFERRED_BYTES_TOTAL = MetricSpec(
    name="tpu_ici_transferred_bytes_total",
    help="Cumulative bytes transferred over one inter-chip link.",
    type=COUNTER,
    label_names=ICI_LABELS,
)

# --- DCN (data-center network — cross-slice fabric, multi-slice) -------------
# Same per-link shape as ICI. Absent entirely (no series) on runtimes that
# serve no DCN counters — single-slice deployments never see these.

TPU_DCN_LINK_BANDWIDTH_BYTES_PER_SECOND = MetricSpec(
    name="tpu_dcn_link_bandwidth_bytes_per_second",
    help="Observed DCN (cross-slice network) traffic rate on one link since the previous poll.",
    type=GAUGE,
    label_names=ICI_LABELS,
)

TPU_DCN_TRANSFERRED_BYTES_TOTAL = MetricSpec(
    name="tpu_dcn_transferred_bytes_total",
    help="Cumulative bytes transferred over one DCN (cross-slice network) link.",
    type=COUNTER,
    label_names=ICI_LABELS,
)

# --- Per-process holders (procfs scanner; --process-metrics) -----------------

# pid/comm/pod_uid come from /proc: the process that holds the chip's device
# file open and its cgroup-derived pod UID. This is the honest TPU analog of
# the reference's per-process NVML dimension (main.go:135-154) — correct host
# PIDs with no exec and no PID-namespace confusion (SURVEY.md §2.6).
PROCESS_LABELS: tuple[str, ...] = CHIP_LABELS + ("pid", "comm", "pod_uid")

TPU_CHIP_PROCESS_INFO = MetricSpec(
    name="tpu_chip_process_info",
    help=(
        "One series per (process, chip): the process with this host pid holds "
        "the chip's device file open; value is always 1. pod/namespace/container "
        "come from the kubelet allocation, pod_uid from the process's cgroup."
    ),
    type=GAUGE,
    label_names=PROCESS_LABELS,
)

# --- GPU device family (backend/nvml.py) -------------------------------------
# Twins of the node surface for the second device family: the NVML-shaped
# backend publishes per-chip series under gpu_* instead of tpu_*, keyed by
# ChipInfo.family — a mixed GPU/TPU fleet must never sum across families.
# Same label schema as the TPU twins (chip_id is the NVML device index,
# main.go:123-124; device_kind carries DeviceGetName). Conditional surface:
# declared only on exporters whose backend (or any observed chip) is
# GPU-family, the same rule as TPU_CHIP_PROCESS_INFO.

GPU_HBM_USED_BYTES = MetricSpec(
    name="gpu_hbm_used_bytes",
    help="Device memory in use on this GPU, in bytes (NVML GetMemoryInfo.used).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

GPU_HBM_TOTAL_BYTES = MetricSpec(
    name="gpu_hbm_total_bytes",
    help="Total device memory capacity of this GPU, in bytes (NVML GetMemoryInfo.total).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

GPU_HBM_USED_PERCENT = MetricSpec(
    name="gpu_hbm_used_percent",
    help="Percent of this GPU's device memory currently in use (0-100) — the per-chip analog of the reference's docker_gpu_memory_perc_usage (main.go:149-150).",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

GPU_UTILIZATION_PERCENT = MetricSpec(
    name="gpu_utilization_percent",
    help="GPU compute-unit utilization over the last sample window (0-100, NVML GetUtilizationRates.gpu) — the GPU twin of tpu_tensorcore_duty_cycle_percent. Absent on boards whose driver reports NOT_SUPPORTED.",
    type=GAUGE,
    label_names=CHIP_LABELS,
)

GPU_CHIP_INFO = MetricSpec(
    name="gpu_chip_info",
    help="Static GPU identity; value is always 1. device_kind is the NVML marketing name. The guaranteed per-chip presence series GPU slice rollups count chips from — same contract as tpu_chip_info.",
    type=GAUGE,
    label_names=CHIP_LABELS + ("device_kind", "coords"),
)

# The reference's headline dimension, honest on GPU: NVML reports true
# per-process device memory (main.go:135,147), so unlike the TPU path this
# is the runtime's own table, not a procfs holder scan. pod/namespace/
# container labels come from the same podresources device-ID join as every
# other chip series.
GPU_PROCESS_MEMORY_USED_BYTES = MetricSpec(
    name="gpu_process_memory_used_bytes",
    help="Device memory used by one process on this GPU, in bytes (NVML GetComputeRunningProcesses, main.go:134-155); pod attribution via the kubelet podresources join.",
    type=GAUGE,
    label_names=CHIP_LABELS + ("pid", "comm"),
)

GPU_BACKEND_UP = MetricSpec(
    name="gpu_backend_up",
    help="1 if the most recent poll read the GPU backend without fatal error, else 0 — the per-backend up twin of the device half of tpu_exporter_up, so mixed-fleet dashboards can alert per family.",
    type=GAUGE,
)

# --- Pod-level rollups -------------------------------------------------------

POD_LABELS: tuple[str, ...] = ("pod", "namespace", "accelerator", "slice_name", "host", "worker_id")

TPU_POD_CHIP_COUNT = MetricSpec(
    name="tpu_pod_chip_count",
    help="Number of TPU chips currently allocated to this pod on this host.",
    type=GAUGE,
    label_names=POD_LABELS,
)

TPU_POD_HBM_USED_BYTES = MetricSpec(
    name="tpu_pod_hbm_used_bytes",
    help="Sum of HBM bytes in use across all chips allocated to this pod on this host.",
    type=GAUGE,
    label_names=POD_LABELS,
)

# GPU twins of the pod rollups — the paper's headline metric
# (pod_gpu_memory_usage, main.go:21-28) with the label-schema defects
# fixed: namespace/host/slice labels, chip counts, and device memory from
# the podresources join instead of the broken container-PID scan.
GPU_POD_CHIP_COUNT = MetricSpec(
    name="gpu_pod_chip_count",
    help="Number of GPUs currently allocated to this pod on this host.",
    type=GAUGE,
    label_names=POD_LABELS,
)

GPU_POD_MEMORY_USED_BYTES = MetricSpec(
    name="gpu_pod_memory_used_bytes",
    help="Sum of device-memory bytes in use across all GPUs allocated to this pod on this host — the per-pod GPU memory headline (main.go:24,147), via the same kubelet device-ID join the TPU path uses.",
    type=GAUGE,
    label_names=POD_LABELS,
)

# The conditional GPU node surface, declared as a block once the exporter
# is (or observes) the GPU family — stable from that poll on.
GPU_NODE_SPECS: tuple[MetricSpec, ...] = (
    GPU_HBM_USED_BYTES,
    GPU_HBM_TOTAL_BYTES,
    GPU_HBM_USED_PERCENT,
    GPU_UTILIZATION_PERCENT,
    GPU_CHIP_INFO,
    GPU_PROCESS_MEMORY_USED_BYTES,
    GPU_POD_CHIP_COUNT,
    GPU_POD_MEMORY_USED_BYTES,
    GPU_BACKEND_UP,
)

# --- Kubelet inventory (podresources GetAllocatableResources) ----------------

# Derived, not restated: the collector's _topo_tuple is built positionally
# in this exact order, so divergence would publish values under wrong names.
TOPO_LABELS: tuple[str, ...] = CHIP_LABELS[2:6]

TPU_KUBELET_ALLOCATABLE_CHIPS = MetricSpec(
    name="tpu_kubelet_allocatable_chips",
    help="TPU devices the kubelet device plugin reports as allocatable on this node (absent when the kubelet cannot report it).",
    type=GAUGE,
    label_names=TOPO_LABELS,
)

TPU_KUBELET_ALLOCATED_CHIPS = MetricSpec(
    name="tpu_kubelet_allocated_chips",
    help="TPU devices currently allocated to pods on this node, per the kubelet.",
    type=GAUGE,
    label_names=TOPO_LABELS,
)

# --- Host identity (multi-slice membership join key) -------------------------

TPU_HOST_INFO = MetricSpec(
    name="tpu_host_info",
    help="Static host identity incl. multi-slice membership; value is always 1. multislice_group is the cross-slice rollup join key (empty outside multi-slice deployments).",
    type=GAUGE,
    label_names=TOPO_LABELS + ("multislice_group", "num_slices"),
)

# --- Exporter self-metrics (SURVEY.md §5: tracing/observability) -------------

TPU_EXPORTER_UP = MetricSpec(
    name="tpu_exporter_up",
    help="1 if the most recent poll completed without fatal error, else 0.",
    type=GAUGE,
)

TPU_EXPORTER_POLL_DURATION_SECONDS = MetricSpec(
    name="tpu_exporter_poll_duration_seconds",
    help="Duration of the most recent poll, by phase (device_read, attribution, join, publish, total).",
    type=GAUGE,
    label_names=("phase",),
)

# Distribution companions to the point-in-time gauges above (VERDICT r4
# "latency distributions"): a p99 of the exporter's own phases must be
# computable from its exposition alone (histogram_quantile over _bucket).
# Bounds span 100 µs (cheap phases at 4 chips) to 2.5 s (first poll against
# a cold runtime); the scrape set stops at 250 ms since the contract is
# p99 < 50 ms and everything past that is pathological anyway.
POLL_DURATION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5,
)
SCRAPE_DURATION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25,
)

TPU_EXPORTER_POLL_PHASE_DURATION_HIST = HistogramSpec(
    name="tpu_exporter_poll_phase_duration_seconds",
    help="Distribution of poll durations by phase since exporter start.",
    buckets=POLL_DURATION_BUCKETS,
    label_names=("phase",),
)

TPU_EXPORTER_SCRAPE_DURATION_HIST = HistogramSpec(
    name="tpu_exporter_scrape_duration_seconds",
    help="Distribution of /metrics request handling durations since exporter start (served scrapes only; rejects are counted in tpu_exporter_scrape_rejects_total).",
    buckets=SCRAPE_DURATION_BUCKETS,
)

TPU_EXPORTER_POLL_ERRORS_TOTAL = MetricSpec(
    name="tpu_exporter_poll_errors_total",
    help="Count of poll-phase errors since exporter start, by source.",
    type=COUNTER,
    label_names=("source",),
)

TPU_EXPORTER_POLLS_TOTAL = MetricSpec(
    name="tpu_exporter_polls_total",
    help="Count of completed poll iterations since exporter start.",
    type=COUNTER,
)

TPU_EXPORTER_POLL_OVERRUNS_TOTAL = MetricSpec(
    name="tpu_exporter_poll_overruns_total",
    help="Poll ticks skipped because the previous iteration overran the interval — rising means the interval is too tight for this host/backend.",
    type=COUNTER,
)

TPU_EXPORTER_SERIES = MetricSpec(
    name="tpu_exporter_series",
    help="Number of time series in the current snapshot.",
    type=GAUGE,
)

TPU_EXPORTER_LAST_POLL_TIMESTAMP_SECONDS = MetricSpec(
    name="tpu_exporter_last_poll_timestamp_seconds",
    help="Unix timestamp of the most recent completed poll.",
    type=GAUGE,
)

# Self-resource accounting: the <1% node CPU budget (BASELINE.md) must be
# auditable in production, not just in bench.py.
TPU_EXPORTER_CPU_SECONDS_TOTAL = MetricSpec(
    name="tpu_exporter_cpu_seconds_total",
    help="Total user+system CPU time consumed by the exporter process.",
    type=COUNTER,
)

TPU_EXPORTER_RSS_BYTES = MetricSpec(
    name="tpu_exporter_rss_bytes",
    help="Resident set size of the exporter process (absent when /proc/self/statm is unreadable).",
    type=GAUGE,
)

TPU_EXPORTER_SCRAPE_REJECTS_TOTAL = MetricSpec(
    name="tpu_exporter_scrape_rejects_total",
    help="Scrapes rejected with 429 since start, by cause: 'concurrency' (too many in-flight renders: slow scrapers or too many of them) vs 'rate' (token bucket: scraping too often). The fixes differ, so the counter splits.",
    type=COUNTER,
    label_names=("cause",),
)

# --- Source supervision (tpu_pod_exporter.supervisor) ------------------------
# One series set per supervised source (device / attribution / process_scan).
# Families are declared unconditionally (stable surface); samples appear
# only when supervision is enabled (--phase-deadline-s > 0, the default).

TPU_EXPORTER_SOURCE_BREAKER_STATE = MetricSpec(
    name="tpu_exporter_source_breaker_state",
    help="Circuit-breaker state of this poll source: 0=closed (healthy), 1=open (quarantined, backoff running), 2=half_open (single probe in flight).",
    type=GAUGE,
    label_names=("source",),
)

TPU_EXPORTER_SOURCE_BREAKER_TRANSITIONS_TOTAL = MetricSpec(
    name="tpu_exporter_source_breaker_transitions_total",
    help="Breaker state entries since exporter start, by source and entered state (state=closed counts recoveries; a never-failed source shows zero everywhere).",
    type=COUNTER,
    label_names=("source", "state"),
)

TPU_EXPORTER_SOURCE_CALLS_ABANDONED_TOTAL = MetricSpec(
    name="tpu_exporter_source_calls_abandoned_total",
    help="Supervised calls abandoned at the phase deadline (--phase-deadline-s): the worker thread was fenced off, the phase degraded as an error. Rising = the source HANGS rather than errors.",
    type=COUNTER,
    label_names=("source",),
)

TPU_EXPORTER_SOURCE_CALLS_SKIPPED_TOTAL = MetricSpec(
    name="tpu_exporter_source_calls_skipped_total",
    help="Poll-phase calls skipped because the source's breaker was open with backoff pending (the quarantine working as designed, not an extra fault).",
    type=COUNTER,
    label_names=("source",),
)

TPU_EXPORTER_SOURCE_RECONNECTS_TOTAL = MetricSpec(
    name="tpu_exporter_source_reconnects_total",
    help="close()+re-open() reconnects issued before half-open breaker probes — a wedged gRPC channel is replaced, not retried into. Compare with breaker transitions to closed to see whether reconnects actually recover the source.",
    type=COUNTER,
    label_names=("source",),
)

# --- Poll tracing (tpu_pod_exporter.trace) -----------------------------------
# Declared unconditionally (stable surface); samples appear only while
# tracing is enabled (--trace, the default) — same conditional-sample rule
# as the supervision series above.

TPU_EXPORTER_SLOW_POLLS_TOTAL = MetricSpec(
    name="tpu_exporter_slow_polls_total",
    help="Polls whose total duration exceeded --trace-slow-poll-s; each carries a sampled stack profile in its trace (GET /debug/trace, loopback-only by default).",
    type=COUNTER,
)

TPU_EXPORTER_TRACES = MetricSpec(
    name="tpu_exporter_traces",
    help="Poll traces currently retained in the bounded in-memory trace ring (--trace-max-traces).",
    type=GAUGE,
)

TPU_EXPORTER_TRACE_SPANS = MetricSpec(
    name="tpu_exporter_trace_spans",
    help="Spans retained across all traces in the ring — the /debug/trace export size driver.",
    type=GAUGE,
)

# --- Restart survivability (tpu_pod_exporter.persist) ------------------------
# warm_start / snapshot_stale live in ALL_SPECS (published 0 on every live
# poll) so the restored exposition can flip them by VALUE EDIT, never by
# header injection — a warm body stays a valid single-header exposition.

TPU_EXPORTER_WARM_START = MetricSpec(
    name="tpu_exporter_warm_start",
    help="1 while serving the restored pre-restart exposition snapshot (warm start: the process restarted and no live poll has completed yet); 0 on every live poll. Scrapes during warm start carry last-known data — check tpu_exporter_snapshot_stale_seconds for its age.",
    type=GAUGE,
)

TPU_EXPORTER_SNAPSHOT_STALE_SECONDS = MetricSpec(
    name="tpu_exporter_snapshot_stale_seconds",
    help="Age of the restored exposition at the moment serving resumed after a restart (0 on live polls). Combine with tpu_exporter_last_poll_timestamp_seconds for ongoing staleness while warm_start=1.",
    type=GAUGE,
)

TPU_EXPORTER_CLIENT_WRITE_TIMEOUTS_TOTAL = MetricSpec(
    name="tpu_exporter_client_write_timeouts_total",
    help="Connections dropped because a client stalled reading a response past --client-write-timeout-s (per-connection socket send timeout): a wedged scraper must not pin a handler thread forever.",
    type=COUNTER,
)

TPU_EXPORTER_INFO = MetricSpec(
    name="tpu_exporter_info",
    help="Static exporter build/runtime info; value is always 1.",
    type=GAUGE,
    label_names=("version", "backend", "attribution"),
)

# --- History flight recorder self-metrics (tpu_pod_exporter.history) ---------
# Emitted only when history is enabled (--history-retention-s > 0), so they
# live outside ALL_SPECS — same conditional-surface rule as
# TPU_CHIP_PROCESS_INFO. Size/eviction/append-time must be auditable: the
# store is hard-bounded, and these say how close to the bound it runs.

TPU_EXPORTER_HISTORY_SERIES = MetricSpec(
    name="tpu_exporter_history_series",
    help="Series currently held in the in-memory history store (bounded by --history-max-series).",
    type=GAUGE,
)

TPU_EXPORTER_HISTORY_SAMPLES = MetricSpec(
    name="tpu_exporter_history_samples",
    help="Samples currently retained across all history ring buffers.",
    type=GAUGE,
)

TPU_EXPORTER_HISTORY_MEMORY_BYTES = MetricSpec(
    name="tpu_exporter_history_memory_bytes",
    help="Preallocated ring-buffer bytes held by the history store (series x capacity x 24).",
    type=GAUGE,
)

TPU_EXPORTER_HISTORY_EVICTED_SERIES_TOTAL = MetricSpec(
    name="tpu_exporter_history_evicted_series_total",
    help="History series dropped since start, by reason: 'capacity' (--history-max-series hit; raise it or expect churned series to age out) vs 'retention' (idle past --history-retention-s — normal pod churn).",
    type=COUNTER,
    label_names=("reason",),
)

TPU_EXPORTER_HISTORY_APPEND_SECONDS = MetricSpec(
    name="tpu_exporter_history_append_seconds",
    help="Duration of the most recent history append (runs after the snapshot swap, off the scrape path; one poll behind).",
    type=GAUGE,
)

# Multi-resolution downsample tiers (history.DEFAULT_TIER_SPEC): occupancy
# and answerable span per tier, labeled by bucket width in seconds. These
# are how an operator audits that long-range query_range answers actually
# have tier data behind them (the Grafana "tier occupancy" panel).
TPU_EXPORTER_HISTORY_TIER_BUCKETS = MetricSpec(
    name="tpu_exporter_history_tier_buckets",
    help="Downsample buckets currently retained across all series of this tier (open accumulator buckets included).",
    type=GAUGE,
    label_names=("tier",),
)

TPU_EXPORTER_HISTORY_TIER_SPAN_SECONDS = MetricSpec(
    name="tpu_exporter_history_tier_span_seconds",
    help="Wall-clock span this downsample tier can currently answer for (newest minus oldest retained bucket) — how far back a query_range at this tier's resolution reaches.",
    type=GAUGE,
    label_names=("tier",),
)

HISTORY_SPECS: tuple[MetricSpec, ...] = (
    TPU_EXPORTER_HISTORY_SERIES,
    TPU_EXPORTER_HISTORY_SAMPLES,
    TPU_EXPORTER_HISTORY_MEMORY_BYTES,
    TPU_EXPORTER_HISTORY_EVICTED_SERIES_TOTAL,
    TPU_EXPORTER_HISTORY_APPEND_SECONDS,
    TPU_EXPORTER_HISTORY_TIER_BUCKETS,
    TPU_EXPORTER_HISTORY_TIER_SPAN_SECONDS,
)

# --- Persistence self-metrics (tpu_pod_exporter.persist) ----------------------
# Emitted only when persistence is enabled (--state-dir set) — the same
# conditional-surface rule as HISTORY_SPECS. The WAL/snapshot health must be
# auditable from the exposition: a silently-failing state dir would only be
# discovered at the NEXT restart, which is exactly too late.

TPU_EXPORTER_PERSIST_WAL_BYTES = MetricSpec(
    name="tpu_exporter_persist_wal_bytes",
    help="Current size of the write-ahead log under --state-dir (resets to near zero at each checkpoint rotation).",
    type=GAUGE,
)

TPU_EXPORTER_PERSIST_WAL_RECORDS_TOTAL = MetricSpec(
    name="tpu_exporter_persist_wal_records_total",
    help="WAL records written since exporter start (samples, layout, and breaker records).",
    type=COUNTER,
)

TPU_EXPORTER_PERSIST_SNAPSHOTS_TOTAL = MetricSpec(
    name="tpu_exporter_persist_snapshots_total",
    help="State checkpoints written since exporter start (write-temp, fsync, rename; cadence --state-snapshot-interval-s).",
    type=COUNTER,
)

TPU_EXPORTER_PERSIST_ERRORS_TOTAL = MetricSpec(
    name="tpu_exporter_persist_errors_total",
    help="Persistence I/O failures since start (WAL writes, fsyncs, checkpoint rotations), by reason: 'disk_full' (ENOSPC/EDQUOT — the disk is FULL, not flaky; the resource-pressure governor sheds on it) vs 'io' (every other filesystem fault). Rising = the state dir's filesystem is failing; the exporter keeps polling but the next restart will cold-start or restore stale state.",
    type=COUNTER,
    label_names=("reason",),
)

TPU_EXPORTER_PERSIST_DROPPED_TOTAL = MetricSpec(
    name="tpu_exporter_persist_dropped_total",
    help="Poll records dropped by persistence WITHOUT being written, by reason: 'queue' (writer queue full — stalled disk), 'disk_full' (the write itself hit ENOSPC/EDQUOT), 'io' (other write failure), 'shed' (deliberately thinned/skipped by the resource-pressure governor's WAL rungs). Polling is never blocked by persistence, so sustained drops mean history restored after a crash will have holes.",
    type=COUNTER,
    label_names=("reason",),
)

TPU_EXPORTER_PERSIST_FSYNC_SECONDS = MetricSpec(
    name="tpu_exporter_persist_fsync_seconds",
    help="Duration of the most recent WAL fsync (cadence --state-fsync-interval-s; 0 syncs every record). The persistence hot path's latency budget check (make persist-fsync-check) polices the same number in CI.",
    type=GAUGE,
)

TPU_EXPORTER_PERSIST_SNAPSHOT_AGE_SECONDS = MetricSpec(
    name="tpu_exporter_persist_snapshot_age_seconds",
    help="Seconds since the last on-disk state checkpoint was written (the worst-case exposition staleness a crash right now would restore). Absent until the first rotation of this process.",
    type=GAUGE,
)

PERSIST_SPECS: tuple[MetricSpec, ...] = (
    TPU_EXPORTER_PERSIST_WAL_BYTES,
    TPU_EXPORTER_PERSIST_WAL_RECORDS_TOTAL,
    TPU_EXPORTER_PERSIST_SNAPSHOTS_TOTAL,
    TPU_EXPORTER_PERSIST_ERRORS_TOTAL,
    TPU_EXPORTER_PERSIST_DROPPED_TOTAL,
    TPU_EXPORTER_PERSIST_FSYNC_SECONDS,
    TPU_EXPORTER_PERSIST_SNAPSHOT_AGE_SECONDS,
)

# --- Resource-pressure governor (tpu_pod_exporter.pressure) ------------------
# Emitted only when a governor is attached (a disk or memory budget is
# configured) — the same conditional-surface rule as PERSIST_SPECS. The
# whole point of the governor is that degradation under ENOSPC/RSS
# pressure happens BY POLICY and is attributable from the exposition
# alone: the ladder rung is a gauge, every shed/recover a counted
# transition, and the bytes-vs-budget pair the decision was made on is
# published verbatim.

TPU_EXPORTER_PRESSURE_STATE = MetricSpec(
    name="tpu_exporter_pressure_state",
    help="Resource-pressure degradation ladder rung per resource ('disk', 'memory'): 0 = no shedding; each higher rung is one more deliberate degradation (disk: WAL thinning -> egress compaction/trim -> checkpoint halving -> WAL off; memory: fleet-cache off -> trace-ring halving -> raw history-ring cut). Recovery steps down rung by rung with hysteresis.",
    type=GAUGE,
    label_names=("resource",),
)

TPU_EXPORTER_PRESSURE_BYTES = MetricSpec(
    name="tpu_exporter_pressure_bytes",
    help="Accounted usage per governed resource: 'disk' = bytes on disk under --state-dir plus --egress-dir; 'memory' = byte-accounted total of the registered in-memory components (history rings, trace ring, fleet query cache, root stale-serve views).",
    type=GAUGE,
    label_names=("resource",),
)

TPU_EXPORTER_PRESSURE_BUDGET_BYTES = MetricSpec(
    name="tpu_exporter_pressure_budget_bytes",
    help="Configured budget per governed resource (--state-max-disk-mb / --memory-budget-mb); 0 = no byte budget (the disk ladder still sheds on reported ENOSPC).",
    type=GAUGE,
    label_names=("resource",),
)

TPU_EXPORTER_PRESSURE_TRANSITIONS_TOTAL = MetricSpec(
    name="tpu_exporter_pressure_transitions_total",
    help="Ladder transitions per resource and direction ('shed' = one rung up under pressure, 'recover' = one rung released after the hysteresis window). A sawtooth here means the budget sits exactly at the steady-state working set — raise it.",
    type=COUNTER,
    label_names=("resource", "direction"),
)

PRESSURE_SPECS: tuple[MetricSpec, ...] = (
    TPU_EXPORTER_PRESSURE_STATE,
    TPU_EXPORTER_PRESSURE_BYTES,
    TPU_EXPORTER_PRESSURE_BUDGET_BYTES,
    TPU_EXPORTER_PRESSURE_TRANSITIONS_TOTAL,
)

# --- Remote-write egress (tpu_pod_exporter.egress) ---------------------------
# Emitted only when egress is enabled (--egress-url set) — the same
# conditional-surface rule as PERSIST_SPECS. Both the exporter and the
# aggregator attach a RemoteWriteShipper, so both expositions may carry
# these. The send buffer's health must be auditable from the exposition:
# a receiver outage shows as breaker_state=1 + growing backlog, and a
# silently-dropping backlog cap is exactly the loss the alert rules watch.

TPU_EXPORTER_EGRESS_SENT_BATCHES_TOTAL = MetricSpec(
    name="tpu_exporter_egress_sent_batches_total",
    help="Remote-write batches acknowledged by the receiver (2xx) since start. Each acked batch is durably marked in the send buffer's cursor, so a restart never re-sends it.",
    type=COUNTER,
)

TPU_EXPORTER_EGRESS_SENT_SAMPLES_TOTAL = MetricSpec(
    name="tpu_exporter_egress_sent_samples_total",
    help="Samples delivered inside acknowledged remote-write batches since start.",
    type=COUNTER,
)

TPU_EXPORTER_EGRESS_FAILED_SENDS_TOTAL = MetricSpec(
    name="tpu_exporter_egress_failed_sends_total",
    help="Remote-write send attempts that failed (timeout, connection error, 5xx, or 429 backpressure) since start. Failed batches stay in the durable send buffer and are retried breaker-gated; compare with dropped to tell 'retrying' from 'losing'.",
    type=COUNTER,
)

TPU_EXPORTER_EGRESS_DROPPED_TOTAL = MetricSpec(
    name="tpu_exporter_egress_dropped_total",
    help="Batches removed from the send buffer WITHOUT delivery, by reason: 'backlog' (bytes/age cap while the receiver was down), 'poison' (non-429 4xx — the receiver rejects the batch body, retrying would wedge the queue), 'queue' (poll-side handoff full: the egress writer stalled), 'corrupt' (torn/scrambled buffer records truncated at boot).",
    type=COUNTER,
    label_names=("reason",),
)

TPU_EXPORTER_EGRESS_BACKLOG_BATCHES = MetricSpec(
    name="tpu_exporter_egress_backlog_batches",
    help="Batches currently sitting in the durable send buffer awaiting acknowledgement (0 when the shipper is keeping up).",
    type=GAUGE,
)

TPU_EXPORTER_EGRESS_BACKLOG_BYTES = MetricSpec(
    name="tpu_exporter_egress_backlog_bytes",
    help="On-disk bytes of unacknowledged batches in the send buffer under --egress-dir (bounded by --egress-max-backlog-mb).",
    type=GAUGE,
)

TPU_EXPORTER_EGRESS_BACKLOG_AGE_SECONDS = MetricSpec(
    name="tpu_exporter_egress_backlog_age_seconds",
    help="Age of the OLDEST unacknowledged batch in the send buffer (0 when empty) — how far behind the receiver the shipped telemetry is; bounded by --egress-max-backlog-age-s.",
    type=GAUGE,
)

TPU_EXPORTER_EGRESS_BREAKER_STATE = MetricSpec(
    name="tpu_exporter_egress_breaker_state",
    help="Remote-write receiver circuit breaker: 0=closed (healthy), 1=open (receiver quarantined, backoff running, batches buffering to disk), 2=half_open (single probe batch in flight).",
    type=GAUGE,
)

TPU_EXPORTER_EGRESS_SEND_SECONDS_HIST = HistogramSpec(
    name="tpu_exporter_egress_send_seconds",
    help="Distribution of remote-write send round-trips since start (successful and failed attempts; breaker-skipped sends are not attempts).",
    buckets=POLL_DURATION_BUCKETS,
)

EGRESS_SPECS: tuple[MetricSpec, ...] = (
    TPU_EXPORTER_EGRESS_SENT_BATCHES_TOTAL,
    TPU_EXPORTER_EGRESS_SENT_SAMPLES_TOTAL,
    TPU_EXPORTER_EGRESS_FAILED_SENDS_TOTAL,
    TPU_EXPORTER_EGRESS_DROPPED_TOTAL,
    TPU_EXPORTER_EGRESS_BACKLOG_BATCHES,
    TPU_EXPORTER_EGRESS_BACKLOG_BYTES,
    TPU_EXPORTER_EGRESS_BACKLOG_AGE_SECONDS,
    TPU_EXPORTER_EGRESS_BREAKER_STATE,
)

# --- Legacy migration aliases (off by default; --legacy-metrics) ------------
# The reference's exact metric names (main.go:24,31) so its dashboards work
# unchanged during migration. Semantic shift, documented in the help text:
# the reference's value was per-process GPU memory keyed {pid, pod}
# (main.go:147-150); TPU runtimes pin whole chips to one container, so the
# honest equivalent is per-pod HBM totals. The pid label carries the chip's
# primary holder pid when the procfs scanner is on (--process-metrics),
# else "".
LEGACY_POD_MEMORY_USAGE = MetricSpec(
    name="pod_gpu_memory_usage",
    help="DEPRECATED migration alias: device memory used by this pod's chips, bytes (TPU: per-pod HBM; pid is the chip's holder pid when --process-metrics is on, else empty).",
    type=GAUGE,
    label_names=("pid", "pod"),
)

LEGACY_POD_MEMORY_PERC_USAGE = MetricSpec(
    name="docker_gpu_memory_perc_usage",
    help="DEPRECATED migration alias: percent of this pod's chips' total device memory in use (pid is the chip's holder pid when --process-metrics is on, else empty).",
    type=GAUGE,
    label_names=("pid", "pod"),
)

ALL_SPECS: tuple[MetricSpec, ...] = (
    TPU_HBM_USED_BYTES,
    TPU_HBM_TOTAL_BYTES,
    TPU_HBM_USED_PERCENT,
    TPU_HBM_PEAK_BYTES,
    TPU_CHIP_INFO,
    TPU_TENSORCORE_DUTY_CYCLE_PERCENT,
    TPU_ICI_LINK_BANDWIDTH_BYTES_PER_SECOND,
    TPU_ICI_TRANSFERRED_BYTES_TOTAL,
    TPU_DCN_LINK_BANDWIDTH_BYTES_PER_SECOND,
    TPU_DCN_TRANSFERRED_BYTES_TOTAL,
    TPU_HOST_INFO,
    TPU_POD_CHIP_COUNT,
    TPU_POD_HBM_USED_BYTES,
    TPU_KUBELET_ALLOCATABLE_CHIPS,
    TPU_KUBELET_ALLOCATED_CHIPS,
    TPU_EXPORTER_UP,
    TPU_EXPORTER_POLL_DURATION_SECONDS,
    TPU_EXPORTER_POLL_ERRORS_TOTAL,
    TPU_EXPORTER_POLLS_TOTAL,
    TPU_EXPORTER_POLL_OVERRUNS_TOTAL,
    TPU_EXPORTER_SERIES,
    TPU_EXPORTER_LAST_POLL_TIMESTAMP_SECONDS,
    TPU_EXPORTER_CPU_SECONDS_TOTAL,
    TPU_EXPORTER_RSS_BYTES,
    TPU_EXPORTER_SCRAPE_REJECTS_TOTAL,
    TPU_EXPORTER_SOURCE_BREAKER_STATE,
    TPU_EXPORTER_SOURCE_BREAKER_TRANSITIONS_TOTAL,
    TPU_EXPORTER_SOURCE_CALLS_ABANDONED_TOTAL,
    TPU_EXPORTER_SOURCE_CALLS_SKIPPED_TOTAL,
    TPU_EXPORTER_SOURCE_RECONNECTS_TOTAL,
    TPU_EXPORTER_SLOW_POLLS_TOTAL,
    TPU_EXPORTER_TRACES,
    TPU_EXPORTER_TRACE_SPANS,
    TPU_EXPORTER_WARM_START,
    TPU_EXPORTER_SNAPSHOT_STALE_SECONDS,
    TPU_EXPORTER_CLIENT_WRITE_TIMEOUTS_TOTAL,
    TPU_EXPORTER_INFO,
)


# --- Slice-aggregator schema (tpu_pod_exporter.aggregate) --------------------
# Served by the optional aggregator, NOT by per-host exporters (hence not in
# ALL_SPECS). Cross-host rollups normally live in Prometheus recording rules
# (SURVEY.md §2.8); the aggregator computes the same label joins for setups
# without one, scraping each host's /metrics and re-exporting slice sums.

# family is the accelerator-family rollup key ("tpu" | "gpu"): slices are
# homogeneous (a GKE node pool is one device family), but the label rides
# every slice rollup so fleet-level sums can stay family-correct and the
# FleetStore's recording rules can aggregate `by (family)`.
SLICE_LABELS: tuple[str, ...] = ("slice_name", "accelerator", "family")

TPU_SLICE_HOSTS_REPORTING = MetricSpec(
    name="tpu_slice_hosts_reporting",
    help="Hosts of this slice contributing chip samples this round (a scraped-but-chipless host counts in tpu_aggregator_target_up, not here).",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_CHIP_COUNT = MetricSpec(
    name="tpu_slice_chip_count",
    help="TPU chips reporting across all scraped hosts of this slice.",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_HBM_USED_BYTES = MetricSpec(
    name="tpu_slice_hbm_used_bytes",
    help="Sum of HBM bytes in use across all chips of this slice.",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_HBM_TOTAL_BYTES = MetricSpec(
    name="tpu_slice_hbm_total_bytes",
    help="Sum of HBM capacity across all chips of this slice.",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_HBM_USED_PERCENT = MetricSpec(
    name="tpu_slice_hbm_used_percent",
    help="Percent of the slice's total HBM capacity in use (0-100).",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_DUTY_CYCLE_AVG_PERCENT = MetricSpec(
    name="tpu_slice_tensorcore_duty_cycle_avg_percent",
    help="Mean TensorCore duty cycle across the slice's reporting chips (0-100).",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_ICI_BYTES_PER_SECOND = MetricSpec(
    name="tpu_slice_ici_bytes_per_second",
    help="Sum of per-link ICI traffic rates across the slice.",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

TPU_SLICE_DCN_BYTES_PER_SECOND = MetricSpec(
    name="tpu_slice_dcn_bytes_per_second",
    help="Sum of per-link DCN (cross-slice network) traffic rates across the slice.",
    type=GAUGE,
    label_names=SLICE_LABELS,
)

# --- Per-family fleet rollups -------------------------------------------------
# Sums of the slice rollups grouped by accelerator family, emitted through
# the same emit_rollups path at every tier (flat aggregator, sharded root):
# the "how much GPU vs TPU is this fleet running" headline, and the series
# the mixed-fleet drills assert against a flat per-family oracle.

FAMILY_LABELS: tuple[str, ...] = ("family",)

TPU_FLEET_FAMILY_HOSTS_REPORTING = MetricSpec(
    name="tpu_fleet_family_hosts_reporting",
    help="Hosts contributing chip samples this round, per accelerator family (tpu/gpu).",
    type=GAUGE,
    label_names=FAMILY_LABELS,
)

TPU_FLEET_FAMILY_CHIP_COUNT = MetricSpec(
    name="tpu_fleet_family_chip_count",
    help="Chips reporting across all scraped slices of this accelerator family — mixed fleets must never sum chips across families, so the split is published, not derived.",
    type=GAUGE,
    label_names=FAMILY_LABELS,
)

TPU_FLEET_FAMILY_HBM_USED_BYTES = MetricSpec(
    name="tpu_fleet_family_hbm_used_bytes",
    help="Device-memory bytes in use across all chips of this accelerator family (absent until at least one chip of the family reports memory).",
    type=GAUGE,
    label_names=FAMILY_LABELS,
)

TPU_FLEET_FAMILY_HBM_TOTAL_BYTES = MetricSpec(
    name="tpu_fleet_family_hbm_total_bytes",
    help="Device-memory capacity across all chips of this accelerator family (absent until at least one chip of the family reports capacity).",
    type=GAUGE,
    label_names=FAMILY_LABELS,
)

FAMILY_SPECS: tuple[MetricSpec, ...] = (
    TPU_FLEET_FAMILY_HOSTS_REPORTING,
    TPU_FLEET_FAMILY_CHIP_COUNT,
    TPU_FLEET_FAMILY_HBM_USED_BYTES,
    TPU_FLEET_FAMILY_HBM_TOTAL_BYTES,
)

# Cross-SLICE (multi-slice group) rollups. Joined via tpu_host_info's
# multislice_group label (BASELINE config 5: 2x v5p-128 over DCN); a slice
# with an empty group contributes to no group series.
MULTISLICE_LABELS: tuple[str, ...] = ("multislice_group",)

TPU_MULTISLICE_SLICES_REPORTING = MetricSpec(
    name="tpu_multislice_slices_reporting",
    help="Slices of this multi-slice group contributing chip samples this round.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_EXPECTED_SLICES = MetricSpec(
    name="tpu_multislice_expected_slices",
    help="Slices this group SHOULD have (MEGASCALE_NUM_SLICES); alert when reporting < expected.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_HOSTS_REPORTING = MetricSpec(
    name="tpu_multislice_hosts_reporting",
    help="Hosts across all slices of this group contributing chip samples this round.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_CHIP_COUNT = MetricSpec(
    name="tpu_multislice_chip_count",
    help="TPU chips reporting across all slices of this multi-slice group.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_HBM_USED_BYTES = MetricSpec(
    name="tpu_multislice_hbm_used_bytes",
    help="Sum of HBM bytes in use across all chips of this multi-slice group.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_ICI_BYTES_PER_SECOND = MetricSpec(
    name="tpu_multislice_ici_bytes_per_second",
    help="Sum of intra-slice ICI traffic rates across the group.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

TPU_MULTISLICE_DCN_BYTES_PER_SECOND = MetricSpec(
    name="tpu_multislice_dcn_bytes_per_second",
    help="Sum of cross-slice DCN traffic rates across the group.",
    type=GAUGE,
    label_names=MULTISLICE_LABELS,
)

# Cross-host workload rollups: a multi-host JobSet replica appears as the
# same {pod, namespace} on several hosts; these sum over that.
WORKLOAD_LABELS: tuple[str, ...] = ("pod", "namespace", "slice_name")

TPU_WORKLOAD_CHIP_COUNT = MetricSpec(
    name="tpu_workload_chip_count",
    help="TPU chips allocated to this workload across all hosts of the slice.",
    type=GAUGE,
    label_names=WORKLOAD_LABELS,
)

TPU_WORKLOAD_HBM_USED_BYTES = MetricSpec(
    name="tpu_workload_hbm_used_bytes",
    help="HBM bytes in use across all chips allocated to this workload, slice-wide.",
    type=GAUGE,
    label_names=WORKLOAD_LABELS,
)

TPU_WORKLOAD_HOSTS = MetricSpec(
    name="tpu_workload_hosts",
    help="Hosts on which this workload currently holds TPU chips.",
    type=GAUGE,
    label_names=WORKLOAD_LABELS,
)

# Aggregator self-metrics.
TPU_AGG_TARGET_UP = MetricSpec(
    name="tpu_aggregator_target_up",
    help="1 if this per-host exporter target was scraped successfully in the last round.",
    type=GAUGE,
    label_names=("target",),
)

TPU_AGG_SCRAPE_DURATION_SECONDS = MetricSpec(
    name="tpu_aggregator_scrape_duration_seconds",
    help="Duration of the last scrape of this target.",
    type=GAUGE,
    label_names=("target",),
)

TPU_AGG_TARGET_BREAKER_STATE = MetricSpec(
    name="tpu_aggregator_target_breaker_state",
    help="Per-target scrape circuit breaker: 0=closed, 1=open (target quarantined with backoff — its scrape AND history fallback are skipped instead of burning timeout_s every round), 2=half_open (probe in flight).",
    type=GAUGE,
    label_names=("target",),
)

TPU_AGG_SCRAPE_ERRORS_TOTAL = MetricSpec(
    name="tpu_aggregator_scrape_errors_total",
    help="Count of failed scrapes since aggregator start, by target.",
    type=COUNTER,
    label_names=("target",),
)

TPU_AGG_HISTORY_FALLBACKS_TOTAL = MetricSpec(
    name="tpu_aggregator_history_fallbacks_total",
    help="Rounds in which a target's full scrape failed but its /api/v1/window_stats history answered, so the host's last-known chip data still contributed to slice rollups (target_up stays 0 for the round).",
    type=COUNTER,
    label_names=("target",),
)

TPU_AGG_LAST_ROUND_TIMESTAMP_SECONDS = MetricSpec(
    name="tpu_aggregator_last_round_timestamp_seconds",
    help="Unix timestamp of the most recent completed aggregation round.",
    type=GAUGE,
)

TPU_AGG_ROUND_DURATION_SECONDS = MetricSpec(
    name="tpu_aggregator_round_duration_seconds",
    help="Wall time of the last full aggregation round (all targets: scrape + parse + fold + publish); budgeted in BASELINE.md.",
    type=GAUGE,
)

# Same self-resource accounting contract as the exporter's
# tpu_exporter_cpu_seconds_total / _rss_bytes: the aggregator's own cost
# at slice scale (BASELINE.md 64x256 budget) must be auditable from its
# exposition alone.
TPU_AGG_POLL_OVERRUNS_TOTAL = MetricSpec(
    name="tpu_aggregator_poll_overruns_total",
    help="Aggregation rounds skipped because the previous round overran the interval (same contract as tpu_exporter_poll_overruns_total: nonzero rate means --interval-s is too tight for the target count/latency).",
    type=COUNTER,
)

TPU_AGG_CPU_SECONDS_TOTAL = MetricSpec(
    name="tpu_aggregator_cpu_seconds_total",
    help="Total user+system CPU time consumed by the aggregator process.",
    type=COUNTER,
)

TPU_AGG_RSS_BYTES = MetricSpec(
    name="tpu_aggregator_rss_bytes",
    help="Resident set size of the aggregator process (absent when /proc/self/statm is unreadable).",
    type=GAUGE,
)

# Distribution companions (same rationale as the exporter's histograms:
# a p99 must be computable from the exposition alone). Distinct base names
# from the point-in-time gauges above — one exposition name, one type.
TPU_AGG_ROUND_HIST = HistogramSpec(
    name="tpu_aggregator_round_seconds",
    help="Distribution of full aggregation round durations since start.",
    buckets=POLL_DURATION_BUCKETS,
)

TPU_AGG_TARGET_SCRAPE_HIST = HistogramSpec(
    name="tpu_aggregator_target_scrape_seconds",
    help="Distribution of SUCCESSFUL per-target scrape durations since start, pooled across targets (failures/timeouts are excluded — see tpu_aggregator_target_up and _scrape_errors_total).",
    buckets=POLL_DURATION_BUCKETS,
)

# --- Fleet query plane (tpu_pod_exporter.fleet) -------------------------------
# Served by the aggregator only while the federated /api/v1 fan-out is
# enabled — conditional surface, like HISTORY_SPECS on the exporter, hence a
# separate tuple from AGGREGATE_SPECS.

TPU_AGG_FLEET_QUERIES_TOTAL = MetricSpec(
    name="tpu_aggregator_fleet_queries_total",
    help="Federated /api/v1 queries served since aggregator start, by route (series / query_range / window_stats). Cache hits included — they are served queries.",
    type=COUNTER,
    label_names=("route",),
)

TPU_AGG_FLEET_QUERY_PARTIAL_TOTAL = MetricSpec(
    name="tpu_aggregator_fleet_query_partial_total",
    help="Federated queries answered with partial=true (at least one non-quarantined target errored or missed its deadline, or a quarantined target's data is absent from the merge). The partial-result RATE is the fleet forensics health signal.",
    type=COUNTER,
)

TPU_AGG_FLEET_QUERY_TARGET_ERRORS_TOTAL = MetricSpec(
    name="tpu_aggregator_fleet_query_target_errors_total",
    help="Per-target fan-out failures (connection error or per-target deadline missed) across all federated queries since start.",
    type=COUNTER,
    label_names=("target",),
)

TPU_AGG_FLEET_QUERY_CACHE_HITS_TOTAL = MetricSpec(
    name="tpu_aggregator_fleet_query_cache_hits_total",
    help="Federated queries answered from the result cache (same query, same grid, same generation — dashboard-refresh traffic costs one fan-out per generation, not one per panel).",
    type=COUNTER,
)

TPU_AGG_FLEET_QUERY_CACHE_MISSES_TOTAL = MetricSpec(
    name="tpu_aggregator_fleet_query_cache_misses_total",
    help="Federated queries that required a live fan-out (cache miss or bypass).",
    type=COUNTER,
)

TPU_AGG_FLEET_QUERY_HIST = HistogramSpec(
    name="tpu_aggregator_fleet_query_seconds",
    help="Distribution of federated /api/v1 query latencies since start (fan-out + merge; cache hits excluded). The CI fleet-query p99 budget reads this.",
    buckets=POLL_DURATION_BUCKETS,
)

FLEET_QUERY_SPECS: tuple[MetricSpec, ...] = (
    TPU_AGG_FLEET_QUERIES_TOTAL,
    TPU_AGG_FLEET_QUERY_PARTIAL_TOTAL,
    TPU_AGG_FLEET_QUERY_TARGET_ERRORS_TOTAL,
    TPU_AGG_FLEET_QUERY_CACHE_HITS_TOTAL,
    TPU_AGG_FLEET_QUERY_CACHE_MISSES_TOTAL,
)

# --- Sharded aggregation tree (tpu_pod_exporter.shard) ------------------------
# Two conditional surfaces:
#
#   tpu_leaf_*  — served by LEAF aggregators (a SliceAggregator owning one
#     consistent-hash shard of node targets). These are the raw rollup
#     ACCUMULATOR COMPONENTS (sums, sample counts, coverage flags) the root
#     tier needs to merge partial per-shard rollups into exact fleet-wide
#     rollups: a mean or a used/total-coverage guard cannot be recomputed
#     from the published rollups alone, so the leaf exposes the parts.
#     Component fields ride a `field` label rather than one spec each —
#     they are an internal tier-to-tier contract, not operator surface.
#
#   tpu_root_*  — served by the ROOT aggregator that scrapes leaf
#     expositions, dedups HA pairs per series by freshest poll wall
#     timestamp, and re-exports the fleet-wide /metrics.

# Fields carried by tpu_leaf_slice_component, in emission order. The root
# rejects unknown fields rather than guessing (forward-compat: a newer
# leaf's extra fields are ignored by an older root only via this list).
LEAF_SLICE_FIELDS: tuple[str, ...] = (
    "hosts", "chips", "hbm_used", "hbm_total", "used_n", "total_n",
    "coverage_eq", "duty_sum", "duty_n", "ici_bw", "ici_n", "dcn_bw",
    "dcn_n",
)

LEAF_WORKLOAD_FIELDS: tuple[str, ...] = (
    "chips", "hbm_used", "hbm_used_n", "hosts",
)

TPU_LEAF_SLICE_COMPONENT = MetricSpec(
    name="tpu_leaf_slice_component",
    help="Raw slice-rollup accumulator component for this leaf's shard (see field label: sums, sample counts, and the used/total coverage-equality flag). Tier-to-tier contract consumed by the root aggregator; operators should read the tpu_slice_* rollups instead.",
    type=GAUGE,
    label_names=SLICE_LABELS + ("field",),
)

TPU_LEAF_WORKLOAD_COMPONENT = MetricSpec(
    name="tpu_leaf_workload_component",
    help="Raw workload-rollup accumulator component for this leaf's shard (see field label). Tier-to-tier contract consumed by the root aggregator.",
    type=GAUGE,
    label_names=WORKLOAD_LABELS + ("field",),
)

TPU_LEAF_SLICE_GROUP_INFO = MetricSpec(
    name="tpu_leaf_slice_group_info",
    help="Multi-slice membership observed by this leaf (slice -> group join key, from tpu_host_info); value is always 1. The root rebuilds multislice rollups fleet-wide from these. No family label: membership comes from tpu_host_info, which carries none (multi-slice is a TPU-fabric concept).",
    type=GAUGE,
    label_names=("slice_name", "accelerator", "multislice_group", "num_slices"),
)

TPU_LEAF_SHARD_INFO = MetricSpec(
    name="tpu_leaf_shard_info",
    help="Identity of this leaf aggregator: which consistent-hash shard it serves, its leaf id within the (optionally HA-paired) shard, and the ring it hashes with (num_shards/vnodes); value is always 1. The root refuses bodies whose shard OR ring disagrees with its own configuration — a leaf on a different ring covers a different target subset, and summing it would silently double-count the fleet rollups.",
    type=GAUGE,
    label_names=("shard", "leaf", "num_shards", "vnodes"),
)

TPU_LEAF_TARGETS = MetricSpec(
    name="tpu_leaf_targets",
    help="Node targets currently assigned to this leaf's shard by the consistent-hash map (tracks live resharding as targets join/leave).",
    type=GAUGE,
    label_names=("shard",),
)

TPU_LEAF_RESHARD_MOVES_TOTAL = MetricSpec(
    name="tpu_leaf_reshard_moves_total",
    help="Target assignment changes applied by this leaf since start (targets entering or leaving its shard on a targets-file reload). The root-side fleet view is tpu_root_reshard_moves_total.",
    type=COUNTER,
)

LEAF_SPECS: tuple[MetricSpec, ...] = (
    TPU_LEAF_SLICE_COMPONENT,
    TPU_LEAF_WORKLOAD_COMPONENT,
    TPU_LEAF_SLICE_GROUP_INFO,
    TPU_LEAF_SHARD_INFO,
    TPU_LEAF_TARGETS,
    TPU_LEAF_RESHARD_MOVES_TOTAL,
)

TPU_ROOT_LEAF_UP = MetricSpec(
    name="tpu_root_leaf_up",
    help="1 if this leaf aggregator was scraped successfully in the root's last round. An HA shard is healthy while at least one of its leaves is up; TpuRootLeafDown alerts on any leaf down.",
    type=GAUGE,
    label_names=("shard", "leaf"),
)

TPU_ROOT_LEAF_STALENESS_SECONDS = MetricSpec(
    name="tpu_root_leaf_staleness_seconds",
    help="Age of this leaf's last completed round at the root's merge time (root wall clock minus the leaf's tpu_aggregator_last_round_timestamp_seconds). The freshest leaf of each HA pair wins the per-series dedup; absent while the leaf has never answered.",
    type=GAUGE,
    label_names=("shard", "leaf"),
)

TPU_ROOT_SHARD_TARGETS = MetricSpec(
    name="tpu_root_shard_targets",
    help="Node targets served under this shard per its freshest answering leaf (tpu_leaf_targets passthrough).",
    type=GAUGE,
    label_names=("shard",),
)

TPU_ROOT_SHARD_QUARANTINED_TARGETS = MetricSpec(
    name="tpu_root_shard_quarantined_targets",
    help="Node targets of this shard whose leaf-side scrape breaker is currently open or half-open (quarantined by the shard's freshest answering leaf).",
    type=GAUGE,
    label_names=("shard",),
)

TPU_ROOT_SHARD_FAMILY_CHIPS = MetricSpec(
    name="tpu_root_shard_family_chips",
    help="Chips this shard's freshest merged view reports, per accelerator family — consistent hashing mixes node pools across shards, so the per-shard family split (status --tree's family column) is published here.",
    type=GAUGE,
    label_names=("shard", "family"),
)

TPU_ROOT_LEAF_STALE_SERVED = MetricSpec(
    name="tpu_root_leaf_stale_served",
    help="1 while the root is merging this leaf's LAST-KNOWN view because the leaf is currently unreachable (within --stale-serve-s). The fleet view stays populated through a root-leaf network partition — stale-but-labeled, never vanished; tpu_root_leaf_staleness_seconds says how stale.",
    type=GAUGE,
    label_names=("shard", "leaf"),
)

TPU_ROOT_LEAF_PARTITION_SUSPECTED = MetricSpec(
    name="tpu_root_leaf_partition_suspected",
    help="1 while this leaf is unreachable from the root but was healthy moments ago AND its HA twin still answers — the one-sided-unreachability shape of a network partition between root and leaf, as opposed to a dead leaf (whose liveness probe would be restarting it). TpuRootLeafPartitioned alerts on it.",
    type=GAUGE,
    label_names=("shard", "leaf"),
)

TPU_ROOT_DEDUP_STALE_WINS_TOTAL = MetricSpec(
    name="tpu_root_dedup_stale_wins_total",
    help="Series groups where the HA dedup had to take a STALER leaf's value because the shard's freshest answering leaf did not carry the series (e.g. a just-restarted leaf mid-warmup). Zero in steady state; a sustained rate means an HA pair disagrees about its shard.",
    type=COUNTER,
)

TPU_ROOT_RESHARD_MOVES_TOTAL = MetricSpec(
    name="tpu_root_reshard_moves_total",
    help="Target-to-shard assignment changes the root has observed across targets-file reloads since start (adds + removes + shard moves). A churn wave moves about (changed targets + targets/shards); TpuRootReshardStorm alerts on a sustained rate.",
    type=COUNTER,
)

TPU_ROOT_LAST_ROUND_TIMESTAMP_SECONDS = MetricSpec(
    name="tpu_root_last_round_timestamp_seconds",
    help="Unix timestamp of the root aggregator's most recent completed merge round.",
    type=GAUGE,
)

TPU_ROOT_ROUND_DURATION_SECONDS = MetricSpec(
    name="tpu_root_round_duration_seconds",
    help="Wall time of the root's last full round (scrape every leaf + merge + publish).",
    type=GAUGE,
)

TPU_ROOT_ROUND_HIST = HistogramSpec(
    name="tpu_root_round_seconds",
    help="Distribution of full root merge-round durations since start. The shard-demo round-time budget reads this.",
    buckets=POLL_DURATION_BUCKETS,
)

ROOT_SPECS: tuple[MetricSpec, ...] = (
    TPU_ROOT_LEAF_UP,
    TPU_ROOT_LEAF_STALENESS_SECONDS,
    TPU_ROOT_LEAF_STALE_SERVED,
    TPU_ROOT_LEAF_PARTITION_SUSPECTED,
    TPU_ROOT_SHARD_TARGETS,
    TPU_ROOT_SHARD_QUARANTINED_TARGETS,
    TPU_ROOT_SHARD_FAMILY_CHIPS,
    TPU_ROOT_DEDUP_STALE_WINS_TOTAL,
    TPU_ROOT_RESHARD_MOVES_TOTAL,
    TPU_ROOT_LAST_ROUND_TIMESTAMP_SECONDS,
    TPU_ROOT_ROUND_DURATION_SECONDS,
)

# --- Root fleet store (tpu_pod_exporter.store) -------------------------------
# Emitted only while a FleetStore is attached to the root (--store-dir) —
# conditional surface, same rule as PERSIST/EGRESS_SPECS. The store's
# health must be auditable from the exposition alone: a full/refusing disk
# shows as append failures (TpuRootStoreAppendFailing), pressure shedding
# as thinned=1 + reason="shed" drops (TpuRootStoreDiskPressure), and a
# stalled store as a growing last-append age.

TPU_ROOT_STORE_APPENDED_SAMPLES_TOTAL = MetricSpec(
    name="tpu_root_store_appended_samples_total",
    help="Samples folded into the root fleet store's downsample tiers since start (merged rollups + per-target series + recording-rule outputs, once per root merge round).",
    type=COUNTER,
)

TPU_ROOT_STORE_APPEND_FAILURES_TOTAL = MetricSpec(
    name="tpu_root_store_append_failures_total",
    help="Store WAL appends that the filesystem refused (ENOSPC, I/O errors). The in-memory tiers keep serving; durability of the failed records is lost — TpuRootStoreAppendFailing alerts on a sustained rate.",
    type=COUNTER,
)

TPU_ROOT_STORE_SERIES = MetricSpec(
    name="tpu_root_store_series",
    help="Series currently held by the root fleet store across all downsample tiers.",
    type=GAUGE,
)

TPU_ROOT_STORE_TIER_BUCKETS = MetricSpec(
    name="tpu_root_store_tier_buckets",
    help="Finalized downsample buckets currently retained per store tier (open accumulator buckets included). 0 for a tier the disk ladder's store_thin rung has shed.",
    type=GAUGE,
    label_names=("tier",),
)

TPU_ROOT_STORE_SPAN_SECONDS = MetricSpec(
    name="tpu_root_store_span_seconds",
    help="Answerable retention span of the root fleet store — how far back a query can currently reach (the widest tier's newest-minus-oldest bucket wall time). Sized in days by --store-tiers.",
    type=GAUGE,
)

TPU_ROOT_STORE_DISK_BYTES = MetricSpec(
    name="tpu_root_store_disk_bytes",
    help="On-disk bytes of the store's pending WAL records across all tier buffers under --store-dir (what the disk ladder's budget measures).",
    type=GAUGE,
)

TPU_ROOT_STORE_MEMORY_BYTES = MetricSpec(
    name="tpu_root_store_memory_bytes",
    help="In-memory bytes of the store's tier rings (preallocated per series per enabled tier) — the number the store registers with the memory-pressure ladder.",
    type=GAUGE,
)

TPU_ROOT_STORE_DROPPED_RECORDS_TOTAL = MetricSpec(
    name="tpu_root_store_dropped_records_total",
    help="Store WAL records removed WITHOUT being replayable, by reason: 'shed' (the disk ladder's store_thin rung dropped the finest tier — policy, never silent), 'retention' (records past the tier's own span — the steady-state trim), 'corrupt' (torn/scrambled records truncated at boot).",
    type=COUNTER,
    label_names=("reason",),
)

TPU_ROOT_STORE_RULES = MetricSpec(
    name="tpu_root_store_rules",
    help="Recording rules loaded from --store-rules (each precomputes one per-slice/per-workload aggregate into its own stored series every root round).",
    type=GAUGE,
)

TPU_ROOT_STORE_RULE_FAILURES_TOTAL = MetricSpec(
    name="tpu_root_store_rule_failures_total",
    help="Recording-rule evaluations that raised (bad samples, arithmetic on absent families). The failing rule is skipped for that round; the others still evaluate.",
    type=COUNTER,
)

TPU_ROOT_STORE_LAST_APPEND_TIMESTAMP_SECONDS = MetricSpec(
    name="tpu_root_store_last_append_timestamp_seconds",
    help="Unix timestamp of the store's most recent successful round append. A growing age with the root up means the store stopped ingesting — see TpuRootStoreAppendFailing.",
    type=GAUGE,
)

TPU_ROOT_STORE_THINNED = MetricSpec(
    name="tpu_root_store_thinned",
    help="1 while the disk ladder's store_thin rung holds the store's finest tier shed (coarse tiers keep answering long windows); 0 when all tiers ingest.",
    type=GAUGE,
)

STORE_SPECS: tuple[MetricSpec, ...] = (
    TPU_ROOT_STORE_APPENDED_SAMPLES_TOTAL,
    TPU_ROOT_STORE_APPEND_FAILURES_TOTAL,
    TPU_ROOT_STORE_SERIES,
    TPU_ROOT_STORE_TIER_BUCKETS,
    TPU_ROOT_STORE_SPAN_SECONDS,
    TPU_ROOT_STORE_DISK_BYTES,
    TPU_ROOT_STORE_MEMORY_BYTES,
    TPU_ROOT_STORE_DROPPED_RECORDS_TOTAL,
    TPU_ROOT_STORE_RULES,
    TPU_ROOT_STORE_RULE_FAILURES_TOTAL,
    TPU_ROOT_STORE_LAST_APPEND_TIMESTAMP_SECONDS,
    TPU_ROOT_STORE_THINNED,
)

# --- Streaming dashboard plane (tpu_pod_exporter.stream) ---------------------
# Emitted only while a StreamHub is attached to the serving tier
# (aggregator, root, or replica) — conditional surface, same rule as
# FLEET_QUERY_SPECS. The plane's health must be auditable from the
# exposition alone: subscriber churn, frames pushed by type, shed
# subscriptions by reason, and per-round push latency.

TPU_STREAM_SUBSCRIBERS = MetricSpec(
    name="tpu_stream_subscribers",
    help="Live dashboard stream subscriptions currently attached to this tier's /api/v1/stream endpoint (SSE connections; long-poll waiters are transient and not counted here).",
    type=GAUGE,
)

TPU_STREAM_QUERY_SHAPES = MetricSpec(
    name="tpu_stream_query_shapes",
    help="Distinct registered query shapes the stream hub computes per round. Each shape costs ONE delta computation per round regardless of how many subscribers share it — the fan-out inversion's whole point.",
    type=GAUGE,
)

TPU_STREAM_SUBSCRIBES_TOTAL = MetricSpec(
    name="tpu_stream_subscribes_total",
    help="Stream subscriptions accepted since start, by transport (sse | longpoll; long-poll counts one per held request).",
    type=COUNTER,
    label_names=("transport",),
)

TPU_STREAM_REJECTS_TOTAL = MetricSpec(
    name="tpu_stream_rejects_total",
    help="Stream subscriptions refused since start, by cause: 'cap' (subscriber cap reached — the admission half of the pressure story; clients get a 429 and should retry against a replica).",
    type=COUNTER,
    label_names=("cause",),
)

TPU_STREAM_FRAMES_TOTAL = MetricSpec(
    name="tpu_stream_frames_total",
    help="Frames pushed to subscribers since start, by type: snapshot (registration answer), delta (changed series only), full_sync (periodic anti-rot full answer), heartbeat.",
    type=COUNTER,
    label_names=("type",),
)

TPU_STREAM_FRAME_BYTES_TOTAL = MetricSpec(
    name="tpu_stream_frame_bytes_total",
    help="Wire bytes of frames pushed to subscribers since start (serialized once per shape per round, counted once per subscriber write).",
    type=COUNTER,
)

TPU_STREAM_SHEDS_TOTAL = MetricSpec(
    name="tpu_stream_sheds_total",
    help="Live subscriptions closed by the server since start, by reason: 'pressure' (the memory ladder's stream_shed rung dropped the oldest half), 'slow' (a subscriber's pending write buffer exceeded the cap), 'cap' (oldest shed to admit pressure-exempt work).",
    type=COUNTER,
    label_names=("reason",),
)

TPU_STREAM_PUSH_SECONDS = HistogramSpec(
    name="tpu_stream_push_seconds",
    help="Per-round push latency per query shape: delta computation plus handing every subscriber's frame to the event loop (socket flush is asynchronous and bounded by the write-progress deadline). The dashboard-storm drill's p99 budget reads this.",
    buckets=POLL_DURATION_BUCKETS,
)

STREAM_SPECS: tuple[MetricSpec, ...] = (
    TPU_STREAM_SUBSCRIBERS,
    TPU_STREAM_QUERY_SHAPES,
    TPU_STREAM_SUBSCRIBES_TOTAL,
    TPU_STREAM_REJECTS_TOTAL,
    TPU_STREAM_FRAMES_TOTAL,
    TPU_STREAM_FRAME_BYTES_TOTAL,
    TPU_STREAM_SHEDS_TOTAL,
)

# --- Stateless root read replicas (tpu-pod-exporter-shard --role replica) ----
# A replica scrapes the leaves read-only exactly like the root and serves
# /metrics + /api/v1 + /api/v1/stream, but owns no egress, no persistence
# and no store writes — viewer fan-out scales by adding replicas while
# exactly one root keeps the write-side duties.

TPU_REPLICA_INFO = MetricSpec(
    name="tpu_replica_info",
    help="Identity of this stateless read replica (value always 1). Present only on --role replica tiers; its absence from a /metrics body is how you know you are talking to the real root.",
    type=GAUGE,
    label_names=("replica",),
)

TPU_REPLICA_STORE_PROXIED_TOTAL = MetricSpec(
    name="tpu_replica_store_proxied_total",
    help="?source= store queries this replica forwarded to the root's store (--root-url), by result (ok | error). Replicas own no store; without --root-url these queries 400 honestly instead.",
    type=COUNTER,
    label_names=("result",),
)

REPLICA_SPECS: tuple[MetricSpec, ...] = (
    TPU_REPLICA_INFO,
    TPU_REPLICA_STORE_PROXIED_TOTAL,
)

# --- Native alerting plane (tpu_pod_exporter.alerting) -----------------------
# Emitted only while an AlertEvaluator is attached to the root
# (--alert-rules) — conditional surface, same rule as STORE_SPECS. The
# plane's health must be auditable from the exposition alone: what is
# firing/pending right now, how states have been transitioning, whether
# partition suppression is holding false positives down, and whether the
# webhook notifier is delivering or backlogging.

TPU_ROOT_ALERTS_FIRING = MetricSpec(
    name="tpu_root_alerts_firing",
    help="Alert instances currently in the firing (or keep-firing) state across every loaded alert rule. The same instants land in the fleet store as ALERTS-shaped series for post-incident forensics.",
    type=GAUGE,
)

TPU_ROOT_ALERTS_PENDING = MetricSpec(
    name="tpu_root_alerts_pending",
    help="Alert instances currently pending: their expression is true but has not yet held for the rule's `for` duration.",
    type=GAUGE,
)

TPU_ROOT_ALERT_TRANSITIONS_TOTAL = MetricSpec(
    name="tpu_root_alert_transitions_total",
    help="Alert state-machine transitions since start, by alert name and destination state (to: pending | firing | resolved). Flap damping (`keep_firing`) absorbs brief recoveries, so a high rate here means genuinely flapping conditions.",
    type=COUNTER,
    label_names=("alert", "to"),
)

TPU_ROOT_ALERT_SUPPRESSED_TOTAL = MetricSpec(
    name="tpu_root_alert_suppressed_total",
    help="Alert-instance evaluations suppressed since start, by alert name: the rule's suppress() expression matched (e.g. the root's stale-serve partition suspicion covering the instance), so a would-be pending/firing state was held down as a presumed false positive.",
    type=COUNTER,
    label_names=("alert",),
)

TPU_ROOT_ALERT_RULES = MetricSpec(
    name="tpu_root_alert_rules",
    help="Alert rules loaded from --alert-rules and evaluated each root merge round.",
    type=GAUGE,
)

TPU_ROOT_ALERT_EVAL_FAILURES_TOTAL = MetricSpec(
    name="tpu_root_alert_eval_failures_total",
    help="Alert-rule evaluations that raised (absent families feeding arithmetic, bad samples). The failing rule is skipped for that round, the others still evaluate; a sustained rate flips /readyz's alerting detail to degraded.",
    type=COUNTER,
)

TPU_ROOT_ALERT_NOTIFICATIONS_SENT_TOTAL = MetricSpec(
    name="tpu_root_alert_notifications_sent_total",
    help="Webhook notifications acknowledged by the receiver since start (2xx — the exactly-once cursor advanced past them; they are never re-sent, even across a root restart).",
    type=COUNTER,
)

TPU_ROOT_ALERT_NOTIFICATIONS_FAILED_TOTAL = MetricSpec(
    name="tpu_root_alert_notifications_failed_total",
    help="Webhook notification attempts that failed since start (timeout, connection error, 5xx, 429). Failed notifications stay in the durable backlog and retry behind the notifier breaker.",
    type=COUNTER,
)

TPU_ROOT_ALERT_NOTIFIER_BACKLOG_BYTES = MetricSpec(
    name="tpu_root_alert_notifier_backlog_bytes",
    help="On-disk bytes of alert notifications buffered under --alert-dir awaiting webhook delivery (grows through a receiver outage, drains exactly-once on recovery).",
    type=GAUGE,
)

TPU_ROOT_ALERT_NOTIFIER_BACKLOG_AGE_SECONDS = MetricSpec(
    name="tpu_root_alert_notifier_backlog_age_seconds",
    help="Age of the oldest alert notification still awaiting webhook delivery. 0 with an empty backlog; a growing value means the webhook receiver has been down that long.",
    type=GAUGE,
)

TPU_ROOT_ALERT_NOTIFIER_BREAKER_STATE = MetricSpec(
    name="tpu_root_alert_notifier_breaker_state",
    help="Webhook notifier circuit-breaker state (0=closed 1=open 2=half_open). Open means notifications are WAL-buffered, not flowing; /readyz reports alerting degraded after repeated reopens but stays 200 — a down webhook must not pull the root from scrape rotation.",
    type=GAUGE,
)

ALERT_SPECS: tuple[MetricSpec, ...] = (
    TPU_ROOT_ALERTS_FIRING,
    TPU_ROOT_ALERTS_PENDING,
    TPU_ROOT_ALERT_TRANSITIONS_TOTAL,
    TPU_ROOT_ALERT_SUPPRESSED_TOTAL,
    TPU_ROOT_ALERT_RULES,
    TPU_ROOT_ALERT_EVAL_FAILURES_TOTAL,
    TPU_ROOT_ALERT_NOTIFICATIONS_SENT_TOTAL,
    TPU_ROOT_ALERT_NOTIFICATIONS_FAILED_TOTAL,
    TPU_ROOT_ALERT_NOTIFIER_BACKLOG_BYTES,
    TPU_ROOT_ALERT_NOTIFIER_BACKLOG_AGE_SECONDS,
    TPU_ROOT_ALERT_NOTIFIER_BREAKER_STATE,
)

# The rollup surface the aggregator's remote-write egress ships
# (tpu_pod_exporter.egress): the slice/multislice/workload rollups plus
# per-target up — the "what is the fleet doing" set a central TSDB wants,
# not the aggregator's own plumbing counters.
AGGREGATE_EGRESS_SPECS: tuple[MetricSpec, ...] = (
    TPU_FLEET_FAMILY_HOSTS_REPORTING,
    TPU_FLEET_FAMILY_CHIP_COUNT,
    TPU_FLEET_FAMILY_HBM_USED_BYTES,
    TPU_FLEET_FAMILY_HBM_TOTAL_BYTES,
    TPU_SLICE_HOSTS_REPORTING,
    TPU_SLICE_CHIP_COUNT,
    TPU_SLICE_HBM_USED_BYTES,
    TPU_SLICE_HBM_TOTAL_BYTES,
    TPU_SLICE_HBM_USED_PERCENT,
    TPU_SLICE_DUTY_CYCLE_AVG_PERCENT,
    TPU_SLICE_ICI_BYTES_PER_SECOND,
    TPU_SLICE_DCN_BYTES_PER_SECOND,
    TPU_MULTISLICE_SLICES_REPORTING,
    TPU_MULTISLICE_EXPECTED_SLICES,
    TPU_MULTISLICE_HOSTS_REPORTING,
    TPU_MULTISLICE_CHIP_COUNT,
    TPU_MULTISLICE_HBM_USED_BYTES,
    TPU_MULTISLICE_ICI_BYTES_PER_SECOND,
    TPU_MULTISLICE_DCN_BYTES_PER_SECOND,
    TPU_WORKLOAD_CHIP_COUNT,
    TPU_WORKLOAD_HBM_USED_BYTES,
    TPU_WORKLOAD_HOSTS,
    TPU_AGG_TARGET_UP,
)

AGGREGATE_SPECS: tuple[MetricSpec, ...] = (
    TPU_SLICE_HOSTS_REPORTING,
    TPU_SLICE_CHIP_COUNT,
    TPU_SLICE_HBM_USED_BYTES,
    TPU_SLICE_HBM_TOTAL_BYTES,
    TPU_SLICE_HBM_USED_PERCENT,
    TPU_SLICE_DUTY_CYCLE_AVG_PERCENT,
    TPU_SLICE_ICI_BYTES_PER_SECOND,
    TPU_SLICE_DCN_BYTES_PER_SECOND,
    TPU_MULTISLICE_SLICES_REPORTING,
    TPU_MULTISLICE_EXPECTED_SLICES,
    TPU_MULTISLICE_HOSTS_REPORTING,
    TPU_MULTISLICE_CHIP_COUNT,
    TPU_MULTISLICE_HBM_USED_BYTES,
    TPU_MULTISLICE_ICI_BYTES_PER_SECOND,
    TPU_MULTISLICE_DCN_BYTES_PER_SECOND,
    TPU_FLEET_FAMILY_HOSTS_REPORTING,
    TPU_FLEET_FAMILY_CHIP_COUNT,
    TPU_FLEET_FAMILY_HBM_USED_BYTES,
    TPU_FLEET_FAMILY_HBM_TOTAL_BYTES,
    TPU_WORKLOAD_CHIP_COUNT,
    TPU_WORKLOAD_HBM_USED_BYTES,
    TPU_WORKLOAD_HOSTS,
    TPU_AGG_TARGET_UP,
    TPU_AGG_TARGET_BREAKER_STATE,
    TPU_AGG_SCRAPE_DURATION_SECONDS,
    TPU_AGG_SCRAPE_ERRORS_TOTAL,
    TPU_AGG_HISTORY_FALLBACKS_TOTAL,
    TPU_AGG_LAST_ROUND_TIMESTAMP_SECONDS,
    TPU_AGG_ROUND_DURATION_SECONDS,
    TPU_AGG_POLL_OVERRUNS_TOTAL,
    TPU_AGG_CPU_SECONDS_TOTAL,
    TPU_AGG_RSS_BYTES,
)


def hbm_used_percent(used_bytes: float, total_bytes: float) -> float:
    """Bytes → percent-of-device-total (analog of ``main.go:149-150``).

    Returns 0.0 when capacity is unknown/zero instead of dividing by zero.
    """
    if total_bytes <= 0:
        return 0.0
    return (float(used_bytes) / float(total_bytes)) * 100.0
