"""Prometheus text-exposition parser (format 0.0.4).

The inverse of the renderer in :mod:`tpu_pod_exporter.metrics.registry`,
used by the slice aggregator to consume per-host exporters' ``/metrics``
bodies. Kept dependency-free and strict about the things that matter for
aggregation correctness (label-value escape sequences, NaN/Inf, optional
timestamps) while tolerating unknown families — an aggregator must not
break when a newer exporter adds metrics.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple


class ParsedSample(NamedTuple):
    name: str
    labels: dict[str, str]
    value: float


class ParseError(ValueError):
    """A metric line was structurally malformed."""


def _parse_label_block(block: str, line: str) -> dict[str, str]:
    """``name="value",…`` (no surrounding braces) → dict, honoring the
    exposition escapes inside values: ``\\\\``, ``\\"``, ``\\n``."""
    labels: dict[str, str] = {}
    i, n = 0, len(block)
    while i < n:
        eq = block.find("=", i)
        if eq < 0:
            raise ParseError(f"label without '=': {line!r}")
        name = block[i:eq].strip()
        if not name:
            raise ParseError(f"empty label name: {line!r}")
        if eq + 1 >= n or block[eq + 1] != '"':
            raise ParseError(f"unquoted label value: {line!r}")
        j = eq + 2
        out: list[str] = []
        while True:
            if j >= n:
                raise ParseError(f"unterminated label value: {line!r}")
            ch = block[j]
            if ch == "\\":
                if j + 1 >= n:
                    raise ParseError(f"dangling escape: {line!r}")
                nxt = block[j + 1]
                out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, "\\" + nxt))
                j += 2
            elif ch == '"':
                break
            else:
                out.append(ch)
                j += 1
        labels[name] = "".join(out)
        j += 1  # past closing quote
        while j < n and block[j] in ", ":
            j += 1
        i = j
    return labels


def parse_exposition(text: str) -> Iterator[ParsedSample]:
    """Yield every sample in an exposition body. ``# HELP``/``# TYPE``/other
    comments are skipped; trailing timestamps are accepted and dropped.

    Lines split on ``\\n`` ONLY — ``str.splitlines()`` also breaks on
    \\v/\\f/U+0085/U+2028…, all of which may legally appear *unescaped*
    inside a label value (the exposition format escapes only ``\\n``,
    ``\\"`` and ``\\\\``)."""
    for raw in text.split("\n"):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line[-1] == "{":
            raise ParseError(f"truncated line: {line!r}")
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ParseError(f"unbalanced braces: {line!r}")
            name = line[:brace].strip()
            labels = _parse_label_block(line[brace + 1 : close], line)
            rest = line[close + 1 :].strip()
        else:
            parts = line.split(None, 1)
            if len(parts) < 2:
                raise ParseError(f"missing value: {line!r}")
            name, rest = parts[0], parts[1]
            labels = {}
        if not name:
            raise ParseError(f"missing metric name: {line!r}")
        value_str = rest.split()[0] if rest else ""
        if not value_str:
            raise ParseError(f"missing value: {line!r}")
        try:
            value = float(value_str)
        except ValueError as e:
            raise ParseError(f"bad value {value_str!r}: {line!r}") from e
        yield ParsedSample(name, labels, value)


def parse_families(text: str) -> dict[str, list[ParsedSample]]:
    """Samples grouped by family name (counter samples keep their ``_total``
    suffix — this is the text format's sample name, not the OpenMetrics
    family abstraction)."""
    out: dict[str, list[ParsedSample]] = {}
    for s in parse_exposition(text):
        out.setdefault(s.name, []).append(s)
    return out
