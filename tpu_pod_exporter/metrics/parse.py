"""Prometheus text-exposition parser (format 0.0.4).

The inverse of the renderer in :mod:`tpu_pod_exporter.metrics.registry`,
used by the slice aggregator to consume per-host exporters' ``/metrics``
bodies. Kept dependency-free and strict about the things that matter for
aggregation correctness (label-value escape sequences, NaN/Inf, optional
timestamps) while tolerating unknown families — an aggregator must not
break when a newer exporter adds metrics.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Iterator, NamedTuple

log = logging.getLogger("tpu_pod_exporter.metrics.parse")

# Oversize-body warnings are rate-limited globally (not once-per-layout):
# a body flapping across the cache cap re-arms the per-layout flag every
# other round, and at a 1 s poll interval an unthrottled warning is ~1800
# lines/hour (code-review r5). One line per 60 s across all targets is
# plenty — debug_vars' layout_oversize carries the per-target state.
_rlog = None


def _warn_oversize(n_lines: int, cap: int) -> None:
    global _rlog
    if _rlog is None:
        from tpu_pod_exporter.utils import RateLimitedLogger

        _rlog = RateLimitedLogger(log, min_interval_s=60.0)
    _rlog.warning(
        "layout-oversize",
        "exposition body has %d lines (> layout cache cap %d); "
        "parsing uncached every round for this target",
        n_lines, cap,
    )


class ParsedSample(NamedTuple):
    name: str
    labels: dict[str, str]
    value: float


class ParseError(ValueError):
    """A metric line was structurally malformed."""


# One label pair: name="value" with the exposition escapes (\\ \" \n)
# allowed inside the value, followed by any run of comma/whitespace
# separators ([,\s]* — matching the historical parser's leniency: space-
# separated pairs, doubled commas, and trailing separators all parse).
# The value uses the *unrolled* form [^"\\]*(?:\\.[^"\\]*)* — the naive
# (?:[^"\\]+|\\.)* has a nested-quantifier ambiguity that backtracks
# exponentially on an unterminated value (a ~30-char bad line would hang
# the aggregator instead of raising ParseError). Validation is positional:
# every match must start exactly where the previous one ended.
_PAIR_RE = re.compile(r'\s*([^=,\s{}]+)\s*=\s*"([^"\\]*(?:\\.[^"\\]*)*)"[,\s]*')
# Key charset for the fast path — must stay equivalent to _PAIR_RE's key
# class (plus the no-quote rule the regex applies via the value grammar).
_FAST_KEY_RE = re.compile(r'[^=,\s{}"]+')
_GOOD_KEYS: dict[str, bool] = {}
_UNESCAPE_RE = re.compile(r"\\(.)")
_ESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape(value: str) -> str:
    return _UNESCAPE_RE.sub(
        lambda m: _ESCAPE_MAP.get(m.group(1), "\\" + m.group(1)), value
    )


def _parse_block_fast(block: str) -> dict[str, str] | None:
    """Non-regex parse of the overwhelmingly common strict shape:
    ``k="v",k2="v2"`` with NO backslash anywhere in the block.

    Soundness of the ``",`` split: the exposition format requires ``"``
    inside a value to be escaped as ``\\"``, so an escape-free block cannot
    contain a quote inside any key or value — every quote-comma sequence
    really ends a pair. Any residual quote after splitting (embedded
    ``="`` in a value, stray separators, spaces) rejects to the lenient
    regex parser, so the accepted grammar is unchanged. ~6x faster than
    the regex walk; at aggregator scale the block working set can exceed
    the cache budget and parses run uncached, where this is the
    difference between a sub-second and a multi-second 64-host round.
    """
    if "\\" in block or not block.endswith('"'):
        return None
    labels: dict[str, str] = {}
    good_keys = _GOOD_KEYS
    memo = _memo_str
    for part in block[:-1].split('",'):
        k, sep, v = part.partition('="')
        if not sep or '"' in v:
            return None
        if k not in good_keys:
            # Same key charset the regex enforces (no =,{}/whitespace/");
            # memoized because real bodies reuse a handful of label names.
            if not _FAST_KEY_RE.fullmatch(k):
                return None
            if len(good_keys) < 4096:
                good_keys[k] = True
        labels[memo(k)] = memo(v)
    return labels


def _parse_block_uncached(block: str, line: str) -> dict[str, str]:
    labels: dict[str, str] = {}
    pos = 0
    memo = _memo_str
    for m in _PAIR_RE.finditer(block):
        if m.start() != pos:
            raise ParseError(f"malformed label block: {line!r}")
        pos = m.end()
        value = m.group(2)
        labels[memo(m.group(1))] = memo(
            _unescape(value) if "\\" in value else value
        )
    if pos != len(block):
        raise ParseError(f"malformed label block: {line!r}")
    return labels


# Label-string memo: at slice scale the same short strings recur across
# thousands of distinct label blocks — chip_id="7" appears once per
# family per target, pod/namespace/host values repeat across every series
# of a host — but each block parse sliced fresh copies, and the layout
# caches then pinned ~108 MiB of duplicate strings at the 64x256 stress
# shape. Deduplicating through one table cuts aggregator RSS ~19% and is
# slightly FASTER (fewer live objects). Deliberately NOT sys.intern: the
# CPython intern table holds its strings forever, which under pod-name
# churn is a slow leak in a long-running sidecar; this table is bounded
# and wholesale-cleared (same policy as the block cache below), so the
# worst case is one round of re-warming. Oversized strings skip the memo
# — a degenerate label value must not occupy the budget.
_STR_MEMO: dict[str, str] = {}
_STR_MEMO_MAX = 65536
_STR_MEMO_MAX_LEN = 256


def _memo_str(s: str) -> str:
    # Deliberately unlocked, unlike the block cache's clear/accounting
    # path: this runs once per label string on the hot parse path, and
    # every individual dict op here is atomic under the GIL. A concurrent
    # miss race can only (a) overshoot the bound by the thread count for
    # one round or (b) clear() away another thread's just-inserted entry —
    # both cost one lost dedup, never a wrong parse result.
    r = _STR_MEMO.get(s)
    if r is not None:
        return r
    if len(s) <= _STR_MEMO_MAX_LEN:
        if len(_STR_MEMO) >= _STR_MEMO_MAX:
            _STR_MEMO.clear()
        _STR_MEMO[s] = s
    return s


# Parsed-block memo: exposition bodies repeat their label blocks verbatim
# every scrape (only sample *values* change), so in steady state label
# parsing collapses to one dict lookup + shallow copy per line. This is
# what keeps the aggregator's round cost flat at slice scale — the
# replaced per-character loop was ~85% of a 64-host round. Bounded by a
# byte budget (keys dominate memory) with wholesale clear — series-churn
# workloads just re-warm in one round — and a per-entry length guard so
# adversarial/degenerate blocks can't occupy the budget.
_BLOCK_CACHE: dict[str, dict[str, str]] = {}
_BLOCK_CACHE_MAX_BYTES = 32 << 20  # approximate *resident* bytes
_BLOCK_CACHE_MAX_ENTRY = 1 << 10
_block_cache_bytes = 0
# The cache is module-global shared mutable state; parsers can run from
# multiple threads (aggregator publish thread today, potentially a scrape
# pool tomorrow), so clear()/byte-accounting mutations are guarded. The
# lock is only taken on cache MISS — the hit path (steady state) stays a
# lock-free dict read, safe under the GIL because entries are immutable
# once inserted.
_block_cache_lock = threading.Lock()


def _entry_cost(block: str) -> int:
    """Approximate resident cost of one cache entry. The parsed value dict
    dominates (measured ~8x the key length: dict header + per-label key and
    value string objects), so counting key characters alone would let the
    'budget' admit ~8x its nominal size."""
    return 200 + 8 * len(block)


def _parse_label_block(block: str, line: str) -> dict[str, str]:
    """``name="value",…`` (no surrounding braces) → dict, honoring the
    exposition escapes inside values: ``\\\\``, ``\\"``, ``\\n``."""
    global _block_cache_bytes
    cached = _BLOCK_CACHE.get(block)
    if cached is None:
        cached = _parse_block_fast(block)
        if cached is None:
            cached = _parse_block_uncached(block, line)
        if len(block) <= _BLOCK_CACHE_MAX_ENTRY:
            with _block_cache_lock:
                if _block_cache_bytes >= _BLOCK_CACHE_MAX_BYTES:
                    _BLOCK_CACHE.clear()
                    _block_cache_bytes = 0
                if block not in _BLOCK_CACHE:  # a racing miss already paid
                    _BLOCK_CACHE[block] = cached
                    _block_cache_bytes += _entry_cost(block)
    # SHARED return: the same dict object serves every line with this
    # block (across targets, too). The layout path's contract already
    # declares labels shared-and-frozen, and the per-line dict(cached)
    # copies were ~45 MiB at the 64x256 stress shape; the one public
    # copy-owning API (parse_exposition / ParsedSample) copies at its own
    # boundary instead.
    return cached


def _parse_line(line: str, names: "set[str] | frozenset[str] | None") -> tuple:
    """One stripped, non-empty, non-comment line → layout entry tuple:
    ``(1, prefix)`` when ``names`` filters the line out, else
    ``(2, prefix, name, labels, value)``. Raises ParseError. The SINGLE
    definition of the line grammar — both :func:`parse_exposition` and
    :func:`parse_exposition_layout`'s slow path call it, so the two
    parsers cannot drift apart (code-review r5). ``labels`` is SHARED
    with the block cache (and with every other line using the same
    block): treat as frozen; copy at any boundary that hands ownership
    out."""
    if line[-1] == "{":
        raise ParseError(f"truncated line: {line!r}")
    brace = line.find("{")
    if brace >= 0:
        close = line.rfind("}")
        if close < brace:
            raise ParseError(f"unbalanced braces: {line!r}")
        name = line[:brace].strip()
        prefix = line[: close + 1]
        if names is not None and name not in names:
            return (1, prefix)
        # Family names repeat on nearly every line of a body; memoized so
        # 290k cached entries at slice scale share a handful of strings.
        # After the filter: a kind-1 entry drops the name, and dead
        # memo slots would hasten the wholesale clear (code-review r5).
        name = _memo_str(name)
        labels = _parse_label_block(line[brace + 1 : close], line)
        rest = line[close + 1 :].strip()
    else:
        parts = line.split(None, 1)
        if len(parts) < 2:
            raise ParseError(f"missing value: {line!r}")
        name, rest = parts[0], parts[1]
        prefix = name
        if names is not None and name not in names:
            return (1, prefix)
        name = _memo_str(name)  # post-filter, same rationale as above
        labels = {}
    if not name:
        raise ParseError(f"missing metric name: {line!r}")
    value_str = rest.split()[0] if rest else ""
    if not value_str:
        raise ParseError(f"missing value: {line!r}")
    try:
        value = float(value_str)
    except ValueError as e:
        raise ParseError(f"bad value {value_str!r}: {line!r}") from e
    return (2, prefix, name, labels, value)


def parse_exposition(
    text: str, names: "frozenset[str] | set[str] | None" = None
) -> Iterator[ParsedSample]:
    """Yield every sample in an exposition body. ``# HELP``/``# TYPE``/other
    comments are skipped; trailing timestamps are accepted and dropped.

    ``names``: optional sample-name filter. Lines whose name is not in the
    set are skipped BEFORE label/value parsing — a consumer that folds a
    handful of families out of a 4k-line body (the slice aggregator reads
    6) skips ~half its parse cost. Malformed *skipped* lines are therefore
    not diagnosed; the aggregator trades that for round latency.

    Lines split on ``\\n`` ONLY — ``str.splitlines()`` also breaks on
    \\v/\\f/U+0085/U+2028…, all of which may legally appear *unescaped*
    inside a label value (the exposition format escapes only ``\\n``,
    ``\\"`` and ``\\\\``)."""
    for raw in text.split("\n"):
        line = raw.strip()
        if not line or line[0] == "#":
            continue
        ent = _parse_line(line, names)
        if ent[0] == 2:
            # Copy here, at the public boundary: ParsedSample callers own
            # their labels dict; _parse_line's is shared with the block
            # cache and with other lines using the same block.
            yield ParsedSample(ent[2], dict(ent[3]), ent[4])


class LayoutCache:
    """One scrape target's parsed line structure, reused across rounds.

    Exposition bodies are layout-stable between churn events: the same
    lines in the same order, only sample VALUES changing (the insight the
    exporter's PrefixCache exploits on the render side — VERDICT r4 #6
    applies it to the parse side). :func:`parse_exposition_layout` compares
    each line's prefix to the previous round's and, on match, re-parses
    only the value — no label-block parsing, no global cache contention,
    no per-round dict building. Memory: holds roughly one body's worth of
    strings + label dicts per target.

    ``entries`` is a list of per-line tuples:
      ``(0, line)``                 verbatim line (comment/blank) — skip
      ``(1, prefix)``               name-filtered sample line — skip
      ``(2, prefix, name, labels)`` consumed sample — labels dict SHARED

    The ``native_*`` slots cache the ctypes views libtpumon's whole-body
    fast path needs (see ``metrics/native.py::parse_layout``); they are
    rebuilt whenever ``entries`` is swapped (``native_built_for`` tracks
    the list identity) and the ``samples_template`` gives the (name,
    labels) pair for each kind-2 entry in order.
    """

    __slots__ = (
        "entries", "max_entries", "oversize_logged", "native_built_for",
        "native_keybytes", "native_keys", "native_klens", "native_kinds",
        "native_out", "samples_template",
    )

    def __init__(self, max_entries: int = 32768) -> None:
        self.entries: list[tuple] = []
        # Memory ceiling: a cached layout holds roughly the body's strings
        # plus per-line tuples (~60 KB per 1k lines measured), so an
        # unbounded cache lets one pathological target grow a sidecar
        # without limit. Bodies beyond the cap simply parse the slow path
        # every round (correct, just uncached). 32k lines ≈ 7× a
        # 256-chip exporter body.
        self.max_entries = max_entries
        self.oversize_logged = False
        self.samples_template: list[tuple] | None = None
        self.drop_native()

    def drop_native(self) -> None:
        """Release the native fast-path buffers + template.

        The single place that knows the full ``native_*`` field list (the
        builder in ``metrics/native.py::parse_layout`` is the other); an
        oversize transition or any future invalidation site calls this so
        a forgotten field can't silently retain a body's worth of encoded
        prefixes."""
        self.native_built_for = None
        self.native_keybytes = None
        self.native_keys = None
        self.native_klens = None
        self.native_kinds = None
        self.native_out = None
        self.samples_template = None


def _native_parse_layout(layout: "LayoutCache", text: str) -> "list[float] | None":
    try:
        from tpu_pod_exporter.metrics import native
    except ImportError:  # partial deployment: the parser must not die
        return None
    return native.parse_layout(layout, text)


def parse_exposition_layout(
    text: str,
    names: "frozenset[str] | set[str]",
    layout: LayoutCache,
) -> "list[tuple[str, dict[str, str], float]]":
    """Like ``list(parse_exposition(text, names))`` but layout-cached via
    ``layout`` (see :class:`LayoutCache`), returning plain
    ``(name, labels, value)`` tuples (ParsedSample construction is
    measurable at 164k samples/round) whose ``labels`` dicts are SHARED
    with the cache: callers must treat them as frozen. Any line that
    diverges from the cached layout (churn, a new exporter version, the
    first round) falls back to the full parser for the rest of the body;
    the rebuilt layout serves the next round. On ParseError the cache is
    left untouched (the next round re-parses)."""
    # Oversize pre-check: the rebuilt entry list would hold exactly one
    # tuple per line, so the line count alone decides cacheability. Bodies
    # over the cap parse a bare loop with NO layout maintenance — the old
    # path built the full new_entries list every round only to throw it
    # away at the cap check (code-review r5). A body that later shrinks
    # under the cap re-enters the cache on its next round.
    if text.count("\n") + 1 > layout.max_entries:
        # Parse FIRST (delegating to parse_exposition keeps the line
        # grammar in one place; ParsedSample is a tuple subclass, and a
        # micro-optimized plain tuple matters least on this once-per-round
        # fallback), touch the cache only on success — a ParseError here
        # must leave the warm layout intact per this function's contract.
        out = list(parse_exposition(text, names))
        if not layout.oversize_logged:
            layout.oversize_logged = True
            _warn_oversize(text.count("\n") + 1, layout.max_entries)
        if layout.entries:
            # Transition small->oversize: drop the cached layout AND the
            # native ctypes buffers/template — they hold a body's worth
            # of encoded prefixes, exactly what the cap bounds.
            layout.entries = []
            layout.drop_native()
        return out
    entries = layout.entries
    if entries:
        # Whole-body native fast path: on a perfect byte-level match of
        # every line (values aside), C returns just the values and the
        # cached (name, labels) template supplies the rest — no per-line
        # Python at all. Any divergence returns None and this function's
        # own per-line hit path (below) takes over.
        values = _native_parse_layout(layout, text)
        if values is not None:
            tmpl = layout.samples_template
            return [
                (name, labels, v)
                for (name, labels), v in zip(tmpl, values)
            ]
    n_cached = len(entries)
    # Lazily materialized: a fully-aligned round (the steady state) never
    # builds a new list at all — entries[:kept] stays the layout.
    new_entries: list[tuple] | None = None
    out: list[tuple[str, dict[str, str], float]] = []
    kept = 0  # entries[:kept] verified against this body so far
    aligned = True
    for raw in text.split("\n"):
        line = raw.strip()
        if aligned and kept < n_cached:
            ent = entries[kept]
            kind = ent[0]
            if kind == 0:
                if line == ent[1]:
                    kept += 1
                    continue
            else:
                pfx = ent[1]
                lp = len(pfx)
                # startswith + a boundary check: the char after the prefix
                # must be whitespace, so name "m" can never claim "m2 1"
                # and a labeled prefix only matches its exact series.
                if (
                    len(line) > lp
                    and (line[lp] == " " or line[lp] == "\t")
                    and line.startswith(pfx)
                ):
                    if kind == 1:
                        kept += 1
                        continue
                    tail = line[lp + 1 :]
                    value = None
                    try:
                        value = float(tail)  # common case: no timestamp
                    except ValueError:
                        # A brace in the tail changes the line's brace
                        # grammar entirely (the reference parser's rfind
                        # would pick a different block) — never a hit.
                        if "{" not in tail and "}" not in tail:
                            vs = tail.split()
                            if vs:
                                try:
                                    value = float(vs[0])  # timestamp dropped
                                except ValueError:
                                    value = None  # slow path diagnoses
                    if value is not None:
                        out.append((ent[2], ent[3], value))
                        kept += 1
                        continue
            # Mismatch: the body's shape changed at this line. Positional
            # alignment is gone for good (an inserted/deleted line shifts
            # everything), so slow-parse the rest of the body this round.
            aligned = False

        # ---- slow path: full parse of this line + entry rebuild --------
        if new_entries is None:
            new_entries = list(entries[:kept])
        if not line or line[0] == "#":
            new_entries.append((0, line))
            continue
        ent = _parse_line(line, names)
        if ent[0] == 2:
            out.append((ent[2], ent[3], ent[4]))
            new_entries.append((2, ent[1], ent[2], ent[3]))
        else:
            new_entries.append(ent)
    if new_entries is not None:
        # The oversize pre-check above guarantees len(new_entries) — one
        # tuple per line — is within layout.max_entries here.
        layout.entries = new_entries
    elif kept != n_cached:
        layout.entries = entries[:kept]  # body shrank, still aligned
    if layout.oversize_logged:
        # Body shrank back under the cap AND this round parsed cleanly
        # (a ParseError above must leave all cache state untouched, flag
        # included): clear the state here, at the success point, so
        # debug_vars' layout_oversize reports the CURRENT condition and a
        # later genuine re-oversize warns again (code-review r5 — a
        # sticky flag sent operators chasing a slow-path problem that no
        # longer existed; an early clear misreported a torn under-cap
        # scrape as recovery).
        layout.oversize_logged = False
    return out


def parse_families(text: str) -> dict[str, list[ParsedSample]]:
    """Samples grouped by family name (counter samples keep their ``_total``
    suffix — this is the text format's sample name, not the OpenMetrics
    family abstraction)."""
    out: dict[str, list[ParsedSample]] = {}
    for s in parse_exposition(text):
        out.setdefault(s.name, []).append(s)
    return out
